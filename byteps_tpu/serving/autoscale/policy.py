"""Hysteresis-banded, target-tracking scale policy (docs/serving.md
"Elastic capacity & SLO classes").

``ScalePolicy`` is the pure half of the autoscaling control loop: it
never reads the wall clock, never touches the router, and keeps only
the cooldown stamps of its own past decisions.  ``decide(signals,
current, now)`` is therefore a deterministic state machine over an
injected clock — the tier-1 tests drive it on scripted signal traces
(hysteresis band, per-direction cooldowns, min/max clamps, dry-run)
with zero sleeps, while ``AutoscaleController`` (actuator.py) drives
the same object on real samples.

The policy is target-tracking in the classic sense: the scale-up
target is ``ceil(current * load / up_threshold)`` — "how many replicas
would bring the observed load back under the threshold" — so a 4x
spike jumps capacity in one decision instead of one replica per
interval.  Scale-down is deliberately conservative: one replica at a
time, only below ``down_threshold``, and only outside BOTH cooldowns
(a fresh scale-up must not be immediately unwound by a transient dip).
The band between the two thresholds is the hysteresis region where
the tier holds steady.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

__all__ = ["ScaleDecision", "ScalePolicy"]


@dataclass(frozen=True)
class ScaleDecision:
    """One typed output of ``ScalePolicy.decide``.

    ``action`` is ``"up"`` / ``"down"`` / ``"hold"``; ``target`` is the
    desired replica count after the decision (equal to ``current`` for
    holds); ``reason`` is a short human-readable explanation;
    ``dry_run`` marks decisions the actuator must log but not act on.
    """

    action: str
    target: int
    reason: str
    dry_run: bool = False

    @property
    def acts(self) -> bool:
        return self.action != "hold" and not self.dry_run


class ScalePolicy:
    """Hysteresis-banded target tracker over a scalar load signal.

    ``decide`` accepts either a plain float load or any object with a
    ``load`` attribute (``SignalAggregate``).  Load is normalized
    utilization: 1.0 means the placeable tier is exactly saturated,
    above 1.0 work is queueing (signals.py folds queue depth in).
    """

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_threshold: float = 0.8, down_threshold: float = 0.3,
                 up_cooldown_s: float = 5.0,
                 down_cooldown_s: float = 15.0,
                 dry_run: bool = False):
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}")
        if not (0.0 < down_threshold < up_threshold):
            raise ValueError(
                f"need 0 < down_threshold < up_threshold, got "
                f"{down_threshold}/{up_threshold}")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_threshold = float(up_threshold)
        self.down_threshold = float(down_threshold)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.dry_run = bool(dry_run)
        self._last_up = float("-inf")
        self._last_down = float("-inf")

    # ------------------------------------------------------------ decide

    def decide(self, signals, current: int, now: float) -> ScaleDecision:
        load = float(getattr(signals, "load", signals))
        current = int(current)

        # clamps outrank thresholds AND cooldowns: an out-of-bounds tier
        # is a config violation, not a load response
        if current < self.min_replicas:
            return self._emit("up", self.min_replicas, now,
                              f"below min_replicas={self.min_replicas}")
        if current > self.max_replicas:
            return self._emit("down", self.max_replicas, now,
                              f"above max_replicas={self.max_replicas}")

        if load > self.up_threshold and current < self.max_replicas:
            if now - self._last_up < self.up_cooldown_s:
                return ScaleDecision(
                    "hold", current,
                    f"load {load:.2f} > {self.up_threshold:.2f} but "
                    f"inside up cooldown", self.dry_run)
            target = min(self.max_replicas,
                         max(current + 1,
                             math.ceil(current * load / self.up_threshold)))
            return self._emit(
                "up", target, now,
                f"load {load:.2f} > {self.up_threshold:.2f}")

        if load < self.down_threshold and current > self.min_replicas:
            # a recent move in EITHER direction pins the tier: scaling
            # down right after an up would thrash on the spike's tail
            since = now - max(self._last_up, self._last_down)
            if since < self.down_cooldown_s:
                return ScaleDecision(
                    "hold", current,
                    f"load {load:.2f} < {self.down_threshold:.2f} but "
                    f"inside down cooldown", self.dry_run)
            return self._emit(
                "down", current - 1, now,
                f"load {load:.2f} < {self.down_threshold:.2f}")

        return ScaleDecision("hold", current,
                             f"load {load:.2f} in band", self.dry_run)

    def _emit(self, action: str, target: int, now: float,
              reason: str) -> ScaleDecision:
        # dry-run stamps cooldowns too — the simulated tier must pace
        # exactly like the live one or the rehearsal lies
        if action == "up":
            self._last_up = now
        else:
            self._last_down = now
        return ScaleDecision(action, target, reason, self.dry_run)
