"""Elastic capacity subsystem: the closed loop from observed tier load
to replica count and per-request admission (docs/serving.md "Elastic
capacity & SLO classes").

Layout mirrors the control loop:

  * :mod:`signals` — ``TierSignals``: windowed samples of queue depth,
    utilization, TTFT p99, credit starvation and KV pressure, polled
    from the router in-process or from replica ``OP_STATS``.
  * :mod:`policy` — ``ScalePolicy``: pure hysteresis-banded target
    tracking emitting typed ``ScaleDecision``s (injected clock — the
    tier-1 tests drive it on scripted traces).
  * :mod:`actuator` — ``ReplicaLauncher`` + ``AutoscaleController``:
    spawn through the launcher, register via the weights-fingerprint
    handshake, retire via zero-client-error ``drain()``; scale events
    journaled so router takeover mid-scale is safe.
  * :mod:`admission` — SLO classes (``guaranteed``/``standard``/
    ``best-effort``), deadline-aware shedding (typed
    ``OverloadShedError``), and work-conserving tenant shares
    (borrow idle credits, clawback on demand).
"""

from .admission import (SLO_BEST_EFFORT, SLO_CLASSES, SLO_GUARANTEED,
                        SLO_STANDARD, AdmissionController, Lease,
                        OverloadShedError, TenantShares, normalize_slo)
from .actuator import (AUTOSCALE_REPLICAS, SCALE_EVENTS,
                       AutoscaleController, ReplicaHandle,
                       ReplicaLauncher)
from .policy import ScaleDecision, ScalePolicy
from .signals import (SignalAggregate, SignalSample, TierSignals,
                      poll_replicas, poll_router)

__all__ = [
    "SLO_GUARANTEED", "SLO_STANDARD", "SLO_BEST_EFFORT", "SLO_CLASSES",
    "normalize_slo", "OverloadShedError", "AdmissionController",
    "Lease", "TenantShares",
    "ScaleDecision", "ScalePolicy",
    "SignalSample", "SignalAggregate", "TierSignals", "poll_router",
    "poll_replicas",
    "AUTOSCALE_REPLICAS", "SCALE_EVENTS", "ReplicaHandle",
    "ReplicaLauncher", "AutoscaleController",
]
