"""SLO-aware admission: priority classes, deadline-aware shedding, and
work-conserving tenant shares (docs/serving.md "Elastic capacity & SLO
classes").

Three pieces, all wired into ``ServeRouter.stream``:

  * **SLO classes** — the ``slo=`` submit param: ``guaranteed`` /
    ``standard`` / ``best-effort``.  Classes are a *shedding* order,
    not a scheduling order: the engine-level priority field still
    orders work once admitted.
  * **Deadline-aware shedding** — ``AdmissionController.admit``
    estimates queue wait from the live backlog and an EWMA of recent
    service times (``est = backlog x service / capacity`` — the same
    M/M/c-shaped estimate vLLM-style schedulers use) and raises the
    typed, retryable :class:`OverloadShedError` at the door when the
    class's deadline cannot be met.  ``guaranteed`` has an infinite
    deadline by default: it is never shed, it queues — the whole point
    of shedding best-effort is to keep the guaranteed queue short.
  * **Work-conserving shares** — :class:`TenantShares` wraps the
    PR 14 strict per-tenant credit pools: a tenant whose own pool is
    empty may *borrow* an idle credit from a tenant with no waiters,
    recorded as a loan.  When the lender comes back and starves,
    ``clawback`` flags the youngest reclaimable (best-effort) loan;
    the router aborts that in-flight stream with ``OverloadShedError``
    (PR 9 engine preemption, one tier up) and the credit flows home.
    Guaranteed/standard borrowers are never reclaimed mid-flight —
    the lender waits at most one service time for those.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..scheduler import AdmissionError

__all__ = ["SLO_GUARANTEED", "SLO_STANDARD", "SLO_BEST_EFFORT",
           "SLO_CLASSES", "normalize_slo", "OverloadShedError",
           "AdmissionController", "Lease", "TenantShares"]

SLO_GUARANTEED = "guaranteed"
SLO_STANDARD = "standard"
SLO_BEST_EFFORT = "best-effort"
SLO_CLASSES = (SLO_GUARANTEED, SLO_STANDARD, SLO_BEST_EFFORT)


def normalize_slo(value: Optional[str],
                  default: str = SLO_STANDARD) -> str:
    """Map a wire ``slo`` param to a class name; ``None``/empty means
    the default.  Unknown classes are a *typed* admission failure — a
    typo'd class must not silently become standard."""
    if not value:
        return default
    v = str(value).strip().lower().replace("_", "-")
    if v not in SLO_CLASSES:
        raise AdmissionError(
            f"unknown slo class {value!r}; expected one of "
            f"{'/'.join(SLO_CLASSES)}")
    return v


class OverloadShedError(AdmissionError):
    """Typed at-the-door shed: the request's SLO deadline cannot be met
    at the current backlog (or its borrowed credit was clawed back).
    ``retryable`` is True — the client should back off and retry; the
    request was never placed, so a retry is always safe."""

    retryable = True

    def __init__(self, slo: str, est_wait_s: float, deadline_s: float,
                 reason: str = "backlog"):
        self.slo = slo
        self.est_wait_s = float(est_wait_s)
        self.deadline_s = float(deadline_s)
        self.reason = reason
        super().__init__(
            f"shed {slo} request ({reason}): estimated queue wait "
            f"{est_wait_s:.2f}s exceeds deadline {deadline_s:.2f}s; "
            f"retry with backoff")


class AdmissionController:
    """Deadline-aware shedding at the router door.

    ``deadlines`` maps SLO class -> max tolerable queue wait in
    seconds (``float('inf')`` = never shed).  ``note_service`` feeds an
    EWMA of observed per-request service times; until the first
    completion, ``service_estimate_s`` seeds it.
    """

    _ALPHA = 0.2  # EWMA weight of the newest observation

    def __init__(self, deadlines: Optional[Dict[str, float]] = None,
                 service_estimate_s: float = 0.5):
        self.deadlines = {SLO_GUARANTEED: float("inf"),
                          SLO_STANDARD: 10.0,
                          SLO_BEST_EFFORT: 1.0}
        if deadlines:
            self.deadlines.update(deadlines)
        self._service_s = float(service_estimate_s)
        self._lock = threading.Lock()
        self.shed_count: Dict[str, int] = {c: 0 for c in SLO_CLASSES}

    def note_service(self, seconds: float) -> None:
        with self._lock:
            self._service_s += self._ALPHA * (float(seconds)
                                              - self._service_s)

    @property
    def service_estimate_s(self) -> float:
        with self._lock:
            return self._service_s

    def estimate_wait(self, inflight: int, queued: int,
                      capacity: int) -> float:
        """Queue-wait estimate for the NEXT arrival: requests beyond
        capacity wait, draining ``capacity`` at a time, one EWMA
        service time per drain round."""
        backlog = inflight + queued + 1 - max(1, capacity)
        if backlog <= 0:
            return 0.0
        return backlog * self.service_estimate_s / max(1, capacity)

    def admit(self, slo: str, inflight: int, queued: int,
              capacity: int) -> float:
        """Admit or raise :class:`OverloadShedError`.  Returns the wait
        estimate so callers can log it."""
        est = self.estimate_wait(inflight, queued, capacity)
        deadline = self.deadlines.get(slo, self.deadlines[SLO_STANDARD])
        if est > deadline:
            with self._lock:
                self.shed_count[slo] = self.shed_count.get(slo, 0) + 1
            raise OverloadShedError(slo, est, deadline)
        return est


class Lease:
    """One admitted stream's credit: from the tenant's own pool
    (``lender is None``) or borrowed from ``lender``'s.  ``reclaimed``
    flips under the shares lock when clawback targets this loan; the
    router's per-token pace check treats it like a cancel and sheds
    the stream typed."""

    __slots__ = ("tenant", "lender", "reclaimable", "reclaimed")

    def __init__(self, tenant: str, lender: Optional[str],
                 reclaimable: bool = False):
        self.tenant = tenant
        self.lender = lender
        self.reclaimable = reclaimable
        self.reclaimed = False

    @property
    def borrowed(self) -> bool:
        return self.lender is not None


class TenantShares:
    """Work-conserving wrapper over the per-tenant credit pools.

    ``pools`` is the PR 14 apportionment (tenant ->
    ``ScheduledQueue``).  Strict shares remain the floor: a tenant can
    always (eventually) use its own credits.  Idle credits are lent —
    never to a pool with live waiters — and clawed back on demand.
    """

    def __init__(self, pools: Dict[str, object], borrow: bool = True,
                 on_borrow: Optional[Callable[[str, str], None]] = None):
        self._pools = pools
        self._borrow = bool(borrow)
        self._on_borrow = on_borrow
        self._lock = threading.Lock()
        self._waiters: Dict[str, int] = {t: 0 for t in pools}
        # outstanding loans keyed by LENDER, youngest last
        self._loans: Dict[str, List[Lease]] = {t: [] for t in pools}
        self.borrowed_total = 0
        self.clawbacks_total = 0

    # ----------------------------------------------------------- acquire

    def acquire(self, tenant: str, reclaimable: bool = False,
                timeout: float = 0.0,
                should_abort: Optional[Callable[[], bool]] = None
                ) -> Optional[Lease]:
        """One admission credit for ``tenant``.  Own pool first, then a
        borrow from an idle tenant, then block on the own pool (clawing
        outstanding loans we made) until ``timeout``.  Returns None on
        timeout or when ``should_abort()`` goes true (the caller owns
        the typed error); a tenant with no configured pool gets a free
        lease — unknown tenants were never gated (PR 14 semantics)."""
        pool = self._pools.get(tenant)
        if pool is None:
            return Lease(tenant, None, reclaimable)
        if pool.try_debit(1):
            return Lease(tenant, None, reclaimable)
        lease = self._try_borrow(tenant, reclaimable)
        if lease is not None:
            return lease
        # strict-share floor: block on our own pool; flag one of OUR
        # outstanding loans per wait chunk so borrowed credits flow home
        deadline = time.monotonic() + max(0.0, timeout)
        with self._lock:
            self._waiters[tenant] = self._waiters.get(tenant, 0) + 1
        try:
            while True:
                self.clawback(tenant)
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                if should_abort is not None and should_abort():
                    return None
                if pool.debit_wait(1, min(0.05, left)):
                    return Lease(tenant, None, reclaimable)
        finally:
            with self._lock:
                self._waiters[tenant] -= 1

    def _try_borrow(self, tenant: str,
                    reclaimable: bool) -> Optional[Lease]:
        if not self._borrow:
            return None
        with self._lock:
            candidates = [(t, p) for t, p in self._pools.items()
                          if t != tenant
                          and self._waiters.get(t, 0) == 0]
        for t, p in candidates:
            if p.try_debit(1):
                lease = Lease(tenant, t, reclaimable)
                with self._lock:
                    self._loans.setdefault(t, []).append(lease)
                    self.borrowed_total += 1
                if self._on_borrow is not None:
                    self._on_borrow(tenant, t)
                return lease
        return None

    # ----------------------------------------------------------- release

    def release(self, lease: Optional[Lease]) -> None:
        """Return the lease's credit: borrowed credits flow back to the
        LENDER's pool (that is the entire clawback mechanism — the
        starved lender's ``debit_wait`` wakes on this credit)."""
        if lease is None:
            return
        if lease.borrowed:
            with self._lock:
                loans = self._loans.get(lease.lender)
                if loans is not None and lease in loans:
                    loans.remove(lease)
            pool = self._pools.get(lease.lender)
        else:
            pool = self._pools.get(lease.tenant)
        if pool is not None:
            pool.credit(1)

    # ---------------------------------------------------------- clawback

    def clawback(self, lender: str, need: int = 1) -> int:
        """Flag up to ``need`` reclaimable loans lent BY ``lender``
        (youngest first — the PR 9 preemption order: the newest work
        has the least sunk cost).  The flagged streams shed themselves
        at their next pace check; their release credits the lender.
        Returns how many loans were flagged."""
        flagged = 0
        with self._lock:
            for lease in reversed(self._loans.get(lender, [])):
                if flagged >= need:
                    break
                if lease.reclaimable and not lease.reclaimed:
                    lease.reclaimed = True
                    flagged += 1
            self.clawbacks_total += flagged
        return flagged

    # ------------------------------------------------------------- stats

    def outstanding_loans(self, lender: Optional[str] = None) -> int:
        with self._lock:
            if lender is not None:
                return len(self._loans.get(lender, []))
            return sum(len(v) for v in self._loans.values())

    def waiters(self, tenant: str) -> int:
        with self._lock:
            return self._waiters.get(tenant, 0)
