"""Continuous-batching serving engine (Orca/vLLM-style, JAX-native).

The inference layer (``byteps_tpu.inference``) stops at one-shot
``generate()`` calls: every caller pays a private prefill + decode loop,
and concurrent callers never share a batch.  This package turns those
kernels into a *serving engine*:

  * ``slots`` — a fixed-capacity KV-cache slot pool built on
    ``models.transformer.init_cache`` (N slots x max_seq padded cache),
    so admitting a request is a cache-row write, not a recompile;
  * ``blocks`` — the paged alternative (``paged=True``): KV memory as
    a pool of fixed-size blocks with per-slot block tables, lazy block
    grants, copy-on-write forks, and preemption under pressure —
    actual usage, not worst-case ``max_seq``, bounds concurrency, and
    a prefix-cache hit shares refcounted blocks instead of copying
    rows (PagedAttention / RadixAttention unified);
  * ``scheduler`` — credit-scheduled admission reusing the semantics of
    ``common/scheduler.py:ScheduledQueue``: prefill (large, bursty)
    interleaves against decode (small, latency-critical) under a token
    credit budget, FIFO within priority, with a bounded queue that
    rejects loudly when full;
  * ``engine`` — the jitted step functions (batched single-token decode
    over the whole slot pool; bucket-padded prefill, optionally split
    into position-offset chunks so long prompts interleave with decode
    ticks instead of stalling them) plus the host-side tick loop;
    static shapes end to end, so steady-state serving never retraces;
  * ``prefix`` — a refcounted, LRU/byte-budgeted store of block-aligned
    KV prefixes keyed by a rolling token hash: shared system prompts
    are copied device-side into the slot row instead of recomputed
    (bit-exact — the bytes move, nothing is re-derived);
  * ``spec`` — draft-free speculative decoding (``spec_k > 0``):
    n-gram prompt-lookup proposals from each request's own history,
    verified in ONE batched multi-token pass per tick
    (``Transformer.verify_tokens``) — several tokens per tick on
    repetitive output, bit-exact by construction because a proposal is
    accepted only when it equals the token the model itself produced;
  * ``frontend`` — an in-process ``ServeClient`` (submit / stream /
    cancel / drain) and a thin length-prefixed TCP frontend launched by
    ``launcher.py`` under the ``serve`` role;
  * ``router`` — the fault-tolerant scale-out tier over N frontend
    replicas (``launcher.py`` role ``router``): health-checked
    failover with deterministic mid-stream re-dispatch (a dead
    replica's requests resume token-identically on a survivor),
    prefix-affinity placement, per-replica credit backpressure,
    per-tenant fair-share credits, and graceful drain — and the
    router itself is no single point of failure: standbys follow an
    ``OP_JOURNAL`` state stream (``journal``), take over
    deterministically at a fenced epoch on active death, and
    multi-router clients re-issue mid-stream with ``resume`` —
    docs/serving.md "Router tier" / "Router HA";
  * ``disagg`` — disaggregated prefill/decode tiers (docs/serving.md
    "Disaggregated tiers"): prefill-role replicas ship finished-prompt
    KV as paged blocks over ``OP_KV_BLOCKS`` to the decode replica the
    router chose, which adopts them through the resume machinery —
    bit-exact, with decode-side re-prefill as the availability floor;
  * ``autoscale`` — the elastic-capacity subsystem (docs/serving.md
    "Elastic capacity & SLO classes"): windowed tier signals, a
    hysteresis-banded target-tracking scale policy, a launcher-backed
    actuator that journals scale events for HA takeover, and SLO-class
    admission — deadline-aware shedding (typed ``OverloadShedError``)
    plus work-conserving tenant shares (idle credits are lent and
    clawed back on demand);
  * ``metrics`` — TTFT/TPOT/queue-wait and occupancy/tokens-per-sec
    counters exported through the process ``Tracer``.

Correctness anchor: in deterministic mode (the default) the engine's
output is token-identical to sequential ``generate()`` per request —
see docs/serving.md.
"""

from .autoscale import (  # noqa: F401
    AutoscaleController,
    OverloadShedError,
    ReplicaLauncher,
    ScaleDecision,
    ScalePolicy,
    TenantShares,
    TierSignals,
    normalize_slo,
)
from .blocks import (  # noqa: F401
    BlockAllocator,
    BlocksExhaustedError,
    BlockTable,
    PagedSlotPool,
)
from .disagg import (  # noqa: F401
    KVShipAbortedError,
    KVShipDigestError,
    KVShipError,
    KVShipGeometryError,
    KVShipSequenceError,
    KVStager,
    pool_geometry,
    ship_parked,
)
from .engine import (  # noqa: F401
    EpochFencedError,
    Request,
    RequestState,
    ServingEngine,
)
from .frontend import (  # noqa: F401
    RemoteServeClient,
    ServeClient,
    ServeConnectionError,
    ServeReplyError,
    serve,
    serve_from_env,
)
from .journal import JournalSender  # noqa: F401
from .metrics import ServeMetrics, get_serve_metrics  # noqa: F401
from .router import (  # noqa: F401
    ReplicaLostError,
    ReplicaState,
    RouterFrontend,
    RouterStandbyError,
    ServeRouter,
    WeightsMismatchError,
    router_from_env,
    serve_router,
)
from .spec import NgramProposer  # noqa: F401
from .prefix import (  # noqa: F401
    PagedPrefixCache,
    PrefixCache,
    PrefixEntry,
    weights_fingerprint,
)
from .scheduler import (  # noqa: F401
    AdmissionError,
    PrefillTask,
    QueueFullError,
    ServeScheduler,
)
from .slots import SlotPool  # noqa: F401
