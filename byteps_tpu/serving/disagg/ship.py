"""KV block shipping: the prefill -> decode wire leg of disaggregation.

One ``OP_KV_BLOCKS`` frame per paged block, sent on a fresh connection
to the decode replica's serve frontend:

    name    = JSON {"key", "i", "n", "pos", "geom", "digest"}
    payload = the block's raw K/V bytes, every layer's caches
              concatenated in sorted-key order (scatter-gather views —
              no user-space copy on the send path)

``key`` is the router-minted ship id the decode-leg dispatch later
claims the staged blocks under; ``i``/``n`` sequence the blocks so a
torn or reordered ship is detected (``KVShipSequenceError`` aborts the
whole staging — partial KV is *never* silently attended); ``digest``
is a per-block blake2b-128 over the payload, verified before the block
is scattered into the pool (a corrupt block is refused typed and the
sender retries it, bounded by ``BYTEPS_DISAGG_SHIP_RETRIES``);
``geom`` commits both pools to the same (layers, block size, per-block
elements, dtype) tuple.  The geometry is layout-agnostic on purpose:
a grouped ``[block, KV, D]`` row and a flat ``[block, KV*D]`` row are
byte-identical in row-major order, so a grouped-pool prefill replica
can ship to a flat-pool decode replica.

Every failure mode downgrades, never corrupts: the sender surfaces a
typed ``KVShipError`` subclass, the frontend reports ``{"shipped":
False}`` alongside the (still valid) first token, and the router falls
back to decode-side re-prefill — the PR 10 resume path, so
disaggregation can never be *less* available than colocated serving.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ...common import logging as bps_log
from ...engine.wire import _decode, _encode, _payload_view, _send_buffers
from .. import metrics as sm

__all__ = ["KVShipError", "KVShipGeometryError", "KVShipSequenceError",
           "KVShipDigestError", "KVShipAbortedError", "KVStager",
           "pool_geometry", "ship_parked", "on_block_sent"]


class KVShipError(RuntimeError):
    """Base of the typed ship failures.  Every subclass means the same
    thing to the router: this request's KV did not arrive whole — fall
    back to decode-side re-prefill."""


class KVShipGeometryError(KVShipError):
    """The two pools disagree on (layers, block, per-block elements,
    dtype) — nothing can be shipped between them."""


class KVShipSequenceError(KVShipError):
    """A block arrived out of order (or for an unknown ship): the
    staging is torn and has been aborted receiver-side."""


class KVShipDigestError(KVShipError):
    """A block's payload failed its blake2b check.  The receiver's
    expected index is unchanged — the sender retries the same block."""


class KVShipAbortedError(KVShipError):
    """The ship died wholesale: unreachable decode replica, connection
    cut mid-transfer, receiver out of blocks, or an unrecognized typed
    refusal."""


# test/chaos hook: called as on_block_sent(key, i, n) after each block
# is ACKed by the receiver.  scripts/router_chaos.py --kill-prefill-at
# uses it to kill the prefill replica after exactly N shipped blocks;
# an exception raised here aborts the ship like a wire cut.
on_block_sent = None


def pool_geometry(engine) -> str:
    """The compatibility string both ends of a ship must agree on:
    layer count, block size, and per-block element count + dtype for
    every cache tensor (sorted-key order — the payload order).  Layout
    (grouped vs flat) is deliberately absent: the row-major bytes are
    identical either way."""
    pool = engine.pool
    c0 = pool.caches[0]
    parts = [f"L{len(pool.caches)}", f"B{pool.block}"]
    for k in sorted(c0):
        a = c0[k]
        parts.append(f"{k}={int(np.prod(a.shape[1:]))}:{a.dtype}")
    return "/".join(parts)


def _frame_buffers(op: int, meta: dict, payload_bufs, plen: int) -> List:
    """Hand-built arr-less frame (name=JSON meta, raw payload) as a
    scatter-gather buffer list — byte-identical to
    ``_encode(op, json.dumps(meta), None, raw=payload)`` without the
    user-space join of the block's K/V views."""
    nb = json.dumps(meta).encode()
    head = struct.pack("<BI", op, len(nb)) + nb
    head += struct.pack("<I", 0)   # dtype tag: none (raw payload)
    head += struct.pack("<B", 0)   # ndim 0
    head += struct.pack("<Q", plen)
    return [head, *payload_bufs]


def _digest(bufs) -> str:
    h = hashlib.blake2b(digest_size=16)
    for b in bufs:
        h.update(b)
    return h.hexdigest()


_TYPED_SHIP_ERRORS = {
    "KVShipGeometryError": KVShipGeometryError,
    "KVShipSequenceError": KVShipSequenceError,
    "KVShipDigestError": KVShipDigestError,
    "KVShipAbortedError": KVShipAbortedError,
}


def ship_parked(engine, addr: str, key: str, parked: dict, *,
                metrics=None, transport: Optional[str] = None) -> dict:
    """Ship a parked prefill's KV blocks to the decode replica at
    ``addr`` under ship id ``key``.  ``parked`` is the engine's
    ``take_parked_kv`` entry; the CALLER keeps ownership of its block
    refs (release them in a ``finally`` — this function only reads).
    Returns ``{"shipped": True, "blocks": n, "bytes": total}``; raises
    a :class:`KVShipError` subclass on any failure."""
    from ...common.config import get_config
    from ..frontend import OP_KV_BLOCKS
    from ...engine.transport import resolve_transport, transport_connect

    cfg = get_config()
    ids = parked["ids"]
    n = len(ids)
    geom = pool_geometry(engine)
    t0 = time.monotonic()
    # one locked device gather + host copy for the whole ship; the
    # per-block sends below slice views out of it
    layers = engine.extract_kv_blocks(ids)
    keys_per_layer = [sorted(layer) for layer in layers]
    kind, path = resolve_transport(addr, transport or cfg.transport)
    try:
        sock = transport_connect(kind, path, addr,
                                 timeout=cfg.disagg_ship_timeout_ms / 1e3)
    except OSError as e:
        raise KVShipAbortedError(
            f"decode replica {addr} unreachable for KV ship: {e}") from e
    total = 0
    try:
        try:
            for i in range(n):
                bufs = [_payload_view(np.ascontiguousarray(layer[k][i]))
                        for layer, ks in zip(layers, keys_per_layer)
                        for k in ks]
                plen = sum(len(b) for b in bufs)
                meta = {"key": key, "i": i, "n": n,
                        "pos": int(parked["pos"]), "geom": geom,
                        "digest": _digest(bufs)}
                attempts = 0
                while True:
                    _send_buffers(sock, _frame_buffers(
                        OP_KV_BLOCKS, meta, bufs, plen))
                    status, _, _, payload = _decode(sock)
                    if status == 0:
                        break
                    msg = payload.decode()
                    ename = msg.split(":", 1)[0].strip()
                    if (ename == "KVShipDigestError"
                            and attempts < cfg.disagg_ship_retries):
                        attempts += 1
                        bps_log.warning(
                            "disagg ship %s: block %d/%d digest refused, "
                            "retry %d", key, i, n, attempts)
                        continue
                    raise _TYPED_SHIP_ERRORS.get(
                        ename, KVShipAbortedError)(msg)
                total += plen
                if metrics is not None:
                    metrics.bump(sm.KV_BLOCKS_SHIPPED)
                    metrics.bump(sm.KV_BLOCKS_SHIPPED_BYTES, plen)
                hook = on_block_sent
                if hook is not None:
                    hook(key, i, n)
        except (ConnectionError, OSError, ValueError) as e:
            raise KVShipAbortedError(
                f"KV ship {key} to {addr} died after {total} bytes: "
                f"{type(e).__name__}: {e}") from e
    finally:
        try:
            sock.close()
        except OSError:
            pass
    if metrics is not None:
        metrics._hist("ship").observe(time.monotonic() - t0)
    return {"shipped": True, "blocks": n, "bytes": total}


class _Staged:
    __slots__ = ("ids", "n", "pos", "next", "t")


class KVStager:
    """Decode-side receiver: verifies, stages, and hands over shipped
    KV blocks.

    Blocks for a ship are allocated from the engine's pool UP FRONT at
    block 0 (``BlocksExhaustedError`` propagates typed — the sender
    aborts and the router re-prefills) and scattered in as frames
    arrive.  ``take(key)`` consumes a COMPLETE staging for the decode
    dispatch's adoption; a partial one is released, never adopted.
    Stranded entries (the router died between ship and dispatch, or
    the request finished at the prefill leg) are TTL-swept."""

    def __init__(self, engine, ttl: float = 60.0):
        self.engine = engine
        self.ttl = ttl
        self._lock = threading.Lock()
        self._entries: Dict[str, _Staged] = {}
        self._geom = pool_geometry(engine)
        # static payload schema: (key, tail shape, dtype) per cache
        # tensor per layer, snapshotted once — reading live caches per
        # frame would race the tick thread's donated buffers
        self._schema = [
            [(k, tuple(int(d) for d in c[k].shape[1:]),
              np.dtype(str(c[k].dtype))) for k in sorted(c)]
            for c in engine.pool.caches]
        self._block_bytes = sum(
            int(np.prod(shape)) * dt.itemsize
            for layer in self._schema for _, shape, dt in layer)

    # ------------------------------------------------------------- wire

    def handle(self, name: str, payload) -> bytes:
        """One OP_KV_BLOCKS frame -> one encoded reply frame.  Typed
        ship errors ride status=1 with the error-name prefix the sender
        maps back; anything else propagates to the handler's generic
        error reply."""
        try:
            ack = self._accept(name, payload)
        except KVShipError as e:
            return _encode(1, "", None,
                           f"{type(e).__name__}: {e}".encode())
        return _encode(0, "", None, json.dumps(ack).encode())

    def _accept(self, name: str, payload) -> dict:
        meta = json.loads(name)
        key, i, n = str(meta["key"]), int(meta["i"]), int(meta["n"])
        if meta.get("geom") != self._geom:
            raise KVShipGeometryError(
                f"pool geometry mismatch: ship says {meta.get('geom')!r},"
                f" this pool is {self._geom!r}")
        if len(payload) != self._block_bytes:
            raise KVShipGeometryError(
                f"block payload is {len(payload)} bytes, this pool's "
                f"blocks are {self._block_bytes}")
        self.sweep()
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                if i != 0:
                    raise KVShipSequenceError(
                        f"block {i} for unknown ship {key} — torn "
                        f"staging refused")
                ent = _Staged()
                # allocate the WHOLE staging up front: a mid-ship pool
                # exhaustion would strand a half-written staging
                ent.ids = self.engine.stage_alloc(n)
                ent.n = n
                ent.pos = int(meta["pos"])
                ent.next = 0
                ent.t = time.monotonic()
                self._entries[key] = ent
            if i != ent.next:
                stale = self._entries.pop(key)
                self.engine.release_kv_ids(stale.ids)
                raise KVShipSequenceError(
                    f"ship {key}: got block {i}, expected {ent.next} — "
                    f"staging aborted")
        # digest + scatter OUTSIDE the stager lock (hash and device
        # write are the slow parts; frames for one key are serial on
        # their connection, so ent is not contended)
        h = hashlib.blake2b(digest_size=16)
        h.update(payload)
        if h.hexdigest() != meta.get("digest"):
            # expected index unchanged: the sender resends this block
            raise KVShipDigestError(
                f"ship {key} block {i}/{n}: payload digest mismatch")
        self.engine.write_kv_block(ent.ids[i], self._split(payload))
        with self._lock:
            if self._entries.get(key) is ent:
                ent.next = i + 1
                ent.t = time.monotonic()
        return {"i": i, "complete": bool(ent.next >= n)}

    def _split(self, payload) -> List[Dict[str, np.ndarray]]:
        mv = memoryview(payload)
        out: List[Dict[str, np.ndarray]] = []
        off = 0
        for layer in self._schema:
            d = {}
            for k, shape, dt in layer:
                nb = int(np.prod(shape)) * dt.itemsize
                d[k] = np.frombuffer(
                    mv[off:off + nb], dtype=dt).reshape(shape)
                off += nb
            out.append(d)
        return out

    # --------------------------------------------------------- handover

    def take(self, key: str) -> Optional[dict]:
        """Claim the staged entry for ``key``.  A COMPLETE staging
        transfers block ownership to the caller (``{"ids", "pos"}``);
        a partial or unknown one returns None (partials are released
        here — the torn ship is never attended)."""
        with self._lock:
            ent = self._entries.pop(key, None)
        if ent is None:
            return None
        if ent.next >= ent.n:
            return {"ids": ent.ids, "pos": ent.pos}
        bps_log.warning(
            "disagg: ship %s claimed at %d/%d blocks — releasing the "
            "torn staging, decode falls back to re-prefill",
            key, ent.next, ent.n)
        self.engine.release_kv_ids(ent.ids)
        return None

    def sweep(self) -> int:
        """Release stagings idle past the TTL (the router died between
        ship and dispatch, or the request needed no decode leg)."""
        now = time.monotonic()
        dead = []
        with self._lock:
            for k in list(self._entries):
                if now - self._entries[k].t > self.ttl:
                    dead.append(self._entries.pop(k))
        for ent in dead:
            self.engine.release_kv_ids(ent.ids)
        return len(dead)

    def stats(self) -> dict:
        with self._lock:
            return {"staged": len(self._entries)}
