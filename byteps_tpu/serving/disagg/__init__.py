"""Disaggregated prefill/decode serving tiers (docs/serving.md
"Disaggregated tiers").

BytePS's core move — split one monolithic role into specialized tiers
connected by a push/pull wire — applied to serving: **prefill**
replicas run chunked prefill only and ship the finished request's KV
as flat paged blocks over a new ``OP_KV_BLOCKS`` wire op; **decode**
replicas scatter the blocks into their own ``PagedSlotPool``, seed the
slot at the prompt cursor through the existing ``resume_tokens``/
parked-key machinery, and decode as if they had prefilled locally —
bit-exact by the position-wise determinism argument, greedy and
seeded.  The router (serving/router.py) owns role-aware placement and
both failure legs: a prefill replica dying mid-ship falls back to
decode-side re-prefill (the PR 10 resume path — disaggregation can
never be *less* available than colocated serving), and a decode
replica dying after the ship re-enters normal failover.
"""

from .ship import (  # noqa: F401
    KVShipAbortedError,
    KVShipDigestError,
    KVShipError,
    KVShipGeometryError,
    KVShipSequenceError,
    KVStager,
    pool_geometry,
    ship_parked,
)
