"""Prefix-reuse KV cache: refcounted, LRU/byte-budgeted shared prefixes.

SGLang's RadixAttention observation, adapted to the slot-pool engine:
system-prompt-heavy traffic recomputes identical prompt K/V over and
over, and for a causal model the K/V of a shared token prefix is
*position-for-position identical* across requests — so it can be copied
device-side instead of recomputed, and the copy is bit-exact by
construction (the bytes are moved, not re-derived; docs/serving.md
"Prefix-reuse KV cache").

Design, sized for the static-shape engine:

  * **Entries are full cache-row buffers.**  An inserted prefix is the
    request's slot row with positions ``>= length`` zero-masked — every
    entry therefore has the SAME pytree shapes (``[1, max_seq, ...]``
    per layer), so the engine's jitted extract and copy functions trace
    exactly once each, the same compile discipline as the decode step.
    The cost is bytes: a short prefix pays a full row's storage, which
    the byte budget accounts honestly.
  * **One buffer, many index keys.**  The lookup index maps a *rolling
    block hash* to ``(entry, boundary_length)``: inserting a prefix of
    ``k`` blocks registers every boundary ``1..k`` against the same
    buffer, so a request sharing only the first block of a longer
    cached prefix still hits.  Copying more rows than the match length
    is safe — rows past the boundary are never attended before the
    request's own prefill/decode overwrites them (the engine's
    overwrite-before-attend invariant, slots.py).
  * **Hashes are verified.**  A match compares the actual stored tokens
    before it is returned; a digest collision degrades to a miss, never
    to wrong K/V.
  * **Refcounts pin, LRU evicts.**  ``acquire``/``release`` bracket an
    entry's use (the engine pins across the device copy); eviction
    walks least-recently-matched entries with zero refs until the store
    fits ``max_bytes``.

The usable match length is capped at ``len(prompt) - 1``: the engine
must still run at least one prefill position to produce the first
token's logits.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PrefixCache", "PagedPrefixCache", "PrefixEntry",
           "weights_fingerprint"]


def _tree_bytes(tree) -> int:
    return sum(int(x.nbytes) for x in jax.tree_util.tree_leaves(tree))


def weights_fingerprint(variables) -> bytes:
    """Order-stable digest of a parameter pytree: leaf paths, shapes,
    dtypes, and a cheap value sample (per-leaf float32 sum plus head
    and tail elements).  Engines fold it into their prefix-hash salt,
    so engines serving *different weights* through one shared
    ``PrefixCache`` occupy disjoint key spaces — K/V computed under one
    checkpoint can never be matched to a prompt served under another.
    Costs a few scalar readbacks per leaf, once per engine."""
    h = hashlib.blake2b(digest_size=16)
    for path, leaf in jax.tree_util.tree_flatten_with_path(variables)[0]:
        arr = jnp.asarray(leaf)
        flat = arr.reshape(-1)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(f"{arr.shape}{arr.dtype}".encode())
        h.update(np.asarray(flat[:8]).tobytes())
        h.update(np.asarray(flat[-8:]).tobytes())
        h.update(np.asarray(jnp.sum(flat.astype(jnp.float32))).tobytes())
    return h.digest()


class PrefixEntry:
    """One stored prefix: a full cache-row buffer plus the tokens it
    holds.  ``refs`` pins the entry against eviction while the engine
    copies it device-side."""

    __slots__ = ("buffer", "tokens", "length", "nbytes", "refs", "keys",
                 "stamp", "salt")

    def __init__(self, buffer, tokens: np.ndarray, length: int,
                 nbytes: int, stamp: int, salt: bytes = b""):
        self.buffer = buffer
        self.tokens = tokens          # [length] int32, verified on match
        self.length = length          # block-aligned token count stored
        self.nbytes = nbytes
        self.refs = 0
        # (digest, boundary_length) index keys referencing this entry
        self.keys: List[Tuple[bytes, int]] = []
        self.stamp = stamp            # LRU clock (monotonic per touch)
        self.salt = salt              # inserter's key-space (weights)


class PrefixCache:
    """Block-aligned KV-prefix store keyed by a rolling token hash.

    ``block`` is the match granularity in tokens (prefixes are stored
    and matched at multiples of it); ``max_bytes`` bounds the summed
    buffer bytes (0 = unbounded).  Thread-safe; the engine additionally
    serializes all calls under its tick lock.
    """

    def __init__(self, block: int = 16, max_bytes: int = 256 << 20):
        if block < 1:
            raise ValueError(f"block must be >= 1, got {block}")
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        self.block = block
        self.max_bytes = max_bytes
        self._index: Dict[bytes, Tuple[PrefixEntry, int]] = {}
        self._entries: List[PrefixEntry] = []
        self._clock = itertools.count(1)
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evictions = 0

    # ------------------------------------------------------------- hashing

    def _digests(self, tokens: np.ndarray, nblocks: int,
                 salt: bytes = b"") -> List[bytes]:
        """Rolling per-boundary digests: ``h_j = H(h_{j-1} || block_j)``
        seeded with ``salt``, so the j-block digest commits to every
        token before it AND to the caller's key space (engines salt
        with a weights fingerprint — see :func:`weights_fingerprint`)."""
        toks = np.ascontiguousarray(np.asarray(tokens, np.int32))
        out: List[bytes] = []
        h = salt
        B = self.block
        for j in range(nblocks):
            h = hashlib.blake2b(h + toks[j * B:(j + 1) * B].tobytes(),
                                digest_size=16).digest()
            out.append(h)
        return out

    def digests_for(self, prompt, salt: bytes = b"") -> List[bytes]:
        """All rolling block digests of ``prompt`` (``len(prompt) //
        block`` of them).  Callers issuing several lookups for one
        prompt (the engine: match at admit, then insertable_len and
        insert after prefill) compute this once and pass it via
        ``digests=`` — each call then skips its own hashing pass, which
        otherwise runs one blake2b per block per call on the engine's
        tick thread."""
        toks = np.asarray(prompt, np.int32).reshape(-1)
        return self._digests(toks, int(toks.shape[0]) // self.block,
                             salt)

    # -------------------------------------------------------------- lookup

    def match(self, prompt, salt: bytes = b"",
              digests: Optional[List[bytes]] = None,
              ) -> Optional[Tuple[PrefixEntry, int]]:
        """Longest cached block-aligned prefix of ``prompt`` usable for
        serving: ``(entry, length)`` with ``length <= len(prompt) - 1``
        (at least one position must remain to prefill for the first
        token's logits), or None.  Touches the entry's LRU stamp.
        Only entries inserted under the same ``salt`` can match.
        ``digests`` (from :meth:`digests_for`, same prompt and salt)
        skips the hashing pass."""
        toks = np.asarray(prompt, np.int32).reshape(-1)
        max_blocks = (int(toks.shape[0]) - 1) // self.block
        if max_blocks < 1:
            with self._lock:
                self.misses += 1
            return None
        if digests is not None and len(digests) >= max_blocks:
            digs = digests[:max_blocks]
        else:
            digs = self._digests(toks, max_blocks, salt)
        with self._lock:
            for j in range(max_blocks, 0, -1):
                found = self._index.get(digs[j - 1])
                if found is None:
                    continue
                entry, blen = found
                if not np.array_equal(entry.tokens[:blen], toks[:blen]):
                    continue  # digest collision -> treat as a miss
                entry.stamp = next(self._clock)
                self.hits += 1
                return entry, blen
            self.misses += 1
            return None

    def insertable_len(self, prompt, salt: bytes = b"",
                       digests: Optional[List[bytes]] = None) -> int:
        """Block-aligned length a post-prefill insert of ``prompt``
        would store, or 0 when nothing new would land (prompt shorter
        than a block, or its full block-aligned prefix is already
        indexed).  ``digests`` as in :meth:`match`."""
        toks = np.asarray(prompt, np.int32).reshape(-1)
        nblocks = int(toks.shape[0]) // self.block
        if nblocks < 1:
            return 0
        if digests is not None and len(digests) >= nblocks:
            digs = digests[:nblocks]
        else:
            digs = self._digests(toks, nblocks, salt)
        with self._lock:
            if digs[-1] in self._index:
                return 0
        return nblocks * self.block

    # -------------------------------------------------------------- insert

    def insert(self, tokens, buffer, salt: bytes = b"",
               digests: Optional[List[bytes]] = None) -> bool:
        """Store ``buffer`` (a full cache-row pytree whose rows
        ``>= len(tokens)`` are zero-masked) under every block boundary
        of ``tokens``, keyed in ``salt``'s key space.  ``len(tokens)``
        must be block-aligned (callers slice with
        :meth:`insertable_len`).  Returns False when nothing was stored
        (already indexed, or larger than the whole budget).
        ``digests`` as in :meth:`match` (rolling digests of the full
        prompt work for its sliced prefix — digest ``j`` commits only
        to blocks ``<= j``)."""
        toks = np.asarray(tokens, np.int32).reshape(-1).copy()
        length = int(toks.shape[0])
        if length < self.block or length % self.block:
            raise ValueError(
                f"insert length {length} is not a positive multiple of "
                f"block {self.block}")
        nblocks = length // self.block
        if digests is not None and len(digests) >= nblocks:
            digs = digests[:nblocks]
        else:
            digs = self._digests(toks, nblocks, salt)
        nbytes = _tree_bytes(buffer)
        with self._lock:
            if digs[-1] in self._index:
                return False  # a concurrent insert won the race
            if self.max_bytes and nbytes > self.max_bytes:
                return False  # a single entry cannot fit the budget
            entry = PrefixEntry(buffer, toks, length, nbytes,
                                next(self._clock), salt)
            for j in range(1, nblocks + 1):
                if digs[j - 1] not in self._index:
                    self._index[digs[j - 1]] = (entry, j * self.block)
                    entry.keys.append((digs[j - 1], j * self.block))
            self._entries.append(entry)
            self.insertions += 1
            self._evict_to_budget_locked()
            return True

    # ------------------------------------------------------------ eviction

    def acquire(self, entry: PrefixEntry) -> None:
        """Pin ``entry`` against eviction (bracket a device copy)."""
        with self._lock:
            entry.refs += 1

    def release(self, entry: PrefixEntry) -> None:
        with self._lock:
            if entry.refs < 1:
                raise ValueError("release() without matching acquire()")
            entry.refs -= 1

    def _evict_entry_locked(self, victim: PrefixEntry) -> None:
        self._entries.remove(victim)
        for digest, blen in victim.keys:
            self._index.pop(digest, None)
            # a boundary first registered by the victim may be
            # covered by a LATER entry that shares its blocks (insert
            # only registers boundaries it does not already find):
            # re-point the key at a surviving cover, or shared-prefix
            # lookups would miss K/V the store still holds
            for heir in self._entries:
                if (heir.salt == victim.salt
                        and heir.length >= blen and np.array_equal(
                            heir.tokens[:blen], victim.tokens[:blen])):
                    self._index[digest] = (heir, blen)
                    heir.keys.append((digest, blen))
                    break
        self.evictions += 1
        self._release_entry(victim)

    def _release_entry(self, victim: PrefixEntry) -> None:
        """Storage-release hook: the base store owns plain device
        buffers (GC'd with the entry); the paged subclass drops block
        references here."""

    def _evict_to_budget_locked(self) -> None:
        if not self.max_bytes:
            return
        while self.total_bytes > self.max_bytes:
            victims = [e for e in self._entries if e.refs == 0]
            if not victims:
                return  # everything pinned; retry on the next insert
            self._evict_entry_locked(min(victims, key=lambda e: e.stamp))

    # ---------------------------------------------------------- inspection

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries)

    @property
    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            total = sum(e.nbytes for e in self._entries)
            return {"hits": self.hits, "misses": self.misses,
                    "insertions": self.insertions,
                    "evictions": self.evictions,
                    "entries": len(self._entries), "bytes": total}


class PagedPrefixCache(PrefixCache):
    """Prefix store over a **paged** KV pool: a radix-style chain of
    per-block nodes holding physical block ids instead of copied row
    buffers (serving/blocks.py).

    This is the unification the paged refactor buys (SGLang's
    RadixAttention observation): the prefix store was already
    block-aligned, so once the cache itself is block-granular a prefix
    *is* a list of blocks — and once the INDEX is block-granular too,
    the stored unit is one node per rolling-hash boundary:

      * **one node per block boundary**: inserting a ``k``-block prefix
        creates (at most) ``k`` nodes, each owning exactly ONE store
        reference on its own physical block and keyed by the rolling
        digest of the chain root..self.  A later insert that extends an
        indexed chain creates only the NEW tail nodes — so two requests
        whose common prefix was never inserted as one entry still meet
        at the same nodes and share the same physical blocks;
      * **canonical blocks**: when an insert walks onto an
        already-indexed boundary, the EXISTING node's physical id wins
        and the newcomer's duplicate block simply keeps its slot
        refcount (freed when the request's table releases it).  This
        dedup is sound because block content is a deterministic
        function of the token prefix — prefill is bit-reproducible,
        and ``kv_dtype="int8"`` pools quantize at write time so even
        the quantized bytes are identical (docs/serving.md);
      * **a hit is sharing**: ``match`` (inherited — the node's key IS
        the boundary key) returns the deepest verified node, whose
        ``buffer`` is the full root..self id chain; the admitted slot's
        table adopts those blocks (refcount bumps) — zero device-side
        K/V copies, enforced by the engine's compile counters.

    Tensor-parallel pools (``PagedSlotPool(tp=...)``) need no paged-
    prefix changes at all: a physical block id names the same token
    span on EVERY shard's sub-pool, so the id chains, refcounts, dedup,
    and byte accounting above are shard-count-independent — a hit
    shares all ``tp`` sub-pool blocks with one refcount bump
    (tests/test_tp_serving.py pins zero-copy hits at tp=2).
        (``prefix_copy``/``prefix_extract`` stay 0);
      * **partial insert under budget**: the walk stores the longest
        affordable prefix of new nodes instead of refusing the whole
        chain — a long prompt's first blocks stay reusable even when
        its tail does not fit ``max_bytes``;
      * **leaf-only LRU eviction**: only nodes with no children and no
        pins are victims, so the chain invariant (boundary ``j``
        indexed ⟹ boundary ``j-1`` indexed) always holds and
        ``insertable_len``'s last-boundary probe stays exact.  Evicting
        a leaf decrefs ONE block; a cold chain drains tail-first.
        Byte accounting is therefore exact — each node charges its one
        block — where the old whole-entry store double-charged
        overlapping chains.

    Matching, hashing, token verification, and LRU stamps are inherited
    unchanged.  A store is bound to ONE allocator (block ids are
    meaningless across pools), so paged engines cannot share a store
    unless they share a pool — ``ServingEngine`` refuses the
    cross-engine case loudly.

    Under block pressure the engine calls :meth:`evict_for` *before*
    preempting live requests: cached-but-unreferenced prefixes are the
    cheapest memory to reclaim (they can always be recomputed).
    """

    def __init__(self, allocator, block: int, block_bytes: int,
                 max_bytes: int = 0, on_evict=None):
        super().__init__(block=block, max_bytes=max_bytes)
        self.allocator = allocator
        self.block_bytes = block_bytes
        self._on_evict = on_evict
        self.blocks_released = 0
        # radix bookkeeping, keyed by each node's boundary digest
        self._node_parent: Dict[bytes, Optional[bytes]] = {}
        self._node_children: Dict[bytes, int] = {}

    def insert(self, tokens, buffer, salt: bytes = b"",
               digests: Optional[List[bytes]] = None) -> bool:
        raise TypeError(
            "PagedPrefixCache stores block references, not row buffers;"
            " use insert_blocks()")

    def insert_blocks(self, tokens, block_ids, salt: bytes = b"",
                      digests: Optional[List[bytes]] = None) -> bool:
        """Register ``tokens``' block-aligned prefix as a chain of
        per-boundary nodes.  Boundaries already indexed are REUSED
        (their canonical physical id wins — no new reference taken);
        each new boundary becomes a node owning one store reference on
        its block.  Returns False when nothing new was stored (fully
        indexed already, or not a single new node fits the budget)."""
        toks = np.asarray(tokens, np.int32).reshape(-1).copy()
        length = int(toks.shape[0])
        nblocks = len(block_ids)
        if length != nblocks * self.block or nblocks < 1:
            raise ValueError(
                f"insert length {length} does not cover {nblocks} "
                f"block(s) of {self.block} tokens")
        if digests is not None and len(digests) >= nblocks:
            digs = digests[:nblocks]
        else:
            digs = self._digests(toks, nblocks, salt)
        with self._lock:
            floor = next(self._clock)  # nodes created below are newer
            chain: List[int] = []
            created = False
            parent: Optional[bytes] = None
            for j in range(1, nblocks + 1):
                blen = j * self.block
                found = self._index.get(digs[j - 1])
                if found is not None:
                    node, node_blen = found
                    if (node_blen != blen or not np.array_equal(
                            node.tokens[:blen], toks[:blen])):
                        # digest collision against a foreign chain:
                        # stop extending rather than corrupt the walk
                        break
                    # canonical block: the indexed node's id wins
                    chain.append(node.buffer[-1])
                    node.stamp = next(self._clock)
                    parent = digs[j - 1]
                    continue
                if self.max_bytes and (self.total_bytes
                                       + self.block_bytes
                                       > self.max_bytes):
                    # partial insert: keep the affordable prefix, try
                    # to fund the next node from LRU leaves older than
                    # this call's own additions
                    self._evict_to_budget_locked(
                        headroom=self.block_bytes, stamp_before=floor)
                    if self.total_bytes + self.block_bytes > \
                            self.max_bytes:
                        break
                bid = block_ids[j - 1]
                self.allocator.incref(bid)
                chain.append(bid)
                node = PrefixEntry(tuple(chain), toks[:blen], blen,
                                   self.block_bytes, next(self._clock),
                                   salt)
                node.keys.append((digs[j - 1], blen))
                self._index[digs[j - 1]] = (node, blen)
                self._entries.append(node)
                self._node_parent[digs[j - 1]] = parent
                self._node_children[digs[j - 1]] = 0
                if parent is not None:
                    self._node_children[parent] += 1
                parent = digs[j - 1]
                created = True
            if created:
                self.insertions += 1
            return created

    # ------------------------------------------------- node-granular evict

    def _evict_entry_locked(self, victim: PrefixEntry) -> None:
        # leaf-only by construction (callers filter on children == 0):
        # no heir scan is ever needed — a boundary digest names exactly
        # one chain, and any other entry covering it would BE this node
        digest, _ = victim.keys[0]
        assert self._node_children.get(digest, 0) == 0, \
            "evicting a prefix node that still has children"
        self._entries.remove(victim)
        self._index.pop(digest, None)
        parent = self._node_parent.pop(digest, None)
        self._node_children.pop(digest, None)
        if parent is not None:
            self._node_children[parent] -= 1
        self.evictions += 1
        self._release_entry(victim)

    def _release_entry(self, victim: PrefixEntry) -> None:
        # one node owns exactly one reference: its own (deepest) block
        self.allocator.decref(victim.buffer[-1])
        self.blocks_released += 1
        if self._on_evict is not None:
            self._on_evict(1)

    def _leaves(self, stamp_before: Optional[int] = None
                ) -> List[PrefixEntry]:
        out = []
        for e in self._entries:
            if e.refs or self._node_children.get(e.keys[0][0], 0):
                continue
            if stamp_before is not None and e.stamp >= stamp_before:
                continue
            out.append(e)
        return out

    def _evict_to_budget_locked(self, headroom: int = 0,
                                stamp_before: Optional[int] = None
                                ) -> None:
        if not self.max_bytes:
            return
        while self.total_bytes + headroom > self.max_bytes:
            victims = self._leaves(stamp_before)
            if not victims:
                return  # everything pinned or interior; retry later
            self._evict_entry_locked(min(victims, key=lambda e: e.stamp))

    def evict_for(self, n_blocks: int) -> bool:
        """Block-pressure eviction: drop LRU unpinned leaf nodes until
        the allocator has gained ``n_blocks`` free blocks or nothing
        evictable remains.  Returns True when at least one node was
        dropped (the caller retries its allocation).  Note an evicted
        node frees its block only when no live slot shares it —
        reclaiming nothing from a still-shared block is normal, not a
        bug."""
        with self._lock:
            before = self.allocator.free_count
            progressed = False
            while self.allocator.free_count - before < n_blocks:
                victims = self._leaves()
                if not victims:
                    break
                self._evict_entry_locked(min(victims,
                                             key=lambda e: e.stamp))
                progressed = True
            return progressed

    # ---------------------------------------------------------- inspection

    @property
    def entry_count(self) -> int:
        """Distinct stored prefixes = chain leaves (nodes with no
        children).  Interior nodes are shared structure, not separately
        meaningful entries — a store holding one 4-block prefix counts
        1, matching the old whole-entry semantics."""
        with self._lock:
            return sum(1 for e in self._entries
                       if not self._node_children.get(e.keys[0][0], 0))

    def stats(self) -> Dict[str, int]:
        s = super().stats()
        with self._lock:
            s["entries"] = self.entry_count
            s["nodes"] = len(self._entries)
        return s
