"""Serving metrics, surfaced through the process ``Tracer``.

Same pattern as ``resilience/counters.py``: every observation bumps a
named monotonic counter and — when ``BYTEPS_TRACE_PATH`` is set — lands
on the shared chrome-trace timeline as a counter event (value track) so
batch occupancy, queue depth, and token throughput render next to the
engine's push/pull spans in Perfetto.  Per-request latency samples
(queue wait, TTFT, TPOT) are additionally kept in-process for the
``summary()`` percentiles the bench and the TCP STATS op report.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..common import logging as bps_log

# canonical counter names
SUBMITTED = "serve.requests_submitted"
ADMITTED = "serve.requests_admitted"
REJECTED = "serve.requests_rejected"
COMPLETED = "serve.requests_completed"
CANCELLED = "serve.requests_cancelled"
FAILED = "serve.requests_failed"
TOKENS = "serve.tokens_generated"
PREFILL_TOKENS = "serve.prefill_tokens"
# chunked prefill (serving/engine.py chunk>0): one bump per jitted
# chunk call — with PREFILL_TOKENS this gives padded tokens/chunk
PREFILL_CHUNKS = "serve.prefill_chunks"
# prefix-reuse KV cache (serving/prefix.py): lookup outcomes per
# admission and the tokens whose prefill was skipped by a device-side
# K/V copy (the FLOP saving PREFILL_TOKENS no longer contains)
PREFIX_HITS = "serve.prefix_hits"
PREFIX_MISSES = "serve.prefix_misses"
PREFIX_HIT_TOKENS = "serve.prefix_hit_tokens"
PREFIX_INSERTIONS = "serve.prefix_insertions"
# per-tick value tracks (gauges, not monotonic)
OCCUPANCY = "serve.batch_occupancy"
QUEUE_DEPTH = "serve.queue_depth"
# per-request latency tracks (milliseconds, one point per completion)
TTFT_MS = "serve.ttft_ms"
TPOT_MS = "serve.tpot_ms"
QUEUE_WAIT_MS = "serve.queue_wait_ms"


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[k]


class ServeMetrics:
    """Thread-safe serving counters + latency samples with Tracer
    surfacing."""

    def __init__(self, tracer=None):
        self._counts: Dict[str, int] = {}
        self._queue_wait: List[float] = []
        self._ttft: List[float] = []
        self._tpot: List[float] = []
        self._lock = threading.Lock()
        self._tracer = tracer

    def _get_tracer(self):
        if self._tracer is not None:
            return self._tracer
        from ..common.tracing import get_tracer

        return get_tracer()

    # ------------------------------------------------------------ counters

    def bump(self, counter: str, n: int = 1, **args) -> int:
        with self._lock:
            total = self._counts.get(counter, 0) + n
            self._counts[counter] = total
        tracer = self._get_tracer()
        if tracer.enabled:
            safe = {("tensor" if k == "name" else k): v
                    for k, v in args.items()}
            tracer.instant(counter, "serve", **safe)
            tracer.counter(counter, total, "serve")
        bps_log.debug("%s -> %d %s", counter, total, args or "")
        return total

    def gauge(self, name: str, value: float) -> None:
        """Non-monotonic value track (occupancy, queue depth)."""
        tracer = self._get_tracer()
        if tracer.enabled:
            tracer.counter(name, value, "serve")

    # --------------------------------------------------------- observations

    def observe_tick(self, occupancy: float, queue_depth: int,
                     tokens_emitted: int) -> None:
        if tokens_emitted:
            self.bump(TOKENS, tokens_emitted)
        self.gauge(OCCUPANCY, occupancy)
        self.gauge(QUEUE_DEPTH, queue_depth)

    def observe_request(self, queue_wait_s: float, ttft_s: float,
                        tpot_s: Optional[float], tokens: int) -> None:
        """Record one completed request's latency profile.  ``tpot_s``
        is None for single-token requests (no inter-token gaps)."""
        with self._lock:
            self._queue_wait.append(queue_wait_s)
            self._ttft.append(ttft_s)
            if tpot_s is not None:
                self._tpot.append(tpot_s)
        self.gauge(QUEUE_WAIT_MS, queue_wait_s * 1e3)
        self.gauge(TTFT_MS, ttft_s * 1e3)
        if tpot_s is not None:
            self.gauge(TPOT_MS, tpot_s * 1e3)
        self.bump(COMPLETED, tokens=tokens)

    # ------------------------------------------------------------ reporting

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def summary(self) -> Dict[str, object]:
        """Counters plus latency percentiles (seconds)."""
        with self._lock:
            counts = dict(self._counts)
            qw = sorted(self._queue_wait)
            ttft = sorted(self._ttft)
            tpot = sorted(self._tpot)
        out: Dict[str, object] = dict(counts)
        for label, vals in (("queue_wait", qw), ("ttft", ttft),
                            ("tpot", tpot)):
            out[f"{label}_p50_s"] = _percentile(vals, 50)
            out[f"{label}_p99_s"] = _percentile(vals, 99)
            out[f"{label}_n"] = len(vals)
        return out


_metrics: Optional[ServeMetrics] = None
_metrics_lock = threading.Lock()


def get_serve_metrics() -> ServeMetrics:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            _metrics = ServeMetrics()
        return _metrics


def reset_serve_metrics() -> None:
    global _metrics
    with _metrics_lock:
        _metrics = None
