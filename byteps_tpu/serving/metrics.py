"""Serving metrics, registry-backed with ``Tracer`` surfacing.

Same pattern as ``resilience/counters.py``: every observation lands in
a :class:`~byteps_tpu.observability.metrics.MetricsRegistry` (the
process-global one for ``get_serve_metrics()`` — what ``/metrics``,
``OP_STATS`` and the TCP STATS reply scrape live — or a private one per
standalone ``ServeMetrics()`` so benches count in isolation).  When
``BYTEPS_TRACE_PATH`` is set each bump also lands on the shared
chrome-trace timeline as a counter event (value track), so batch
occupancy, queue depth, and token throughput render next to the
engine's push/pull spans in Perfetto — unchanged from pre-registry
traces.  Per-request latency samples (queue wait, TTFT, TPOT) feed
bounded-reservoir registry histograms that back the ``summary()``
percentiles the bench and the TCP STATS op report.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..common import logging as bps_log
from ..observability.metrics import MetricsRegistry, get_registry

# canonical counter names
SUBMITTED = "serve.requests_submitted"
ADMITTED = "serve.requests_admitted"
REJECTED = "serve.requests_rejected"
COMPLETED = "serve.requests_completed"
CANCELLED = "serve.requests_cancelled"
FAILED = "serve.requests_failed"
TOKENS = "serve.tokens_generated"
PREFILL_TOKENS = "serve.prefill_tokens"
# chunked prefill (serving/engine.py chunk>0): one bump per jitted
# chunk call — with PREFILL_TOKENS this gives padded tokens/chunk
PREFILL_CHUNKS = "serve.prefill_chunks"
# prefix-reuse KV cache (serving/prefix.py): lookup outcomes per
# admission and the tokens whose prefill was skipped by a device-side
# K/V copy (the FLOP saving PREFILL_TOKENS no longer contains)
PREFIX_HITS = "serve.prefix_hits"
PREFIX_MISSES = "serve.prefix_misses"
PREFIX_HIT_TOKENS = "serve.prefix_hit_tokens"
PREFIX_INSERTIONS = "serve.prefix_insertions"
# paged KV cache (serving/blocks.py): live block-pool accounting
# (gauges, per tick) plus the pressure-path counters — prefix-entry
# evictions that released blocks, and requests preempted back to
# QUEUED when the pool ran dry mid-flight
KV_BLOCKS_FREE = "serve.kv_blocks_free"
KV_BLOCKS_USED = "serve.kv_blocks_used"
KV_BLOCKS_SHARED = "serve.kv_blocks_shared"
BLOCK_EVICTIONS = "serve.block_evictions"
PREEMPTIONS = "serve.preemptions"
# blocks the XLA gather fallback materialized into dense rows this
# tick (n_slots x high-water bucket, decode AND verify passes):
# GATHERED_BLOCKS * pool.block_bytes is the per-tick cache-stream copy
# the pos-capped gather shrinks and the fused kernel eliminates —
# bench_serve.py --paged reports the reduction (serve_paged_kernel)
GATHERED_BLOCKS = "serve.gathered_blocks"
# speculative decoding (serving/engine.py spec_k > 0, serving/spec.py):
# DECODE_TICKS counts ticks that ran a decode/verify forward (the
# denominator of tokens-per-tick — what speculation exists to raise);
# SPEC_* account the proposal economy.  PROPOSED counts tokens handed
# to the verifier, ACCEPTED the proposed tokens the model confirmed
# (extra tokens beyond the one-per-tick floor, BEFORE budget/eos
# truncation — the verifier's own yield), VERIFY_TICKS the ticks that
# ran the widened verify program instead of plain decode.  TOKENS
# stays emissions-only: accepted-but-never-emitted tokens (truncated
# at the request's budget or at EOS) are counted nowhere, so
# TPOT/tokens-per-tick cannot be skewed by work the client never saw.
DECODE_TICKS = "serve.decode_ticks"
SPEC_PROPOSED = "serve.spec_proposed_tokens"
SPEC_ACCEPTED = "serve.spec_accepted_tokens"
SPEC_VERIFY_TICKS = "serve.spec_verify_ticks"
# per-tick value tracks (gauges, not monotonic)
OCCUPANCY = "serve.batch_occupancy"
QUEUE_DEPTH = "serve.queue_depth"
# per-request latency tracks (milliseconds, one point per completion)
TTFT_MS = "serve.ttft_ms"
TPOT_MS = "serve.tpot_ms"
QUEUE_WAIT_MS = "serve.queue_wait_ms"
# per-request latency histograms (seconds — the summary()/scrape unit)
QUEUE_WAIT_S = "serve.queue_wait_s"
TTFT_S = "serve.ttft_s"
TPOT_S = "serve.tpot_s"
# live credit level of the prefill scheduler (padded tokens remaining)
PREFILL_CREDITS = "serve.prefill_credits"
# disaggregated prefill/decode (serving/disagg): KV blocks shipped from
# a prefill replica to its decode target over OP_KV_BLOCKS, the wire
# bytes they carried (payload only — framing overhead excluded so the
# counter divides into block_bytes exactly), and the per-request ship
# latency (park -> last ack, seconds) as a reservoir histogram
KV_BLOCKS_SHIPPED = "serve.kv_blocks_shipped"
KV_BLOCKS_SHIPPED_BYTES = "serve.kv_blocks_shipped_bytes"
SHIP_LATENCY_S = "serve.ship_latency_s"


class ServeMetrics:
    """Thread-safe serving counters + latency samples, registry-backed.

    ``registry=None`` builds a private registry (isolated counting —
    the semantics standalone instances always had); the
    ``get_serve_metrics()`` singleton binds the process-global registry
    so scrapes see the serving engine live."""

    _HIST = {"queue_wait": QUEUE_WAIT_S, "ttft": TTFT_S, "tpot": TPOT_S,
             "ship": SHIP_LATENCY_S}

    def __init__(self, tracer=None,
                 registry: Optional[MetricsRegistry] = None):
        self._registry = (registry if registry is not None
                          else MetricsRegistry(tracer=tracer))
        # bumped-through-this-instance names: snapshot()/summary() report
        # exactly this instance's series even on a shared registry
        self._names: Dict[str, None] = {}
        self._lock = threading.Lock()

    @property
    def registry(self) -> MetricsRegistry:
        return self._registry

    def _hist(self, label: str):
        return self._registry.histogram(self._HIST[label], track="serve")

    # ------------------------------------------------------------ counters

    def bump(self, counter: str, n: int = 1, **args) -> int:
        with self._lock:
            self._names.setdefault(counter, None)
        total = self._registry.counter(counter, track="serve").inc(n, **args)
        bps_log.debug("%s -> %d %s", counter, total, args or "")
        return total

    def gauge(self, name: str, value: float) -> None:
        """Non-monotonic value track (occupancy, queue depth, credit
        levels) — stored in the registry (live scrapes) AND mirrored to
        the Tracer value track as before."""
        self._registry.gauge(name, track="serve").set(value)

    # --------------------------------------------------------- observations

    def observe_tick(self, occupancy: float, queue_depth: int,
                     tokens_emitted: int) -> None:
        if tokens_emitted:
            self.bump(TOKENS, tokens_emitted)
        self.gauge(OCCUPANCY, occupancy)
        self.gauge(QUEUE_DEPTH, queue_depth)

    def observe_request(self, queue_wait_s: float, ttft_s: float,
                        tpot_s: Optional[float], tokens: int) -> None:
        """Record one completed request's latency profile.  ``tpot_s``
        is None for single-token requests (no inter-token gaps)."""
        self._hist("queue_wait").observe(queue_wait_s)
        self._hist("ttft").observe(ttft_s)
        if tpot_s is not None:
            self._hist("tpot").observe(tpot_s)
        self.gauge(QUEUE_WAIT_MS, queue_wait_s * 1e3)
        self.gauge(TTFT_MS, ttft_s * 1e3)
        if tpot_s is not None:
            self.gauge(TPOT_MS, tpot_s * 1e3)
        self.bump(COMPLETED, tokens=tokens)

    # ------------------------------------------------------------ reporting

    def get(self, name: str) -> int:
        m = self._registry.get(name)
        return m.value if m is not None else 0

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            names = list(self._names)
        return {n: self.get(n) for n in names}

    def summary(self) -> Dict[str, object]:
        """Counters plus latency percentiles (seconds)."""
        out: Dict[str, object] = dict(self.snapshot())
        for label in ("queue_wait", "ttft", "tpot", "ship"):
            h = self._hist(label)
            out[f"{label}_p50_s"] = h.percentile(50)
            out[f"{label}_p99_s"] = h.percentile(99)
            out[f"{label}_n"] = h.count
        return out


_metrics: Optional[ServeMetrics] = None
_metrics_lock = threading.Lock()


def get_serve_metrics() -> ServeMetrics:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            _metrics = ServeMetrics(registry=get_registry())
        return _metrics


def reset_serve_metrics() -> None:
    """Forget the singleton AND its counts.  The backing metrics live in
    the process-global registry, which outlives the singleton, so the
    ``serve.*`` namespace (counters, gauges, latency histograms) is
    removed explicitly — otherwise a rebuilt ``get_serve_metrics()``
    would report the previous run's totals and percentile samples."""
    global _metrics
    with _metrics_lock:
        inst, _metrics = _metrics, None
    if inst is not None:
        inst.registry.remove_prefix("serve.")
        for n in inst.snapshot():  # free-form names outside serve.*
            inst.registry.remove(n)
