"""Fault-tolerant serving router: health-checked replica failover with
deterministic request re-dispatch.

One ``ServingEngine`` is one slot pool on one machine; this tier fans
client traffic out over N ``ServeFrontend`` replicas while speaking the
SAME wire protocol clients already use (``frontend.py`` ops — a router
is indistinguishable from a big frontend).  Robustness is the headline
(docs/serving.md "Router tier"):

  * **Health-checked replicas.**  A :class:`resilience.FailureDetector`
    heartbeats every replica over the serve protocol (one-shot OP_PING
    round trips); replica-leg wire failures feed the same detector so
    death is noticed at traffic speed.  Replicas move through typed
    states: HEALTHY -> SUSPECT (missed pings / leg failures, still
    routable) -> DEAD (excluded, detector watches for recovery) and
    back (failback re-admission), or HEALTHY -> DRAINING (operator
    drain — no new placements, in-flight finishes, then retired).

  * **Deterministic re-dispatch.**  The router records every request's
    prompt and the tokens that crossed the wire so far.  When a replica
    dies mid-stream, the request is re-submitted to a survivor with the
    emitted prefix (``resume`` submits — engine.py ``resume_tokens``):
    the new replica re-prefills prompt + emitted (position-wise
    determinism rebuilds the exact K/V the dead replica's decode wrote
    — the PR 9 preempt/resume argument, one machine wider), restores
    the parked next-input token, and under sampling recomputes the
    carried key as the k-fold split chain of ``PRNGKey(seed)``.  The
    spliced stream is token-identical to a never-interrupted run —
    greedy by construction, seeded because the key state is a pure
    function of ``(seed, tokens emitted)``.  (If a future sampling
    scheme made key state non-derivable — external entropy, per-tick
    reseeding — resume would be inexact; the engine refuses resume
    loudly for the configs where bit-exactness already cannot hold:
    ``kv_quant`` and flash-prefill models.)

  * **Bounded, typed failure.**  Queued-but-unstarted requests retry
    transparently under :class:`resilience.RetryPolicy` backoff; every
    request carries a deadline, and when no replica can complete it in
    time it fails with the typed :class:`ReplicaLostError` — never a
    hang, never a silent drop.  Every wire read is timeout-bounded.

  * **Prefix-affinity placement.**  Requests are steered by a digest of
    the prompt's leading block (the rolling-hash discipline of
    serving/prefix.py), so shared-system-prompt traffic lands on the
    replica whose prefix cache is warm — SGLang-style cache-aware load
    balancing.  First placement of a prefix group is rendezvous-hashed
    (HRW: deterministic, stable under replica-set changes) and then
    sticky; dead primaries remap through the reused
    :class:`resilience.DegradedModeRouter` (the deterministic
    next-alive scan every PS worker already agrees on).

  * **Credit backpressure.**  Each replica holds ``credits`` in-flight
    requests; a full replica sheds to the next-best candidate instead
    of queueing blind, and total saturation becomes backoff-then-typed
    failure, not an unbounded queue.

  * **Router high availability** (docs/serving.md "Router HA").  The
    router itself must not be the tier's single point of failure.  A
    priority-ordered peer list (``BYTEPS_ROUTER_PEERS``) makes the
    lowest-priority-index live router the ACTIVE one; it streams a
    compact journal to the standbys over the serve wire
    (``OP_JOURNAL`` — serving/journal.py): affinity-map entries,
    replica health/fingerprint verdicts, and per-request in-flight
    records (id, seed, params, replica, emitted-token COUNT — the
    client holds the tokens).  Every dispatch to a replica carries a
    monotonic **epoch**; on active death (each standby runs a
    ``FailureDetector`` over the routers' own OP_PING) the
    highest-priority standby assumes the journaled state — warm
    affinity map, verified replicas, no cold re-probe storm — and
    bumps the epoch, so replicas FENCE the deposed epoch
    (``EpochFencedError``): a stale active that comes back is refused
    by the very replicas it tries to reach and demotes itself (it
    also demotes on a journal ack carrying a higher epoch).  Clients
    hold the multi-router address list and re-issue mid-stream with
    ``resume_tokens`` — token-identical by the resume argument, one
    tier higher.

  * **Per-tenant fair share.**  With ``tenant_weights`` configured
    (``BYTEPS_ROUTER_TENANT_WEIGHTS``), dispatch debits a per-tenant
    credit pool (the ``ScheduledQueue`` credit machinery) sized by
    weight over the tier's total credits, so one tenant flooding the
    router cannot starve another's share of in-flight capacity.

  * **Wire-level cancel.**  ``OP_CANCEL`` propagates
    client -> router -> replica: cancelling a routed request reclaims
    the replica's slot and paged KV blocks same-tick, not when the
    abandoned stream would have finished.

Metrics land on the PR 6 registry (``router.*``): per-replica state and
in-flight gauges, failover / redispatch / shed / retry counters, and
the affinity hit rate.  The launcher grows a ``router`` role
(``DMLC_ROLE=router``, knobs ``BYTEPS_ROUTER_*`` — docs/env.md).
"""

from __future__ import annotations

import collections
import enum
import hashlib
import itertools
import json
import re
import socketserver
import threading
import time
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..common import logging as bps_log
from ..common.scheduler import ScheduledQueue
from ..engine.ps_server import _decode, _encode
from ..engine.transport import maybe_nodelay
from ..engine.wire import hard_reset
from ..observability.metrics import MetricsRegistry, get_registry
from ..resilience.detector import FailureDetector
from ..resilience.policy import RetryPolicy
from ..resilience.router import DegradedModeRouter
from .autoscale.admission import (SLO_BEST_EFFORT, SLO_GUARANTEED,
                                  SLO_STANDARD, AdmissionController,
                                  Lease, OverloadShedError, TenantShares,
                                  normalize_slo)
from .frontend import (OP_CANCEL, OP_JOURNAL, OP_PING, OP_STATS,
                       OP_STREAM, OP_SUBMIT, RemoteServeClient,
                       ServeConnectionError, ServeReplyError,
                       _split_resume, _wire_cancel)
from .journal import JournalSender

__all__ = ["ReplicaState", "ReplicaLostError", "RouterStandbyError",
           "WeightsMismatchError", "ServeRouter", "RouterFrontend",
           "serve_router", "router_from_env"]

# ------------------------------------------------------------- metric names
REQUESTS = "router.requests"
COMPLETED = "router.requests_completed"
FAILED = "router.requests_failed"
# replica-leg wire failures (the request then re-dispatches or retries)
FAILOVERS = "router.failovers"
# re-dispatches that carried an emitted prefix (mid-stream failover)
REDISPATCHES = "router.redispatches"
# placements diverted off a full (or replica-side-rejecting) candidate
SHEDS = "router.sheds"
# backoff waits (no placeable replica / transient leg failure)
RETRIES = "router.retries"
AFFINITY_HITS = "router.affinity_hits"
AFFINITY_MISSES = "router.affinity_misses"
DRAINS = "router.drains"
# replicas refused placement because their STATS weights fingerprint
# disagrees with the tier's (resume across different checkpoints would
# be silently wrong — docs/serving.md "Router tier")
WEIGHTS_REFUSED = "router.weights_refused"
# labeled per-replica gauges
REPLICA_STATE = "router.replica_state"      # 0 healthy 1 suspect 2 dead
REPLICA_INFLIGHT = "router.replica_inflight"  # 3 draining/retired
# --- router HA (docs/serving.md "Router HA")
EPOCH = "router.epoch"                      # gauge: this router's epoch
TAKEOVERS = "router.takeovers"
# journaled in-flight records orphaned at takeover (the clients hold
# their tokens and re-issue with resume — the honest recovery window)
TAKEOVER_ORPHANS = "router.takeover_orphans"
DEMOTIONS = "router.demotions"
STANDBY_REFUSED = "router.standby_refused"
JOURNAL_SENT = "router.journal_entries_sent"
JOURNAL_APPLIED = "router.journal_entries_applied"
# --- wire-level cancel propagation
CANCELS = "router.cancels"
CANCELLED = "router.requests_cancelled"
# --- per-tenant fair share (labeled gauge: credits remaining)
TENANT_CREDITS = "router.tenant_credits"
# --- disaggregated prefill/decode (docs/serving.md "Disaggregated
# tiers"): prefill legs dispatched to the prefill tier, KV blocks
# their ships delivered, and legs that fell back to decode-side
# re-prefill (failed/partial ship or a dead prefill replica — the
# availability floor is the colocated path)
DISAGG_PREFILLS = "router.disagg_prefills"
DISAGG_SHIPPED_BLOCKS = "router.disagg_shipped_blocks"
DISAGG_FALLBACKS = "router.disagg_fallbacks"
# --- elastic capacity (docs/serving.md "Elastic capacity & SLO
# classes"): per-class door sheds (incl. clawed-back borrows), total
# credits borrowed across tenant pools, and journaled QUEUED requests
# the NEW active re-dispatched itself at takeover
SHED_GUARANTEED = "router.shed_guaranteed"
SHED_STANDARD = "router.shed_standard"
SHED_BEST_EFFORT = "router.shed_best_effort"
BORROWED_CREDITS = "router.borrowed_credits"
QUEUED_REDISPATCHES = "router.queued_redispatches"

_SHED_COUNTER = {SLO_GUARANTEED: SHED_GUARANTEED,
                 SLO_STANDARD: SHED_STANDARD,
                 SLO_BEST_EFFORT: SHED_BEST_EFFORT}

# journaled in-flight record fields ("p" — a still-QUEUED record's
# prompt — rides separately, only while r is None)
_JOURNAL_FIELDS = ("rid", "seed", "prio", "mnt", "tenant", "slo",
                   "r", "n", "st")


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"    # missed pings / leg failures; still routable
    DEAD = "dead"          # excluded; detector watches for failback
    DRAINING = "draining"  # no new placements; retires when empty


_STATE_GAUGE = {ReplicaState.HEALTHY: 0, ReplicaState.SUSPECT: 1,
                ReplicaState.DEAD: 2, ReplicaState.DRAINING: 3}


class ReplicaLostError(RuntimeError):
    """No replica could complete the request within its deadline: the
    serving tier lost the replica(s) serving it and ran out of retry
    budget.  ``emitted`` carries any tokens already streamed (the
    client saw them; they are valid — the sequence is just truncated)."""

    def __init__(self, msg: str, attempts: int = 0,
                 emitted: Sequence[int] = ()):
        self.attempts = attempts
        self.emitted = list(emitted)
        super().__init__(msg)


class RouterStandbyError(RuntimeError):
    """This router is a STANDBY (or a deposed active): it holds the
    journal but must not place traffic — only the epoch owner may
    dispatch, or two routers would split the affinity map and the
    in-flight bookkeeping (the exact failure HA exists to close).
    Typed AND client-retryable (``ServeReplyError.retryable``): a
    multi-router client rotates to the next address instead of failing
    the request."""


class WeightsMismatchError(RuntimeError):
    """A replica's STATS weights fingerprint disagrees with the tier's:
    it serves a different checkpoint, so a mid-stream re-dispatch onto
    it would splice a silently-wrong continuation.  Raised typed at
    registration (``ServeRouter.start``); at ping/failback time the
    replica is refused placement instead (it stays alive but never
    receives traffic until its fingerprint matches again)."""


class _Replica:
    __slots__ = ("idx", "addr", "role", "inflight", "suspect", "dead",
                 "draining", "retired", "refused", "verified")

    def __init__(self, idx: int, addr: str, role: str = "both"):
        self.idx = idx
        self.addr = addr
        # serving role (docs/serving.md "Disaggregated tiers"):
        # "prefill" replicas only ever receive prefill+ship legs,
        # "decode" / "both" replicas take normal placement ("both"
        # additionally runs its own prefill — the colocated default)
        self.role = role
        self.inflight = 0
        self.suspect = False
        self.dead = False
        self.draining = False
        self.retired = False
        # weights handshake: ``verified`` = fingerprint checked against
        # the tier's; ``refused`` = checked and DISAGREED (alive but
        # unplaceable until a later check matches — e.g. the operator
        # restarted it on the right checkpoint)
        self.refused = False
        self.verified = False

    @property
    def state(self) -> ReplicaState:
        if self.draining or self.retired:
            return ReplicaState.DRAINING
        if self.dead or self.refused:
            return ReplicaState.DEAD
        if self.suspect:
            return ReplicaState.SUSPECT
        return ReplicaState.HEALTHY

    @property
    def placeable(self) -> bool:
        return not (self.dead or self.draining or self.retired
                    or self.refused)


class ServeRouter:
    """Fan requests out over N serve replicas; see the module docstring
    for the failover / placement / backpressure contracts.

    ``registry=None`` binds the process-global metrics registry (what
    ``/metrics`` and the router's OP_STATS scrape); tests pass a
    private :class:`MetricsRegistry` to count in isolation.  Call
    :meth:`start` to run the heartbeat detector (per-request failover
    works without it — leg failures are detected at traffic speed —
    but only the detector takes a silent replica out of placement and
    re-admits it on recovery)."""

    def __init__(self, replicas: Sequence[str], *,
                 credits: int = 16,
                 affinity: bool = True,
                 affinity_block: int = 16,
                 deadline: float = 60.0,
                 stream_timeout: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 heartbeat_interval: float = 0.5,
                 miss_threshold: int = 3,
                 ping_timeout: float = 1.0,
                 registry: Optional[MetricsRegistry] = None,
                 expected_weights_fp: Optional[str] = None,
                 peers: Optional[Sequence[str]] = None,
                 self_addr: str = "",
                 epoch_timeout: float = 0.5,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 journal_every: int = 8,
                 roles: Optional[Sequence[str]] = None,
                 disagg: bool = True,
                 slo_default: str = SLO_STANDARD,
                 slo_deadlines: Optional[Dict[str, float]] = None,
                 service_estimate_s: float = 0.5,
                 slo_borrow: bool = True):
        if not replicas:
            raise ValueError(
                "ServeRouter needs at least one replica address "
                "(BYTEPS_ROUTER_REPLICAS=host:port,host:port)")
        self._replicas = [_Replica(i, a) for i, a in enumerate(replicas)]
        # ---- disaggregated tiers (docs/serving.md) -------------------
        # ``roles`` mirrors ``replicas`` positionally (BYTEPS_ROUTER_
        # ROLES=prefill,decode,...).  Omitted/empty = every replica is
        # "both" (colocated — today's behaviour, bit for bit).
        if roles:
            roles = [str(x).strip() for x in roles]
            if len(roles) != len(self._replicas):
                raise ValueError(
                    f"roles has {len(roles)} entries for "
                    f"{len(self._replicas)} replicas (BYTEPS_ROUTER_ROLES "
                    f"must mirror BYTEPS_ROUTER_REPLICAS positionally)")
            for r, role in zip(self._replicas, roles):
                if role not in ("prefill", "decode", "both"):
                    raise ValueError(
                        f"unknown replica role {role!r} (want prefill, "
                        f"decode, or both)")
                r.role = role
            if all(r.role == "prefill" for r in self._replicas):
                raise ValueError(
                    "every replica is prefill-role: at least one decode "
                    "or both replica must exist to run decode")
        # disaggregation is live only when the operator actually split
        # the pool; the flag (BYTEPS_DISAGG=0) force-colocates even then
        self._disagg = bool(disagg) and any(
            r.role == "prefill" for r in self._replicas)
        self.credits = max(1, credits)
        self.affinity = bool(affinity)
        self.affinity_block = max(1, affinity_block)
        self.deadline = deadline
        self.stream_timeout = stream_timeout
        # the policy paces attempts; the router's per-request deadline
        # is passed to should_retry as the bound (the policy's own
        # deadline field is unused here)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=6, backoff_base=0.05, backoff_mult=2.0,
            backoff_cap=1.0, jitter=0.1, deadline=0.0)
        self.ping_timeout = ping_timeout
        self._degraded = DegradedModeRouter(len(self._replicas))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)  # drain waits here
        # prefix-group digest -> replica idx (sticky placements),
        # LRU-bounded so a long-tailed prompt population cannot grow it
        # without bound
        self._affinity_map: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._affinity_cap = 4096
        self._rr = itertools.count()
        self._registry = registry if registry is not None else get_registry()
        self._detector = FailureDetector(
            len(self._replicas), self._ping_replica,
            interval=heartbeat_interval, miss_threshold=miss_threshold,
            on_down=self._on_replica_down, on_up=self._on_replica_up)
        for r in self._replicas:
            self._gauge_state(r)

        # the tier's weights anchor.  Default: first-verified-wins —
        # the first fingerprint a replica proves becomes the tier's.
        # ``expected_weights_fp`` (BYTEPS_ROUTER_WEIGHTS_FP) lets the
        # operator PIN the anchor instead: WHICH checkpoint wins is
        # then an explicit deployment decision, not an accident of
        # which replica registered first, and a replica that cannot
        # prove the pinned fingerprint (including pre-handshake builds
        # that report none) is refused placement.
        self._expected_fp: Optional[str] = expected_weights_fp or None
        self._fp_pinned = bool(expected_weights_fp)

        # ---- router HA (docs/serving.md "Router HA") -----------------
        # ``peers`` is the PRIORITY-ORDERED router address list (index
        # 0 = initially active); ``self_addr`` names this router in it.
        # Without peers the router is a plain single active (epoch 1 —
        # still stamped on dispatches, so replicas always fence).
        self.peers = ([p.strip() for p in peers if p.strip()]
                      if peers else [])
        self.self_addr = self_addr
        if self.peers:
            if self_addr not in self.peers:
                raise ValueError(
                    f"self_addr {self_addr!r} must appear in the peer "
                    f"list {self.peers} (BYTEPS_ROUTER_SELF names this "
                    f"router's own entry in BYTEPS_ROUTER_PEERS)")
            self._self_idx = self.peers.index(self_addr)
        else:
            self._self_idx = 0
        self.epoch_timeout = epoch_timeout
        self.journal_every = max(1, journal_every)
        self._active = self._self_idx == 0
        self.epoch = 1 if self._active else 0
        self._journal_epoch = 0   # highest epoch seen in the journal
        # peer index of the current epoch owner, as far as we know
        self._active_peer: Optional[int] = (0 if self.peers else None)
        self._promoting = False
        self._killed = False
        # standby-side journal state: per-request in-flight records
        # (bounded — the takeover contract tolerates loss; clients
        # hold the tokens)
        self._journal_inflight: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        # active-side live dispatch records (rid -> record) + cancel
        # tombstones for OP_CANCELs racing their own submit
        self._inflight: Dict[str, dict] = {}
        self._cancel_tombs: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        # recently-FINISHED rids (bounded): a too-late cancel must not
        # be tombstoned — the tombstone would cancel the next request
        # reusing the rid at admission (mirrors ServeFrontend._rid_done)
        self._rid_done: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        self._rid_seq = itertools.count()
        self._journal: Optional[JournalSender] = None
        if self.peers:
            self._journal = JournalSender(
                [p for p in self.peers if p != self_addr],
                timeout=ping_timeout, epoch_of=lambda: self.epoch,
                on_stale=self._demote,
                snapshot_fn=self._journal_snapshot)
        self._peer_detector: Optional[FailureDetector] = None
        if len(self.peers) > 1:
            self._peer_detector = FailureDetector(
                len(self.peers), self._ping_peer,
                interval=heartbeat_interval,
                miss_threshold=miss_threshold,
                on_down=lambda i: self._maybe_takeover())
        self._registry.gauge(EPOCH, track="router").set(self.epoch)

        # ---- per-tenant fair share -----------------------------------
        # weight -> a ScheduledQueue credit pool sized as this tenant's
        # share of the tier's total in-flight credits; tenants not
        # named in the config (and untagged requests) share the
        # "default" bucket.  Strict reservation, deliberately NOT
        # work-conserving: a flooding tenant is bounded by its share
        # even when others are idle (the starvation guard is the
        # contract; docs/serving.md "Per-tenant fair share").
        self.tenant_weights: Dict[str, float] = dict(tenant_weights or {})
        self._tenant_pools: Dict[str, ScheduledQueue] = {}
        if self.tenant_weights:
            buckets = dict(self.tenant_weights)
            buckets.setdefault("default", 1.0)
            for t, w in buckets.items():
                if w <= 0:
                    raise ValueError(
                        f"tenant weight must be > 0, got {t}={w}")
            cap = self.credits * len(self._replicas)
            if cap < len(buckets):
                raise ValueError(
                    f"tenant fair share needs at least one credit per "
                    f"bucket: {len(buckets)} buckets (incl. 'default') "
                    f"but the tier only has {cap} credits "
                    f"(credits x replicas)")
            # largest-remainder apportionment: the pools sum EXACTLY to
            # the tier's total credits (the documented invariant —
            # naive per-bucket rounding can over-admit past the tier
            # cap and flatten configured ratios), then a 1-credit floor
            # funded by the largest shares so no tenant is configured
            # into permanent starvation
            total_w = sum(buckets.values())
            raw = {t: cap * w / total_w for t, w in buckets.items()}
            share = {t: int(raw[t]) for t in buckets}
            order = sorted(buckets, key=lambda t: raw[t] - share[t],
                           reverse=True)
            for t in order[:cap - sum(share.values())]:
                share[t] += 1
            while min(share.values()) == 0:
                share[min(share, key=share.get)] += 1
                share[max(share, key=share.get)] -= 1
            for t in buckets:
                self._tenant_pools[t] = ScheduledQueue(
                    scheduled=True, credit_bytes=share[t],
                    name=f"router.tenant.{t}")
                self._gauge_tenant(t)

        # ---- SLO admission + work-conserving shares ------------------
        # (docs/serving.md "Elastic capacity & SLO classes"): classes
        # shed at the door when the estimated queue wait blows their
        # deadline, and the strict tenant pools above become a FLOOR —
        # idle credits are lent across tenants and clawed back on
        # demand (TenantShares).
        self.slo_default = normalize_slo(slo_default)
        self._admission = AdmissionController(
            deadlines=slo_deadlines,
            service_estimate_s=service_estimate_s)
        self._shares = TenantShares(self._tenant_pools,
                                    borrow=slo_borrow,
                                    on_borrow=self._on_borrow)
        # takeover re-dispatch: rid -> parked token buffer the client's
        # retry attaches to (bounded — see _park_redispatch)
        self._parked: Dict[str, dict] = {}
        self._parked_cv = threading.Condition()
        # the scale intent (k="scale" journal entry) currently open —
        # kept on the active AND folded on standbys, so a takeover
        # mid-scale reconciles it instead of orphaning the spawn
        self._pending_scale: Optional[dict] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ServeRouter":
        """Run the registration handshake, then the heartbeat detector.

        Registration compares every reachable replica's STATS weights
        fingerprint (the same digest the prefix-store salt commits to —
        serving/prefix.py ``weights_fingerprint``): the first fingerprint
        seen becomes the tier's — unless the operator pinned the anchor
        via ``expected_weights_fp`` (BYTEPS_ROUTER_WEIGHTS_FP), in which
        case every replica must prove THAT checkpoint — and a
        disagreeing replica raises the typed
        :class:`WeightsMismatchError`: refusing to build a tier
        whose failover re-dispatch would splice tokens from different
        checkpoints.  Replicas unreachable right now are re-checked on
        their first successful ping and at failback.

        A STANDBY router starts only its peer detector: replica health
        and weights verdicts arrive through the journal, so takeover
        needs no registration round and no cold re-probe storm."""
        if self._peer_detector is not None:
            self._peer_detector.start()
        if self._journal is not None:
            self._journal.start()
        if not self._active:
            return self
        for r in self._replicas:
            self._verify_replica_weights(r, raising=True)
        self._detector.start()
        self._jpub(k="hello")
        for r in self._replicas:
            self._jpub_replica(r)
        return self

    def close(self) -> None:
        self._detector.stop()
        if self._peer_detector is not None:
            self._peer_detector.stop()
        if self._journal is not None:
            self._journal.close()

    def kill(self) -> None:
        """Crash semantics (chaos): journaling stops IMMEDIATELY —
        in-flight "done" entries and queued state never reach the
        standbys, exactly like a crashed process — then the detectors
        come down.  The standby's takeover must recover the orphaned
        records from client ``resume_tokens``, which is the honest
        window the docs promise."""
        self._killed = True
        if self._journal is not None:
            self._journal.kill()
        self.close()

    # --------------------------------------------------------- HA: journal

    @property
    def active(self) -> bool:
        return self._active

    def _jpub(self, **ent) -> None:
        """Publish one journal entry to the standbys (active only —
        a standby publishing would be the split brain itself)."""
        if self._journal is None or not self._active or self._killed:
            return
        ent["e"] = self.epoch
        ent["src"] = self._self_idx
        self._journal.publish(ent)
        self._bump(JOURNAL_SENT)

    def _jpub_replica(self, r: _Replica) -> None:
        self._jpub(**self._replica_entry(r))

    def _replica_entry(self, r: _Replica) -> dict:
        # the address rides along so standbys can APPEND replicas the
        # active scaled up at runtime (add_replica), not just fold
        # verdicts for a roster they already share
        return {"k": "replica", "r": r.idx, "addr": r.addr,
                "role": r.role, "dead": r.dead,
                "refused": r.refused, "verified": r.verified,
                "draining": r.draining or r.retired,
                "fp": self._expected_fp}

    def _journal_snapshot(self) -> List[dict]:
        """Full-state dump for a peer (re)connect: epoch hello, every
        replica's verdict, the whole affinity map, and the live
        in-flight records — a standby that booted late (or dropped and
        returned) warms up to the same state incremental entries would
        have built."""
        if not self._active or self._killed:
            return []
        with self._lock:
            ents: List[dict] = [{"k": "hello"}]
            ents.extend(self._replica_entry(r) for r in self._replicas)
            ents.extend({"k": "affinity", "d": d.hex(), "r": i}
                        for d, i in self._affinity_map.items())
            for rec in self._inflight.values():
                ent = {"k": "inflight",
                       **{f: rec[f] for f in _JOURNAL_FIELDS}}
                if rec.get("r") is None and "p" in rec:
                    # QUEUED records carry their prompt: a takeover
                    # re-dispatches them instead of orphaning them
                    ent["p"] = rec["p"]
                ents.append(ent)
            if self._pending_scale is not None:
                ents.append(dict(self._pending_scale))
            for ent in ents:
                ent["e"] = self.epoch
                ent["src"] = self._self_idx
        return ents

    def apply_journal(self, entries: Sequence[dict]) -> Dict[str, int]:
        """Standby side: fold a journal batch into local state.  The
        ack carries OUR epoch — an active sender seeing a higher one
        knows it was deposed and demotes.  Entries from an epoch lower
        than the highest already seen are stale (a deposed active
        still flushing its queue) and are ignored."""
        # deposed-discovery first, OUTSIDE the state lock (_demote
        # takes it): one pass over the batch's epochs, not per-entry
        if self._active:
            newest = max((int(ent.get("e", 0)) for ent in entries),
                         default=0)
            if newest > self.epoch:
                # a peer owns a NEWER epoch: we are deposed
                self._demote(newest)
        applied = 0
        # one lock hold for the whole batch (a reconnect snapshot can
        # carry thousands of entries — per-entry acquire/release would
        # churn against the dispatch path for no isolation gain)
        with self._lock:
            for ent in entries:
                if self._active:
                    continue  # stale sender; the ack will demote it
                e = int(ent.get("e", 0))
                if e < self._journal_epoch:
                    continue
                self._journal_epoch = e
                if ent.get("src") is not None:
                    self._active_peer = int(ent["src"])
                k = ent.get("k")
                if k == "affinity":
                    d = bytes.fromhex(ent["d"])
                    self._affinity_map[d] = int(ent["r"])
                    self._affinity_map.move_to_end(d)
                    while len(self._affinity_map) > self._affinity_cap:
                        self._affinity_map.popitem(last=False)
                elif k == "inflight":
                    rid = str(ent["rid"])
                    old = self._journal_inflight.get(rid)
                    # MERGE, don't replace: the queued record's prompt
                    # ("p") arrives once at admission — a later count
                    # update must not erase it
                    self._journal_inflight[rid] = (
                        {**old, **ent} if old else ent)
                    self._journal_inflight.move_to_end(rid)
                    while len(self._journal_inflight) > 4096:
                        self._journal_inflight.popitem(last=False)
                elif k == "done":
                    self._journal_inflight.pop(str(ent["rid"]), None)
                elif k == "scale":
                    self._pending_scale = (
                        None if ent.get("phase") in ("done", "abort")
                        else dict(ent))
                elif k == "replica":
                    i = int(ent["r"])
                    if i == len(self._replicas) and ent.get("addr"):
                        # a replica the active scaled UP at runtime
                        # (add_replica journals the address): append it
                        # so a takeover owns the grown tier
                        self._replicas.append(_Replica(
                            i, str(ent["addr"]),
                            str(ent.get("role") or "both")))
                        self._detector.grow(1)
                        self._degraded.grow(1)
                    if 0 <= i < len(self._replicas):
                        r = self._replicas[i]
                        r.dead = bool(ent.get("dead"))
                        r.suspect = False
                        r.refused = bool(ent.get("refused"))
                        r.verified = bool(ent.get("verified"))
                        if bool(ent.get("draining")):
                            r.draining = True
                        if r.dead:
                            self._degraded.mark_down(i)
                        else:
                            self._degraded.mark_up(i)
                        if ent.get("fp") and not self._fp_pinned:
                            self._expected_fp = str(ent["fp"])
                        self._gauge_state(r)
                # k == "hello": epoch/src bookkeeping above is the point
                applied += 1
            # ack epoch computed while the batch's epoch bump is still
            # pinned under the lock: read after release, a concurrent
            # batch could promote _journal_epoch between our last apply
            # and the read, acking an epoch whose entries we never
            # folded (the lock-unguarded-field lint finding here)
            ack = max(self.epoch, self._journal_epoch)
        if applied:
            self._bump(JOURNAL_APPLIED, applied)
        return {"epoch": ack}

    # -------------------------------------------------- HA: role movement

    def _ping_peer(self, idx: int) -> bool:
        if idx == self._self_idx:
            return True
        ok = False
        try:
            c = RemoteServeClient(self.peers[idx],
                                  timeout=self.ping_timeout)
            try:
                ok = c.ping()
            finally:
                c.close()
        except (OSError, ValueError):
            ok = False
        if not ok and not self._active:
            # the detector only fires on_down on the TRANSITION; an
            # aborted takeover (grace re-ping briefly succeeded) must
            # re-arm while the blockers stay dead
            self._maybe_takeover()
        return ok

    def _takeover_blockers(self) -> Set[int]:
        """Peers that must ALL be dead before this router may assume
        the epoch: every higher-priority peer, plus the current epoch
        owner wherever it sits — determinism: for any set of live
        routers exactly one satisfies this.  A router DEPOSED by an
        owner it cannot name yet (_active_peer == -1: fenced before
        the new active's journal reconnected) must treat every other
        peer as a blocker — it KNOWS a higher epoch lives somewhere,
        so promoting while any peer is up risks seizing the epoch
        from the live active it just lost to."""
        need = set(range(self._self_idx))
        if self._active_peer is not None and self._active_peer < 0:
            need.update(j for j in range(len(self.peers))
                        if j != self._self_idx)
        elif (self._active_peer is not None
                and self._active_peer != self._self_idx):
            need.add(self._active_peer)
        return need

    def _maybe_takeover(self) -> None:
        if self._active or self._peer_detector is None:
            return
        blockers = self._takeover_blockers()
        if any(self._peer_detector.is_up(j) for j in blockers):
            return
        with self._lock:
            if self._active or self._promoting:
                return
            self._promoting = True
        threading.Thread(target=self._takeover_after_grace,
                         daemon=True).start()

    def _takeover_after_grace(self) -> None:
        """The epoch-timeout grace window: a transiently-stalled active
        must not trigger a takeover it would immediately fence.  After
        the wait every blocker is re-pinged directly — only when all
        are STILL dead does this router assume the epoch."""
        try:
            time.sleep(self.epoch_timeout)
            for j in sorted(self._takeover_blockers()):
                if self._ping_peer(j):
                    return  # active (or a better-priority peer) lives
            self._become_active()
        finally:
            with self._lock:
                self._promoting = False

    def _become_active(self) -> None:
        with self._lock:
            if self._active:
                return
            # the floor of 1 matters: a takeover epoch must be
            # STRICTLY greater than any epoch a router can BOOT with
            # (index 0 boots at 1).  Without it, a standby that never
            # received a journal entry would take over at epoch 1 and
            # a stalled-but-alive old active would never be fenced
            # (equal epochs pass) — permanent split brain.  With the
            # snapshot-on-connect warmup the journal epoch is normally
            # known anyway; this closes the cold-standby window.
            self.epoch = max(self.epoch, self._journal_epoch, 1) + 1
            self._journal_epoch = self.epoch
            self._active = True
            self._active_peer = self._self_idx
            # journaled in-flight records split two ways: QUEUED ones
            # (never placed, emitted nothing, prompt journaled) are
            # re-dispatched by US — the client's retry attaches to the
            # parked stream by rid; records that already reached a
            # replica stay orphans (their clients hold the tokens and
            # re-issue with resume — the honest recovery window)
            requeue: List[dict] = []
            orphans = 0
            for ent in self._journal_inflight.values():
                if (ent.get("r") is None and not ent.get("n")
                        and ent.get("p")
                        and len(requeue) < self._parked_cap):
                    requeue.append(dict(ent))
                else:
                    orphans += 1
            self._journal_inflight.clear()
        self._bump(TAKEOVERS)
        if orphans:
            self._bump(TAKEOVER_ORPHANS, orphans)
        self._registry.gauge(EPOCH, track="router").set(self.epoch)
        # the journaled verdicts ARE the warm state: verified replicas
        # stay verified (no registration storm), dead ones stay out of
        # placement until the detector — started here — re-admits them
        self._detector.start()
        self._jpub(k="hello")
        for r in self._replicas:
            self._jpub_replica(r)
        for ent in requeue:
            self._park_redispatch(ent)
        bps_log.warning(
            "router %s: TAKEOVER — assuming epoch %d with %d journaled "
            "affinity group(s), %d queued request(s) re-dispatched, "
            "%d orphaned in-flight record(s) (clients recover them "
            "via resume_tokens)",
            self.self_addr or self._self_idx, self.epoch,
            len(self._affinity_map), len(requeue), orphans)

    def _demote(self, higher_epoch: int) -> None:
        """A higher epoch exists (journal ack, incoming journal, or a
        replica's EpochFencedError): this router is deposed.  It keeps
        its journal state and its detectors — it is now a standby that
        may take over again if the whole newer chain dies."""
        with self._lock:
            self._journal_epoch = max(self._journal_epoch, higher_epoch)
            if not self._active:
                return
            self._active = False
            # the epoch owner is SOMEONE ELSE now, identity unknown
            # until their journal names it (-1 sentinel, distinct from
            # the boot-time None): leaving _active_peer at self would
            # make our own blocker set empty and re-promote us over
            # the live active on the next peer-down transition
            self._active_peer = -1
        self._bump(DEMOTIONS)
        bps_log.warning(
            "router %s: DEMOTED — epoch %d fenced by epoch %d; "
            "standing by", self.self_addr or self._self_idx,
            self.epoch, higher_epoch)

    # ------------------------------------------------- HA: cancel registry

    def cancel(self, rid: str) -> bool:
        """Wire-cancel propagation (OP_CANCEL): mark the in-flight
        record cancelled — the dispatch loop stops re-dispatching it —
        and forward the cancel to the replica currently serving it so
        the slot and paged KV blocks reclaim same-tick.  Unknown rids
        are tombstoned (bounded) to absorb a cancel racing its own
        submit.  A STANDBY refuses typed (client-retryable) instead of
        tombstoning: it has no in-flight records, so a False here would
        read as "already finished" while the active router's leg keeps
        generating."""
        rid = str(rid)
        if not self._active:
            self._bump(STANDBY_REFUSED)
            raise RouterStandbyError(
                f"router {self.self_addr or self._self_idx} is standby "
                f"(epoch owner: peer {self._active_peer}); cancel via "
                f"the active router")
        with self._lock:
            rec = self._inflight.get(rid)
            if rec is None:
                if rid not in self._rid_done:
                    # too EARLY (racing its own submit): tombstone.  A
                    # recently-finished rid is too LATE — tombstoning
                    # it would cancel the rid's next reuse
                    self._cancel_tombs[rid] = None
                    while len(self._cancel_tombs) > 1024:
                        self._cancel_tombs.popitem(last=False)
                return False
            rec["cancelled"] = True
            ridx = rec.get("r")
            addr = (self._replicas[ridx].addr
                    if ridx is not None else None)
        self._bump(CANCELS)
        if addr is None:
            # not dispatched yet: the cancelled flag drops it before
            # any replica leg is placed
            return True
        try:
            # one fresh connection (a RemoteServeClient would eagerly
            # open a second, unused one just to be constructed)
            _wire_cancel(addr, {"rid": rid, "epoch": self.epoch},
                         self.ping_timeout)
        except ServeReplyError as e:
            if e.name == "EpochFencedError":
                # the replica is ALIVE and refusing our epoch: a newer
                # active owns this request now and its leg keeps
                # driving the replica — claiming "cancelled" would lie
                # to the client.  Demote and report failure; the client
                # re-issues the cancel to the new active.
                m = re.search(r"high-water (\d+)", str(e))
                self._demote(int(m.group(1)) if m else self.epoch)
                return False
            bps_log.debug("router cancel: replica %s refused (%s)",
                          addr, e)
        except (OSError, RuntimeError) as e:
            # replica unreachable / leg already dead: leg death
            # reclaims the slot on its own and the cancelled record
            # stops re-dispatch — only the eager reclaim is lost
            bps_log.debug("router cancel: replica %s unreachable "
                          "(%s)", addr, e)
        return True

    def _gauge_tenant(self, tenant: str) -> None:
        self._registry.gauge(TENANT_CREDITS, track="router",
                             tenant=tenant).set(
            self._tenant_pools[tenant].credits)

    def _on_borrow(self, tenant: str, lender: str) -> None:
        self._bump(BORROWED_CREDITS)
        self._gauge_tenant(lender)

    # -------------------------------------------------------------- metrics

    def _bump(self, name: str, n: int = 1) -> None:
        self._registry.counter(name, track="router").inc(n)

    def _gauge_state(self, r: _Replica) -> None:
        self._registry.gauge(REPLICA_STATE, track="router",
                             replica=r.idx, role=r.role
                             ).set(_STATE_GAUGE[r.state])

    def _gauge_inflight(self, r: _Replica) -> None:
        self._registry.gauge(REPLICA_INFLIGHT, track="router",
                             replica=r.idx, role=r.role).set(r.inflight)

    # --------------------------------------------------------------- health

    def _verify_replica_weights(self, r: _Replica, *,
                                raising: bool) -> bool:
        """Weights handshake against one replica: fetch its STATS
        fingerprint and compare with the tier's (the first fingerprint
        seen).  A mismatch marks the replica REFUSED — alive, heartbeat-
        tracked, but never placed — and raises the typed
        :class:`WeightsMismatchError` when ``raising`` (registration
        path).  A later matching check (operator restarted it on the
        right checkpoint) clears the refusal.  Replicas that do not
        report a fingerprint (pre-handshake builds) are accepted — the
        operator-guarantees-homogeneity contract they were deployed
        under.  Returns True when the replica is verified placeable."""
        try:
            c = RemoteServeClient(r.addr, timeout=self.ping_timeout)
            try:
                fp = c.stats().get("weights_fingerprint")
            finally:
                c.close()
        except (OSError, ValueError, RuntimeError):
            return False  # unreachable: re-checked at ping/failback
        with self._lock:
            if fp is None and not self._fp_pinned:
                # no fingerprint, no pin: the operator-guarantees-
                # homogeneity contract pre-handshake builds were
                # deployed under
                r.verified = True
                r.refused = False
                self._jpub_replica(r)
                return True
            if fp is not None:
                if self._expected_fp is None:
                    self._expected_fp = fp
                if fp == self._expected_fp:
                    r.verified = True
                    r.refused = False
                    self._jpub_replica(r)
                    return True
            first_refusal = not r.refused
            r.refused = True
            r.verified = True
            self._jpub_replica(r)
            # snapshot the tier anchor for the messages below while
            # still holding _lock: a journal batch can overwrite
            # _expected_fp between release and the read, and the
            # refusal must name the anchor it was judged against
            # (lock-unguarded-field lint finding)
            expected_fp = self._expected_fp
        if first_refusal:
            self._bump(WEIGHTS_REFUSED)
        self._gauge_state(r)
        if fp is None:
            msg = (f"replica {r.idx} ({r.addr}) reports no weights "
                   f"fingerprint but the operator pinned "
                   f"BYTEPS_ROUTER_WEIGHTS_FP="
                   f"{expected_fp[:16]}...: refusing placement — "
                   f"an unverifiable replica cannot prove it serves "
                   f"the pinned checkpoint.")
        else:
            msg = (f"replica {r.idx} ({r.addr}) serves different "
                   f"weights (fingerprint {fp[:16]}... != "
                   f"{'pinned' if self._fp_pinned else 'tier'} "
                   f"{expected_fp[:16]}...): refusing placement "
                   f"— a mid-stream re-dispatch onto it would splice "
                   f"a silently-wrong continuation.  Restart it on "
                   f"the tier's checkpoint to re-admit it.")
        if raising:
            raise WeightsMismatchError(msg)
        bps_log.warning("router: %s", msg)
        return False

    def _ping_replica(self, idx: int) -> bool:
        """Serve-protocol liveness probe: one short-timeout OP_PING
        round trip on a fresh connection (never contends with data
        legs).  Drives the detector's suspect/dead transitions.  Also
        the retry path of the weights handshake: an alive replica that
        was unreachable at registration (or refused since) re-verifies
        here, so fixing its checkpoint re-admits it within a ping
        interval."""
        r = self._replicas[idx]
        ok = False
        try:
            c = RemoteServeClient(r.addr, timeout=self.ping_timeout)
            try:
                ok = c.ping()
            finally:
                c.close()
        except (OSError, ValueError):
            ok = False
        if ok:
            r.suspect = False
            if not r.verified or r.refused:
                self._verify_replica_weights(r, raising=False)
        elif not r.dead:
            r.suspect = True
        self._gauge_state(r)
        return ok

    def _on_replica_down(self, idx: int) -> None:
        r = self._replicas[idx]
        r.dead, r.suspect = True, False
        # a dead replica's identity is stale the moment it dies: the
        # operator may restart it on a different checkpoint, and a
        # transiently-failing failback re-check must not leave a stale
        # verified=True letting it back in unchecked — clear it so the
        # failback/ping/dispatch paths all re-verify until a STATS
        # fetch actually succeeds
        r.verified = False
        self._degraded.mark_down(idx)
        self._gauge_state(r)
        self._jpub_replica(r)
        bps_log.warning("router: replica %d (%s) DEAD", idx, r.addr)

    def _on_replica_up(self, idx: int) -> None:
        r = self._replicas[idx]
        if r.draining or r.retired:
            return  # drained replicas never re-enter placement
        r.dead = r.suspect = False
        self._degraded.mark_up(idx)
        # failback handshake: a replica that went away and came back may
        # have restarted on a different checkpoint — it must prove its
        # weights before placement resumes (a mismatch leaves it alive
        # but refused; matching again later re-admits it)
        self._verify_replica_weights(r, raising=False)
        self._gauge_state(r)
        self._jpub_replica(r)
        if r.refused:
            return
        bps_log.warning("router: replica %d (%s) re-admitted (failback)",
                        idx, r.addr)

    def _note_leg_failure(self, r: _Replica) -> None:
        """A data leg to ``r`` died: feed the detector (detection at
        traffic speed, not ping cadence) and mark the replica suspect
        until a ping succeeds."""
        if not r.dead:
            r.suspect = True
            self._gauge_state(r)
        self._detector.report_failure(r.idx)

    # ------------------------------------------------------------ placement

    def _digest(self, prompt: np.ndarray) -> bytes:
        """Prefix-group key: digest of the prompt's leading affinity
        block (shorter prompts digest whole) — the rolling-block-hash
        discipline of serving/prefix.py, truncated to the one block
        that defines a shared-system-prompt group."""
        toks = np.ascontiguousarray(prompt[:self.affinity_block])
        return hashlib.blake2b(toks.tobytes(), digest_size=16).digest()

    def _hrw_order(self, digest: bytes) -> List[int]:
        """Rendezvous (highest-random-weight) order of ALL replicas for
        this prefix group: deterministic, and stable under replica-set
        changes (a dead replica's groups re-home without reshuffling
        everyone else's)."""
        scored = sorted(
            (hashlib.blake2b(digest + r.addr.encode(),
                             digest_size=8).digest(), r.idx)
            for r in self._replicas)
        return [idx for _, idx in reversed(scored)]

    def _acquire(self, digest: bytes,
                 tried: Set[int]) -> Optional[_Replica]:
        """Pick a replica for this request and take one credit.  None =
        nothing placeable right now (dead / draining / full / already
        tried this round) — the caller backs off and retries.

        Candidate order: the sticky affinity target (or the rendezvous
        winner) first — remapped around dead replicas by the reused
        ``DegradedModeRouter`` scan — then the remaining rendezvous
        order; round-robin mode replaces the whole ranking with a
        rotating scan."""
        with self._lock:
            n = len(self._replicas)
            mapped = (self._affinity_map.get(digest)
                      if self.affinity else None)
            if self.affinity:
                order = self._hrw_order(digest)
                primary = mapped if mapped is not None else order[0]
                try:
                    first = self._degraded.route(primary)
                except RuntimeError:
                    first = primary  # every replica down: scan anyway
                cands = [first] + [i for i in order if i != first]
            else:
                start = next(self._rr) % n
                cands = [(start + j) % n for j in range(n)]
            preferred = cands[0]
            preferred_full = False
            for idx in cands:
                r = self._replicas[idx]
                # prefill-role replicas never take normal placement:
                # they only ever see the prefill+ship leg
                if idx in tried or not r.placeable or r.role == "prefill":
                    continue
                if r.inflight >= self.credits:
                    if idx == preferred:
                        preferred_full = True
                    continue
                r.inflight += 1
                self._gauge_inflight(r)
                if self.affinity:
                    if mapped == idx:
                        self._bump(AFFINITY_HITS)
                    else:
                        self._bump(AFFINITY_MISSES)
                    # stickiness survives a transient shed: re-home the
                    # group only when it has no home or its home is
                    # gone (dead/draining) — one credit-full blip must
                    # not move every later request off the warm cache
                    if (mapped is None
                            or not self._replicas[mapped].placeable):
                        self._affinity_map[digest] = idx
                        # warm placements must survive a takeover:
                        # replicate the group -> replica binding
                        self._jpub(k="affinity", d=digest.hex(), r=idx)
                        while (len(self._affinity_map)
                                > self._affinity_cap):
                            self._affinity_map.popitem(last=False)
                    if digest in self._affinity_map:
                        self._affinity_map.move_to_end(digest)
                if preferred_full:
                    # the best candidate was full: we shed to the
                    # next-best instead of queueing blind behind it
                    self._bump(SHEDS)
                return r
            return None

    def _release(self, r: _Replica) -> None:
        with self._lock:
            r.inflight -= 1
            self._gauge_inflight(r)
            self._cv.notify_all()

    def _acquire_prefill(self, tried: Set[int]) -> Optional[_Replica]:
        """Queue-depth placement for the prefill leg: the prefill-role
        replica with the fewest in-flight legs (every dispatch flows
        through the router, so ``inflight`` IS the queue depth) that
        still has a credit.  Prefill has no prefix affinity — there is
        no warm cache to return to; the leg's KV leaves with the ship.
        ``None`` = no prefill capacity right now (the caller falls
        back to colocated decode-side prefill, never queues)."""
        with self._lock:
            best = None
            for r in self._replicas:
                if (r.role != "prefill" or r.idx in tried
                        or not r.placeable
                        or r.inflight >= self.credits):
                    continue
                if best is None or r.inflight < best.inflight:
                    best = r
            if best is None:
                return None
            best.inflight += 1
            self._gauge_inflight(best)
            return best

    def _peek_decode(self, digest: bytes) -> Optional[_Replica]:
        """The decode replica normal placement would choose for this
        prefix group — WITHOUT taking a credit (the ship needs a
        destination address at prefill time; the decode dispatch takes
        the credit itself moments later).  Pins the affinity map so the
        later :meth:`_acquire` lands on the same replica the blocks
        were shipped to; a divergence (the replica died or filled in
        between) only strands the staging for the TTL sweep — the
        decode leg then re-prefills, it never attends foreign KV."""
        with self._lock:
            n = len(self._replicas)
            mapped = (self._affinity_map.get(digest)
                      if self.affinity else None)
            if self.affinity:
                order = self._hrw_order(digest)
                if mapped is not None:
                    order = [mapped] + [i for i in order if i != mapped]
            else:
                start = next(self._rr) % n
                order = [(start + j) % n for j in range(n)]
            for idx in order:
                r = self._replicas[idx]
                if not r.placeable or r.role == "prefill":
                    continue
                if self.affinity and mapped != idx:
                    self._affinity_map[digest] = idx
                    self._jpub(k="affinity", d=digest.hex(), r=idx)
                    while len(self._affinity_map) > self._affinity_cap:
                        self._affinity_map.popitem(last=False)
                if self.affinity and digest in self._affinity_map:
                    self._affinity_map.move_to_end(digest)
                return r
            return None

    # ------------------------------------------------------------- dispatch

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0, deadline: Optional[float] = None,
               resume=None, rid: Optional[str] = None,
               tenant: Optional[str] = None,
               slo: Optional[str] = None, _redispatch: bool = False):
        """Token iterator: place the request, stream its tokens, and on
        replica death re-dispatch to a survivor with the emitted prefix
        — the consumer sees one uninterrupted, token-identical
        sequence.  Raises :class:`ReplicaLostError` (typed, within the
        deadline) when the serving tier cannot complete it.

        ``resume`` = tokens the CALLER already holds (a client retrying
        through the router after its own connection loss — the same
        wire contract the serve frontend speaks); they count against
        ``max_new_tokens`` and only new tokens are yielded.

        ``rid`` (caller-chosen, minted when absent) names the request
        for OP_CANCEL propagation and the HA journal's in-flight
        record; ``tenant`` debits that tenant's fair-share credit pool
        when tenant weights are configured.

        ``slo`` names the request's class (``guaranteed`` /
        ``standard`` / ``best-effort`` — docs/serving.md "Elastic
        capacity & SLO classes"): when the estimated queue wait blows
        the class deadline the request sheds AT THE DOOR with the
        typed, retryable :class:`OverloadShedError` instead of
        queueing into a miss.  A best-effort stream running on a
        BORROWED tenant credit additionally sheds mid-flight when the
        lender claws its credit back.  ``_redispatch`` is internal:
        the takeover path re-running a journaled queued record (skips
        admission — it was admitted once — and parks its tokens for
        the client to attach to)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        emitted: List[int] = ([int(t) for t in resume]
                              if resume is not None else [])
        if len(emitted) >= max_new_tokens:
            raise ValueError(
                f"resume carries {len(emitted)} tokens but "
                f"max_new_tokens is {max_new_tokens} — nothing left "
                f"to generate")
        if not self._active:
            self._bump(STANDBY_REFUSED)
            raise RouterStandbyError(
                f"router {self.self_addr or self._self_idx} is standby "
                f"(epoch owner: peer {self._active_peer}); retry the "
                f"active router")
        slo_class = normalize_slo(slo, self.slo_default)
        deadline_ts = time.monotonic() + (
            deadline if deadline is not None else self.deadline)
        rid = str(rid) if rid else f"r{self._self_idx}.{next(self._rid_seq)}"
        if not _redispatch:
            with self._parked_cv:
                parked = self._parked.get(rid)
            if parked is not None:
                # a takeover re-dispatch already runs this request:
                # attach to its parked stream instead of re-submitting
                yield from self._attach_parked(rid, parked, emitted,
                                               deadline_ts)
                return
        self._bump(REQUESTS)
        if not _redispatch:
            # deadline-aware admission: estimate the queue wait from
            # the live backlog and shed typed AT THE DOOR when the
            # class deadline cannot be met (guaranteed never sheds by
            # default — infinite deadline)
            with self._lock:
                queued = sum(1 for q in self._inflight.values()
                             if q.get("r") is None)
                busy = sum(r.inflight for r in self._replicas)
                cap = self.credits * sum(
                    1 for r in self._replicas
                    if r.placeable and r.role != "prefill")
            try:
                self._admission.admit(slo_class, busy, queued, cap)
            except OverloadShedError:
                self._bump(_SHED_COUNTER[slo_class])
                raise
        t_start = time.monotonic()
        digest = self._digest(prompt)
        dispatched = False  # a leg reached a replica at least once
        tried: Set[int] = set()
        attempt = 0  # consecutive no-progress attempts (resets on tokens)
        stalls = 0   # consecutive no-placeable-replica waits
        rec = {"rid": rid, "seed": int(seed), "prio": int(priority),
               "mnt": int(max_new_tokens), "tenant": tenant,
               "slo": slo_class,
               "r": None, "n": len(emitted), "cancelled": False,
               # dispatch stage, journaled to standbys: None (normal)
               # or "ship" (PREFILL_SHIPPING — a takeover knows the
               # request was mid-prefill-leg and owns no decode slot)
               "st": None}
        if (self._journal is not None and not emitted
                and len(prompt) <= 4096):
            # the QUEUED record's prompt, journaled so a takeover can
            # re-dispatch a request that never reached a replica
            rec["p"] = [int(t) for t in prompt]
        with self._lock:
            if rid in self._cancel_tombs:
                del self._cancel_tombs[rid]
                rec["cancelled"] = True
            self._rid_done.pop(rid, None)  # the rid is live again
            self._inflight[rid] = rec

        def _give_up(cause: str, err=None):
            self._bump(FAILED)
            e = ReplicaLostError(
                f"request could not complete on any replica within its "
                f"deadline: {cause} (attempts without progress: "
                f"{attempt}, tokens already streamed: {len(emitted)})",
                attempts=attempt, emitted=emitted)
            if err is not None:
                raise e from err
            raise e

        def _pace(cause: str, err=None):
            # backoff before the next attempt, deadline- and
            # attempt-bounded by the RetryPolicy contract
            nonlocal attempt
            attempt += 1
            if not self.retry.should_retry(attempt, deadline_ts):
                _give_up(cause, err)
            self._bump(RETRIES)
            self.retry.sleep(attempt + 1)

        journaled = False

        def _jpub_inflight():
            nonlocal journaled
            ent = {f: rec[f] for f in _JOURNAL_FIELDS}
            if rec["r"] is None and "p" in rec:
                ent["p"] = rec["p"]
            journaled = True
            self._jpub(k="inflight", **ent)

        tname = (tenant if tenant in self._tenant_pools else "default")
        lease: Optional[Lease] = None

        def _claw_check():
            # the work-conserving contract's teeth: a borrowed credit
            # flagged by clawback sheds this stream typed at its next
            # pace point, and release() sends the credit home
            if lease is not None and lease.reclaimed:
                self._bump(_SHED_COUNTER[slo_class])
                raise OverloadShedError(
                    slo_class, 0.0, 0.0,
                    reason="borrowed credit clawed back")

        try:
            if not _redispatch:
                # journal the QUEUED record before any gate: a
                # takeover between here and placement re-dispatches it
                _jpub_inflight()
            if self._tenant_pools:
                # fair-share gate: ONE credit for the request's whole
                # lifetime (held across failover re-dispatches — it
                # bounds in-flight share, not attempts).  Own pool
                # first, then a BORROWED idle credit (work-conserving
                # shares), then block on the own pool clawing our
                # outstanding loans back.  Deadline-bounded.
                lease = self._shares.acquire(
                    tname,
                    reclaimable=(slo_class == SLO_BEST_EFFORT),
                    timeout=max(0.0, deadline_ts - time.monotonic()),
                    should_abort=lambda: bool(rec["cancelled"]))
                if lease is None:
                    if rec["cancelled"]:
                        self._bump(CANCELLED)
                        return
                    _give_up(
                        f"tenant {tname!r} at its fair-share "
                        f"in-flight limit for the whole deadline "
                        f"(router.tenant_credits)")
                self._gauge_tenant(tname)
            # ---- disaggregated prefill leg (docs/serving.md) ---------
            # One-shot: run the prompt on a prefill-role replica with
            # mnt=1 and ship_to=<the decode replica placement would
            # pick>; the prefill frontend parks the finished KV and
            # ships it to the decode target before replying.  ANY
            # failure on this leg falls through to the normal loop —
            # decode-side re-prefill over the resume path (PR 10) is
            # the availability floor: disaggregation is never less
            # available than colocated serving.
            ship_addr: Optional[str] = None
            ship_first = False  # first decode leg after a prefill leg
            if self._disagg and not emitted and not rec["cancelled"]:
                d = self._peek_decode(digest)
                p = (self._acquire_prefill(tried)
                     if d is not None else None)
                if (p is not None and not p.verified
                        and not self._verify_replica_weights(
                            p, raising=False)):
                    self._release(p)
                    p = None
                if p is not None:
                    pleg: Optional[RemoteServeClient] = None
                    try:
                        dispatched = True
                        rec["r"] = p.idx
                        rec["st"] = "ship"  # PREFILL_SHIPPING
                        _jpub_inflight()
                        pleg = RemoteServeClient(
                            p.addr, timeout=self.stream_timeout)
                        toks, info = pleg.prefill_ship(
                            prompt, seed=seed, priority=priority,
                            ship_to=d.addr, kv_ship=rid,
                            epoch=self.epoch, rid=rid, tenant=tenant)
                        self._bump(DISAGG_PREFILLS)
                        # name the staging on the decode dispatch
                        # either way: a complete ship is adopted, a
                        # failed/partial one is aborted and released
                        # promptly instead of waiting out the TTL
                        ship_addr = d.addr
                        ship_first = True
                        if info.get("shipped"):
                            self._bump(DISAGG_SHIPPED_BLOCKS,
                                       int(info.get("blocks", 0)))
                        else:
                            self._bump(DISAGG_FALLBACKS)
                            bps_log.warning(
                                "disagg: ship %s -> %s failed (%s); "
                                "decode-side re-prefill",
                                rid, d.addr, info.get("error"))
                        for tok in toks:
                            if rec["cancelled"]:
                                self._bump(CANCELLED)
                                return
                            emitted.append(int(tok))
                            rec["n"] = len(emitted)
                            yield int(tok)
                        if len(emitted) >= max_new_tokens:
                            # mnt=1 request: the prefill leg WAS the
                            # whole request (its staging, if any, is
                            # TTL-swept at the decode replica)
                            self._bump(COMPLETED)
                            return
                    except (ServeConnectionError, OSError) as e:
                        # the prefill replica died mid-leg or mid-ship:
                        # fall through — the loop below re-prefills
                        # decode-side from scratch (no tokens were
                        # emitted, so the prefix is just the prompt)
                        self._note_leg_failure(p)
                        self._bump(FAILOVERS)
                        self._bump(DISAGG_FALLBACKS)
                        if rec["cancelled"]:
                            self._bump(CANCELLED)
                            return
                        bps_log.warning(
                            "disagg: prefill replica %d (%s) lost "
                            "mid-leg (%s); decode-side re-prefill",
                            p.idx, p.addr, e)
                    except RuntimeError as e:
                        msg = str(e)
                        if "EpochFencedError" in msg:
                            m = re.search(r"high-water (\d+)", msg)
                            self._demote(int(m.group(1)) if m
                                         else self.epoch)
                            self._bump(STANDBY_REFUSED)
                            raise RouterStandbyError(
                                f"router "
                                f"{self.self_addr or self._self_idx} "
                                f"deposed: replica {p.idx} fenced "
                                f"epoch {self.epoch}; retry the "
                                f"active router with resume") from e
                        if "ValueError" in msg:
                            # deterministic client error: recurs on
                            # every replica — propagate, don't retry
                            self._bump(FAILED)
                            raise
                        # backpressure / engine failure on the prefill
                        # tier: colocated fallback, not a request
                        # failure
                        self._bump(DISAGG_FALLBACKS)
                        if rec["cancelled"]:
                            self._bump(CANCELLED)
                            return
                    finally:
                        if pleg is not None:
                            pleg.close()
                        self._release(p)
                        rec["r"] = None
                        rec["st"] = None
            while True:
                if rec["cancelled"]:
                    self._bump(CANCELLED)
                    return
                _claw_check()
                if not self._active:
                    # deposed mid-request (epoch fence / higher-epoch
                    # journal): the new epoch's router owns the tier —
                    # the client fails over to it with resume
                    self._bump(STANDBY_REFUSED)
                    raise RouterStandbyError(
                        f"router {self.self_addr or self._self_idx} "
                        f"was deposed mid-request (epoch owner: peer "
                        f"{self._active_peer}); retry the active "
                        f"router with resume")
                r = self._acquire(digest, tried)
                if r is None:
                    # no placeable replica this round: clear the
                    # per-round exclusions and wait — states and
                    # credits change while we do.  Saturation is NOT a
                    # failed attempt: it is bounded by the request
                    # DEADLINE alone (the RetryPolicy attempt budget
                    # counts replicas actually failing, not the router
                    # waiting its turn for a credit).
                    tried.clear()
                    stalls += 1
                    delay = max(0.005, self.retry.backoff(
                        min(stalls, self.retry.max_attempts) + 1))
                    if time.monotonic() + delay > deadline_ts:
                        _give_up("no placeable replica within the "
                                 "deadline (all dead, draining, or at "
                                 "their credit limit)")
                    self._bump(RETRIES)
                    time.sleep(delay)
                    continue
                stalls = 0
                if not r.verified and not self._verify_replica_weights(
                        r, raising=False):
                    # registration could not reach this replica and it
                    # is still unverified (or the check just refused
                    # it): an unverified replica must never see traffic
                    # — a wrong-checkpoint replica receiving a resume
                    # re-dispatch in the window before its first
                    # successful ping is the exact splice the handshake
                    # exists to prevent.  Not a failed attempt: like
                    # saturation, this round simply skips it (the
                    # deadline bounds the overall wait, and a
                    # transiently-unreachable stats endpoint is retried
                    # on the next round / ping).
                    self._release(r)
                    tried.add(r.idx)
                    continue
                leg: Optional[RemoteServeClient] = None
                try:
                    leg = RemoteServeClient(r.addr,
                                            timeout=self.stream_timeout)
                    if emitted and dispatched and not ship_first:
                        # a router-internal re-dispatch (mid-stream
                        # failover) — caller-supplied resume tokens on
                        # the FIRST leg are not one, and neither is the
                        # decode leg that follows a prefill leg
                        self._bump(REDISPATCHES)
                    dispatched = True
                    rec["r"] = r.idx
                    rec["n"] = len(emitted)
                    if rec["cancelled"]:
                        # cancel() ran between the loop-top check and
                        # the placement (it saw r=None and relied on
                        # us): honor it BEFORE the SUBMIT ever leaves
                        self._bump(CANCELLED)
                        return
                    # the journaled in-flight record: id, params,
                    # replica, emitted COUNT (counts, not tokens — the
                    # client holds the tokens)
                    _jpub_inflight()
                    extra = None
                    if (ship_addr is not None and r.addr == ship_addr
                            and len(emitted) == 1):
                        # name the staged ship on the decode dispatch
                        # (consumed once: the frontend's stager.take
                        # pops the staging, adopted or aborted)
                        extra = {"kv_ship": rid}
                        ship_addr = None
                    ship_first = False
                    for tok in leg.stream(prompt, max_new_tokens,
                                          seed=seed, priority=priority,
                                          resume=emitted or None,
                                          epoch=self.epoch, rid=rid,
                                          extra=extra):
                        if rec["cancelled"]:
                            # a cancel whose replica-side forward
                            # missed this leg (raced a re-dispatch, or
                            # found r=None): tear the leg down — the
                            # finally's leg.close() disconnects, and
                            # the replica's disconnect path eager-
                            # cancels the slot
                            self._bump(CANCELLED)
                            return
                        _claw_check()
                        emitted.append(int(tok))
                        attempt = 0
                        tried.clear()
                        rec["n"] = len(emitted)
                        if rec["n"] % self.journal_every == 0:
                            _jpub_inflight()
                        yield int(tok)
                    if rec["cancelled"]:
                        # the replica-side eager cancel ended the leg
                        # with its terminal frame early
                        self._bump(CANCELLED)
                    else:
                        self._bump(COMPLETED)
                    return
                except OverloadShedError:
                    # our own clawback shed (_claw_check inside the
                    # token loop): typed, not a replica failure — the
                    # leg teardown below still runs
                    raise
                except (ServeConnectionError, OSError) as e:
                    # the replica died or stalled mid-leg (connect
                    # refused, reset mid-stream, no token within
                    # stream_timeout): feed the detector and
                    # re-dispatch to a survivor with the emitted prefix
                    self._note_leg_failure(r)
                    self._bump(FAILOVERS)
                    if rec["cancelled"]:
                        self._bump(CANCELLED)
                        return
                    if len(emitted) >= max_new_tokens:
                        # the replica died BETWEEN the final token and
                        # the terminal frame: the stream is already
                        # fully delivered — completing it is correct,
                        # and a re-dispatch would be infeasible
                        # (nothing left to generate)
                        self._bump(COMPLETED)
                        return
                    tried.add(r.idx)
                    _pace(f"replica {r.idx} ({r.addr}) lost "
                          f"mid-request: {e}", e)
                except RuntimeError as e:
                    msg = str(e)
                    if "EpochFencedError" in msg:
                        # the replica has served a NEWER epoch: a
                        # standby took over while we thought we were
                        # active.  Demote and bounce the request — the
                        # client re-issues to the new active with
                        # resume; continuing here would double-serve it
                        m = re.search(r"high-water (\d+)", msg)
                        self._demote(int(m.group(1)) if m
                                     else self.epoch)
                        self._bump(STANDBY_REFUSED)
                        raise RouterStandbyError(
                            f"router {self.self_addr or self._self_idx}"
                            f" deposed: replica {r.idx} fenced epoch "
                            f"{self.epoch}; retry the active router "
                            f"with resume") from e
                    if ("QueueFullError" in msg
                            or "AdmissionError" in msg
                            or "BlocksExhaustedError" in msg):
                        # typed replica-side backpressure: shed to the
                        # next candidate instead of queueing blind
                        self._bump(SHEDS)
                        tried.add(r.idx)
                        _pace(f"replica {r.idx} shedding load: {msg}",
                              e)
                    elif "ValueError" in msg:
                        # a deterministic client error (infeasible
                        # request) recurs on every replica —
                        # propagate, don't retry
                        self._bump(FAILED)
                        raise
                    else:
                        # replica-side engine failure: that engine is
                        # gone for this request — treat like a dead
                        # replica
                        self._note_leg_failure(r)
                        self._bump(FAILOVERS)
                        if rec["cancelled"]:
                            self._bump(CANCELLED)
                            return
                        if len(emitted) >= max_new_tokens:
                            self._bump(COMPLETED)  # fully delivered
                            return
                        tried.add(r.idx)
                        _pace(f"replica {r.idx} failed the request: "
                              f"{msg}", e)
                finally:
                    if leg is not None:
                        leg.close()
                    self._release(r)
        finally:
            with self._lock:
                self._inflight.pop(rid, None)
                self._rid_done[rid] = None
                while len(self._rid_done) > 1024:
                    self._rid_done.popitem(last=False)
            if lease is not None:
                # a borrowed credit flows back to the LENDER's pool —
                # that release IS the clawback's delivery mechanism
                self._shares.release(lease)
                self._gauge_tenant(lease.lender or tname)
            if dispatched and emitted:
                # feed the EWMA service-time estimate the admission
                # door's queue-wait math runs on
                self._admission.note_service(
                    max(0.0, time.monotonic() - t_start))
            if journaled:
                # "done" retires the journaled record whether or not a
                # replica was ever reached — a standby must not
                # re-dispatch a request that already failed typed here
                self._jpub(k="done", rid=rid)

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0, deadline: Optional[float] = None,
                 resume=None, rid: Optional[str] = None,
                 tenant: Optional[str] = None,
                 slo: Optional[str] = None) -> np.ndarray:
        """Blocking dispatch -> the NEW tokens (the OP_SUBMIT analog
        of :meth:`stream`; with ``resume`` the caller already holds
        the prefix, so only the continuation comes back)."""
        return np.asarray(
            list(self.stream(prompt, max_new_tokens, seed=seed,
                             priority=priority, deadline=deadline,
                             resume=resume, rid=rid, tenant=tenant,
                             slo=slo)),
            np.int32)

    # ----------------------------------------------------------------- drain

    def drain(self, idx: int, timeout: Optional[float] = None) -> None:
        """Gracefully remove replica ``idx``: stop new placements
        immediately, let in-flight requests finish, then retire it —
        zero client-visible errors.  Its affinity groups re-home on
        their next request (rendezvous keeps everyone else's placement
        stable)."""
        r = self._replicas[idx]
        deadline_ts = (time.monotonic() + timeout
                       if timeout is not None else None)
        with self._lock:
            if r.retired:
                # idempotent: a takeover reconcile and the autoscale
                # controller may both retire the same replica — the
                # second call must be a no-op, not a second drain
                return
            r.draining = True
            self._gauge_state(r)
            for d in [d for d, i in self._affinity_map.items()
                      if i == idx]:
                del self._affinity_map[d]
            while r.inflight > 0:
                remaining = (None if deadline_ts is None
                             else deadline_ts - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"drain of replica {idx} timed out with "
                        f"{r.inflight} request(s) still in flight")
                self._cv.wait(remaining)
            r.retired = True
        self._bump(DRAINS)
        self._jpub_replica(r)
        bps_log.info("router: replica %d (%s) drained and retired",
                     idx, r.addr)

    # ---------------------------------------------------- elastic capacity

    def placeable_count(self) -> int:
        """Replicas currently accepting normal placement — the
        autoscale policy's notion of tier size (prefill-role replicas
        are not decode capacity)."""
        with self._lock:
            return sum(1 for r in self._replicas
                       if r.placeable and r.role != "prefill")

    def signal_snapshot(self) -> Dict[str, int]:
        """Load signals for the in-process autoscale sampler
        (``autoscale.signals.poll_router``): in-flight legs vs the
        placeable tier's credit capacity, plus the admission-queue
        depth (admitted but not yet placed)."""
        with self._lock:
            cap = self.credits * sum(
                1 for r in self._replicas
                if r.placeable and r.role != "prefill")
            busy = sum(r.inflight for r in self._replicas)
            queued = sum(1 for rec in self._inflight.values()
                         if rec.get("r") is None)
        return {"inflight": busy, "capacity": cap, "queued": queued}

    def replica_index(self, addr: str) -> Optional[int]:
        """Roster index of the (non-retired) replica at ``addr``, or
        None — how the takeover reconcile maps a journaled scale
        intent back onto the roster."""
        with self._lock:
            for r in self._replicas:
                if r.addr == addr and not r.retired:
                    return r.idx
        return None

    def add_replica(self, addr: str, role: str = "both") -> int:
        """Register a NEW replica with the running tier (the autoscale
        actuator's scale-up path).  The replica joins the roster and
        the heartbeat/degraded maps, then must pass the same weights-
        fingerprint handshake registration runs — a wrong-checkpoint
        spawn raises typed and never takes traffic.  The journaled
        roster entry carries the address, so HA standbys append the
        same replica (a takeover mid-scale-up owns the grown tier
        instead of orphaning the spawn).  Idempotent on address."""
        addr = str(addr).strip()
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown replica role {role!r}")
        with self._lock:
            for r in self._replicas:
                if r.addr == addr and not r.retired:
                    return r.idx
            r = _Replica(len(self._replicas), addr, role)
            self._replicas.append(r)
            self._detector.grow(1)
            self._degraded.grow(1)
        self._gauge_state(r)
        self._verify_replica_weights(r, raising=True)
        self._jpub_replica(r)
        with self._lock:
            self._cv.notify_all()
        return r.idx

    def journal_scale(self, op: str, addr: Optional[str] = None,
                      idx: Optional[int] = None,
                      phase: str = "intent") -> None:
        """Journal one scale event (``k="scale"``).  Standbys fold the
        open intent into :meth:`pending_scale`, so a takeover
        mid-scale reconciles it (``AutoscaleController.
        reconcile_takeover``) instead of orphaning a spawning replica
        or double-draining a retiring one."""
        ent = {"k": "scale", "op": str(op), "addr": addr, "i": idx,
               "phase": str(phase)}
        with self._lock:
            self._pending_scale = (None if phase in ("done", "abort")
                                   else dict(ent))
        self._jpub(**ent)

    def pending_scale(self) -> Optional[dict]:
        with self._lock:
            return (dict(self._pending_scale)
                    if self._pending_scale else None)

    # ------------------------------------- takeover queued re-dispatch

    _parked_cap = 64

    def _park_redispatch(self, ent: dict) -> None:
        """Re-dispatch one journaled QUEUED-but-unstarted record on a
        background thread, buffering its tokens under the rid; the
        client's retry (same rid, SUBMIT or STREAM) attaches to the
        buffer instead of double-submitting.  Bounded by
        ``_parked_cap`` — records past it stay orphans (their clients
        re-issue with resume, the pre-existing recovery window)."""
        rid = str(ent["rid"])
        with self._parked_cv:
            if (rid in self._parked
                    or len(self._parked) >= self._parked_cap):
                return
            slot = {"toks": [], "done": False, "err": None}
            self._parked[rid] = slot
        self._bump(QUEUED_REDISPATCHES)

        def _run():
            try:
                for tok in self.stream(
                        np.asarray(ent["p"], np.int32),
                        int(ent["mnt"]),
                        seed=int(ent.get("seed") or 0),
                        priority=int(ent.get("prio") or 0),
                        tenant=ent.get("tenant"), slo=ent.get("slo"),
                        rid=rid, _redispatch=True):
                    with self._parked_cv:
                        slot["toks"].append(int(tok))
                        self._parked_cv.notify_all()
            except BaseException as e:  # delivered to the attacher
                slot["err"] = f"{type(e).__name__}: {e}"
            finally:
                with self._parked_cv:
                    slot["done"] = True
                    self._parked_cv.notify_all()

        threading.Thread(target=_run, daemon=True,
                         name=f"bps-requeue-{rid}").start()

    def _attach_parked(self, rid: str, slot: dict,
                       emitted: List[int], deadline_ts: float):
        """Yield the parked re-dispatch's tokens past the caller's
        resume offset; the slot is consumed when the underlying
        stream completes (accounting — REQUESTS/COMPLETED/journal —
        belongs to the re-dispatch run, not this view)."""
        i = len(emitted)
        while True:
            with self._parked_cv:
                while (len(slot["toks"]) <= i and not slot["done"]
                        and time.monotonic() < deadline_ts):
                    self._parked_cv.wait(min(
                        0.1, max(0.001,
                                 deadline_ts - time.monotonic())))
                toks = list(slot["toks"])
                done = bool(slot["done"])
                err = slot["err"]
            while i < len(toks):
                yield int(toks[i])
                i += 1
            if done and i >= len(toks):
                with self._parked_cv:
                    self._parked.pop(rid, None)
                if err:
                    raise ReplicaLostError(
                        f"takeover re-dispatch of {rid} failed: "
                        f"{err}", emitted=toks)
                return
            if time.monotonic() >= deadline_ts:
                raise ReplicaLostError(
                    f"takeover re-dispatch of {rid} still running at "
                    f"the caller's deadline", emitted=toks)

    # ------------------------------------------------------------ inspection

    def replica_states(self) -> List[str]:
        return [r.state.value for r in self._replicas]

    def stats(self) -> Dict[str, object]:
        # one lock hold for the WHOLE mutable-state snapshot: the
        # journal epoch, role and in-flight maps move together under
        # _lock (apply_journal / takeover), so reading them after
        # releasing it could pair a pre-takeover role with a
        # post-takeover epoch — the exact torn read the lock-discipline
        # lint (lock-unguarded-field) flagged here
        with self._lock:
            reps = [{"addr": r.addr, "state": r.state.value,
                     "inflight": r.inflight, "role": r.role}
                    for r in self._replicas]
            out: Dict[str, object] = {"replicas": reps,
                                      "affinity": self.affinity,
                                      "credits": self.credits,
                                      "disagg": self._disagg,
                                      "role": ("active" if self._active
                                               else "standby"),
                                      "epoch": self.epoch,
                                      "journal_epoch": self._journal_epoch,
                                      "journal_inflight":
                                          len(self._journal_inflight),
                                      "inflight": len(self._inflight)}
            if self._tenant_pools:
                out["tenant_credits"] = {
                    t: q.credits for t, q in self._tenant_pools.items()}
        for name in (REQUESTS, COMPLETED, FAILED, FAILOVERS,
                     REDISPATCHES, SHEDS, RETRIES, AFFINITY_HITS,
                     AFFINITY_MISSES, DRAINS, WEIGHTS_REFUSED,
                     TAKEOVERS, DEMOTIONS, STANDBY_REFUSED, CANCELS,
                     CANCELLED, JOURNAL_SENT, JOURNAL_APPLIED,
                     TAKEOVER_ORPHANS, DISAGG_PREFILLS,
                     DISAGG_SHIPPED_BLOCKS, DISAGG_FALLBACKS,
                     SHED_GUARANTEED, SHED_STANDARD, SHED_BEST_EFFORT,
                     BORROWED_CREDITS, QUEUED_REDISPATCHES):
            m = self._registry.get(name)
            out[name] = m.value if m is not None else 0
        return out


# --------------------------------------------------------------- wire tier


class _RouterHandler(socketserver.BaseRequestHandler):
    def setup(self):
        track = getattr(self.server, "_track_conn", None)
        if track is not None:
            track(self.request)

    def handle(self):  # one connection, many requests
        router: ServeRouter = self.server.router  # type: ignore
        sock = self.request
        maybe_nodelay(sock)
        try:
            while True:
                try:
                    op, name, arr, _ = _decode(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    if op in (OP_SUBMIT, OP_STREAM):
                        # same request layout as the serve frontend
                        # (wire compatibility is the point) — ONE
                        # definition of the resume-split contract
                        params = json.loads(name) if name else {}
                        prompt, resumed = _split_resume(params, arr)
                        kw = dict(
                            seed=int(params.get("seed", 0)),
                            priority=int(params.get("priority", 0)),
                            resume=resumed,
                            rid=params.get("rid"),
                            tenant=params.get("tenant"),
                            slo=params.get("slo"))
                        mnt = int(params.get("max_new_tokens", 16))
                    if op == OP_SUBMIT:
                        new = router.generate(prompt, mnt, **kw)
                        # like the frontend: the reply is the FULL
                        # sequence, resume prefix included
                        full = (np.concatenate([resumed, new])
                                if resumed is not None else new)
                        reply = _encode(0, "", full)
                    elif op == OP_STREAM:
                        gen = router.stream(prompt, mnt, **kw)
                        emitted: List[int] = ([int(t) for t in resumed]
                                              if resumed is not None
                                              else [])
                        try:
                            try:
                                for tok in gen:
                                    emitted.append(tok)
                                    sock.sendall(_encode(
                                        0, "t",
                                        np.asarray([tok], np.int32)))
                                sock.sendall(_encode(
                                    0, "end",
                                    np.asarray(emitted, np.int32)))
                            except OSError:
                                # client went away: closing the
                                # generator tears the replica leg down,
                                # which triggers the replica-side eager
                                # cancel
                                return
                        finally:
                            gen.close()
                        continue
                    elif op == OP_CANCEL:
                        params = json.loads(name) if name else {}
                        ok = router.cancel(str(params.get("rid", "")))
                        reply = _encode(
                            0, "", None,
                            json.dumps({"cancelled": ok}).encode())
                    elif op == OP_JOURNAL:
                        ack = router.apply_journal(
                            json.loads(name) if name else [])
                        reply = _encode(0, "", None,
                                        json.dumps(ack).encode())
                    elif op == OP_STATS:
                        reply = _encode(
                            0, "", None,
                            json.dumps(router.stats()).encode())
                    elif op == OP_PING:
                        reply = _encode(0, "", None)
                    else:
                        reply = _encode(1, "", None,
                                        f"bad op {op}".encode())
                except Exception as e:
                    # typed errors (ReplicaLostError, replica-side
                    # rejections) ride the status=1 reply; the
                    # connection survives
                    reply = _encode(
                        1, "", None, f"{type(e).__name__}: {e}".encode())
                sock.sendall(reply)
        except Exception as e:  # pragma: no cover - teardown races
            bps_log.debug("router handler exit: %s", e)


class RouterFrontend(socketserver.ThreadingTCPServer):
    """TCP frontend over a :class:`ServeRouter` — wire-compatible with
    ``ServeFrontend``, so existing clients point at the router
    unchanged.  A STANDBY router serves the same port: it answers
    PING/STATS/JOURNAL and refuses SUBMIT/STREAM with the typed,
    client-retryable ``RouterStandbyError``."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, router: ServeRouter):
        super().__init__(addr, _RouterHandler)
        self.router = router
        # live client sockets, so kill() can die like a crashed router
        # process (sever mid-stream connections, not just stop
        # accepting) — the ServeFrontend.kill discipline one tier up
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._killing = False
        router.start()

    def _track_conn(self, sock) -> None:
        with self._conns_lock:
            if not self._killing:
                self._conns.add(sock)
                self._conns = {s for s in self._conns
                               if s.fileno() != -1}
                return
        hard_reset(sock)

    def kill(self) -> None:
        """Die like a crashed active router: hard-reset every live
        client connection FIRST (mid-stream clients see ECONNRESET
        mid-frame — what the multi-router client failover must
        absorb), stop accepting, and take the ServeRouter down with
        this process (its journal sender and detectors die too — a
        crash leaves no background threads).  Chaos/test only."""
        self._killing = True
        # journaling stops FIRST: a crashed process never flushes its
        # queued entries, and the takeover proof depends on that
        self.router.kill()
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            hard_reset(c)
        self.shutdown()
        self.server_close()

    def server_close(self):
        self.router.close()
        super().server_close()


def serve_router(router: ServeRouter, port: int, host: str = "0.0.0.0",
                 in_thread: bool = False):
    """Run the router frontend.  ``in_thread=True`` returns
    ``(server, thread)`` for tests; otherwise blocks (launcher mode)."""
    srv = RouterFrontend((host, port), router)
    bps_log.info("byteps_tpu serve router (%s, epoch %d) listening on "
                 "%s:%d over %d replica(s)",
                 "active" if router.active else "standby", router.epoch,
                 host, srv.server_address[1], len(router._replicas))
    from ..observability.scrape import maybe_start_metrics_server

    maybe_start_metrics_server(
        role="router",
        health_fn=lambda: {"replicas": router.replica_states()})
    if in_thread:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, t
    try:
        srv.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        srv.server_close()


def router_from_env(env=None) -> int:
    """Entry point for the launcher's ``router`` role: build the router
    from ``BYTEPS_ROUTER_*`` and block on the TCP frontend."""
    import os

    from ..common.config import get_config, reset_config

    if env is not None:
        os.environ.update({k: str(v) for k, v in env.items()
                           if k.startswith(("BYTEPS_", "DMLC_"))})
    reset_config()
    cfg = get_config()
    replicas = [a.strip() for a in cfg.router_replicas.split(",")
                if a.strip()]
    if not replicas:
        raise SystemExit(
            "byteps_tpu.launcher: the router role needs "
            "BYTEPS_ROUTER_REPLICAS=host:port,host:port (the serve "
            "replicas to fan out over)")
    peers = [a.strip() for a in cfg.router_peers.split(",")
             if a.strip()]
    if peers and not cfg.router_self:
        raise SystemExit(
            "byteps_tpu.launcher: BYTEPS_ROUTER_PEERS is set, so "
            "BYTEPS_ROUTER_SELF must name this router's own entry in "
            "it (host:port) — priority is the list order, and every "
            "router must know its place in it")
    tenant_weights: Dict[str, float] = {}
    if cfg.router_tenant_weights:
        for pair in cfg.router_tenant_weights.split(","):
            t, _, w = pair.partition("=")
            if not t.strip() or not w.strip():
                raise SystemExit(
                    f"byteps_tpu.launcher: malformed "
                    f"BYTEPS_ROUTER_TENANT_WEIGHTS entry {pair!r} "
                    f"(want tenant=weight,tenant=weight)")
            try:
                tenant_weights[t.strip()] = float(w)
            except ValueError:
                raise SystemExit(
                    f"byteps_tpu.launcher: BYTEPS_ROUTER_TENANT_WEIGHTS "
                    f"weight for {t.strip()!r} must be a number, got "
                    f"{w.strip()!r}") from None
    roles = [x.strip() for x in cfg.router_roles.split(",")
             if x.strip()]
    if roles and len(roles) != len(replicas):
        raise SystemExit(
            f"byteps_tpu.launcher: BYTEPS_ROUTER_ROLES has "
            f"{len(roles)} entries for {len(replicas)} replicas — it "
            f"must mirror BYTEPS_ROUTER_REPLICAS positionally "
            f"(prefill, decode, or both)")
    router = ServeRouter(
        replicas,
        roles=roles or None,
        disagg=cfg.disagg,
        credits=cfg.router_credits,
        affinity=cfg.router_affinity,
        affinity_block=cfg.router_affinity_block,
        deadline=cfg.router_deadline_ms / 1e3,
        stream_timeout=cfg.router_stream_timeout_ms / 1e3,
        heartbeat_interval=cfg.router_heartbeat_ms / 1e3,
        miss_threshold=cfg.router_miss_threshold,
        ping_timeout=cfg.heartbeat_timeout_ms / 1e3,
        expected_weights_fp=cfg.router_weights_fp or None,
        peers=peers or None,
        self_addr=cfg.router_self,
        epoch_timeout=cfg.router_epoch_timeout_ms / 1e3,
        tenant_weights=tenant_weights or None,
        slo_default=cfg.slo_default,
        slo_deadlines={
            SLO_STANDARD: cfg.slo_standard_deadline_ms / 1e3,
            SLO_BEST_EFFORT: cfg.slo_best_effort_deadline_ms / 1e3},
        service_estimate_s=cfg.slo_service_estimate_ms / 1e3,
        slo_borrow=cfg.slo_borrow)
    controller = None
    if cfg.autoscale:
        from .autoscale import (AutoscaleController, ReplicaLauncher,
                                ScalePolicy, TierSignals, poll_router)
        controller = AutoscaleController(
            router,
            ScalePolicy(
                min_replicas=cfg.autoscale_min,
                max_replicas=cfg.autoscale_max,
                up_threshold=cfg.autoscale_up,
                down_threshold=cfg.autoscale_down,
                up_cooldown_s=cfg.autoscale_up_cooldown_ms / 1e3,
                down_cooldown_s=cfg.autoscale_down_cooldown_ms / 1e3,
                dry_run=cfg.autoscale_dry_run),
            TierSignals(poll_router(router),
                        window_s=cfg.autoscale_window_ms / 1e3),
            ReplicaLauncher(),
            interval_s=cfg.autoscale_interval_ms / 1e3).start()
    try:
        serve_router(router, cfg.router_port)
    finally:
        if controller is not None:
            controller.close()
    return 0
