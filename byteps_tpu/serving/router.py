"""Fault-tolerant serving router: health-checked replica failover with
deterministic request re-dispatch.

One ``ServingEngine`` is one slot pool on one machine; this tier fans
client traffic out over N ``ServeFrontend`` replicas while speaking the
SAME wire protocol clients already use (``frontend.py`` ops — a router
is indistinguishable from a big frontend).  Robustness is the headline
(docs/serving.md "Router tier"):

  * **Health-checked replicas.**  A :class:`resilience.FailureDetector`
    heartbeats every replica over the serve protocol (one-shot OP_PING
    round trips); replica-leg wire failures feed the same detector so
    death is noticed at traffic speed.  Replicas move through typed
    states: HEALTHY -> SUSPECT (missed pings / leg failures, still
    routable) -> DEAD (excluded, detector watches for recovery) and
    back (failback re-admission), or HEALTHY -> DRAINING (operator
    drain — no new placements, in-flight finishes, then retired).

  * **Deterministic re-dispatch.**  The router records every request's
    prompt and the tokens that crossed the wire so far.  When a replica
    dies mid-stream, the request is re-submitted to a survivor with the
    emitted prefix (``resume`` submits — engine.py ``resume_tokens``):
    the new replica re-prefills prompt + emitted (position-wise
    determinism rebuilds the exact K/V the dead replica's decode wrote
    — the PR 9 preempt/resume argument, one machine wider), restores
    the parked next-input token, and under sampling recomputes the
    carried key as the k-fold split chain of ``PRNGKey(seed)``.  The
    spliced stream is token-identical to a never-interrupted run —
    greedy by construction, seeded because the key state is a pure
    function of ``(seed, tokens emitted)``.  (If a future sampling
    scheme made key state non-derivable — external entropy, per-tick
    reseeding — resume would be inexact; the engine refuses resume
    loudly for the configs where bit-exactness already cannot hold:
    ``kv_quant`` and flash-prefill models.)

  * **Bounded, typed failure.**  Queued-but-unstarted requests retry
    transparently under :class:`resilience.RetryPolicy` backoff; every
    request carries a deadline, and when no replica can complete it in
    time it fails with the typed :class:`ReplicaLostError` — never a
    hang, never a silent drop.  Every wire read is timeout-bounded.

  * **Prefix-affinity placement.**  Requests are steered by a digest of
    the prompt's leading block (the rolling-hash discipline of
    serving/prefix.py), so shared-system-prompt traffic lands on the
    replica whose prefix cache is warm — SGLang-style cache-aware load
    balancing.  First placement of a prefix group is rendezvous-hashed
    (HRW: deterministic, stable under replica-set changes) and then
    sticky; dead primaries remap through the reused
    :class:`resilience.DegradedModeRouter` (the deterministic
    next-alive scan every PS worker already agrees on).

  * **Credit backpressure.**  Each replica holds ``credits`` in-flight
    requests; a full replica sheds to the next-best candidate instead
    of queueing blind, and total saturation becomes backoff-then-typed
    failure, not an unbounded queue.

Metrics land on the PR 6 registry (``router.*``): per-replica state and
in-flight gauges, failover / redispatch / shed / retry counters, and
the affinity hit rate.  The launcher grows a ``router`` role
(``DMLC_ROLE=router``, knobs ``BYTEPS_ROUTER_*`` — docs/env.md).
"""

from __future__ import annotations

import collections
import enum
import hashlib
import itertools
import json
import socketserver
import threading
import time
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..common import logging as bps_log
from ..engine.ps_server import _decode, _encode
from ..engine.transport import maybe_nodelay
from ..observability.metrics import MetricsRegistry, get_registry
from ..resilience.detector import FailureDetector
from ..resilience.policy import RetryPolicy
from ..resilience.router import DegradedModeRouter
from .frontend import (OP_PING, OP_STATS, OP_STREAM, OP_SUBMIT,
                       RemoteServeClient, ServeConnectionError,
                       _split_resume)

__all__ = ["ReplicaState", "ReplicaLostError", "WeightsMismatchError",
           "ServeRouter", "RouterFrontend", "serve_router",
           "router_from_env"]

# ------------------------------------------------------------- metric names
REQUESTS = "router.requests"
COMPLETED = "router.requests_completed"
FAILED = "router.requests_failed"
# replica-leg wire failures (the request then re-dispatches or retries)
FAILOVERS = "router.failovers"
# re-dispatches that carried an emitted prefix (mid-stream failover)
REDISPATCHES = "router.redispatches"
# placements diverted off a full (or replica-side-rejecting) candidate
SHEDS = "router.sheds"
# backoff waits (no placeable replica / transient leg failure)
RETRIES = "router.retries"
AFFINITY_HITS = "router.affinity_hits"
AFFINITY_MISSES = "router.affinity_misses"
DRAINS = "router.drains"
# replicas refused placement because their STATS weights fingerprint
# disagrees with the tier's (resume across different checkpoints would
# be silently wrong — docs/serving.md "Router tier")
WEIGHTS_REFUSED = "router.weights_refused"
# labeled per-replica gauges
REPLICA_STATE = "router.replica_state"      # 0 healthy 1 suspect 2 dead
REPLICA_INFLIGHT = "router.replica_inflight"  # 3 draining/retired


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"    # missed pings / leg failures; still routable
    DEAD = "dead"          # excluded; detector watches for failback
    DRAINING = "draining"  # no new placements; retires when empty


_STATE_GAUGE = {ReplicaState.HEALTHY: 0, ReplicaState.SUSPECT: 1,
                ReplicaState.DEAD: 2, ReplicaState.DRAINING: 3}


class ReplicaLostError(RuntimeError):
    """No replica could complete the request within its deadline: the
    serving tier lost the replica(s) serving it and ran out of retry
    budget.  ``emitted`` carries any tokens already streamed (the
    client saw them; they are valid — the sequence is just truncated)."""

    def __init__(self, msg: str, attempts: int = 0,
                 emitted: Sequence[int] = ()):
        self.attempts = attempts
        self.emitted = list(emitted)
        super().__init__(msg)


class WeightsMismatchError(RuntimeError):
    """A replica's STATS weights fingerprint disagrees with the tier's:
    it serves a different checkpoint, so a mid-stream re-dispatch onto
    it would splice a silently-wrong continuation.  Raised typed at
    registration (``ServeRouter.start``); at ping/failback time the
    replica is refused placement instead (it stays alive but never
    receives traffic until its fingerprint matches again)."""


class _Replica:
    __slots__ = ("idx", "addr", "inflight", "suspect", "dead",
                 "draining", "retired", "refused", "verified")

    def __init__(self, idx: int, addr: str):
        self.idx = idx
        self.addr = addr
        self.inflight = 0
        self.suspect = False
        self.dead = False
        self.draining = False
        self.retired = False
        # weights handshake: ``verified`` = fingerprint checked against
        # the tier's; ``refused`` = checked and DISAGREED (alive but
        # unplaceable until a later check matches — e.g. the operator
        # restarted it on the right checkpoint)
        self.refused = False
        self.verified = False

    @property
    def state(self) -> ReplicaState:
        if self.draining or self.retired:
            return ReplicaState.DRAINING
        if self.dead or self.refused:
            return ReplicaState.DEAD
        if self.suspect:
            return ReplicaState.SUSPECT
        return ReplicaState.HEALTHY

    @property
    def placeable(self) -> bool:
        return not (self.dead or self.draining or self.retired
                    or self.refused)


class ServeRouter:
    """Fan requests out over N serve replicas; see the module docstring
    for the failover / placement / backpressure contracts.

    ``registry=None`` binds the process-global metrics registry (what
    ``/metrics`` and the router's OP_STATS scrape); tests pass a
    private :class:`MetricsRegistry` to count in isolation.  Call
    :meth:`start` to run the heartbeat detector (per-request failover
    works without it — leg failures are detected at traffic speed —
    but only the detector takes a silent replica out of placement and
    re-admits it on recovery)."""

    def __init__(self, replicas: Sequence[str], *,
                 credits: int = 16,
                 affinity: bool = True,
                 affinity_block: int = 16,
                 deadline: float = 60.0,
                 stream_timeout: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 heartbeat_interval: float = 0.5,
                 miss_threshold: int = 3,
                 ping_timeout: float = 1.0,
                 registry: Optional[MetricsRegistry] = None,
                 expected_weights_fp: Optional[str] = None):
        if not replicas:
            raise ValueError(
                "ServeRouter needs at least one replica address "
                "(BYTEPS_ROUTER_REPLICAS=host:port,host:port)")
        self._replicas = [_Replica(i, a) for i, a in enumerate(replicas)]
        self.credits = max(1, credits)
        self.affinity = bool(affinity)
        self.affinity_block = max(1, affinity_block)
        self.deadline = deadline
        self.stream_timeout = stream_timeout
        # the policy paces attempts; the router's per-request deadline
        # is passed to should_retry as the bound (the policy's own
        # deadline field is unused here)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=6, backoff_base=0.05, backoff_mult=2.0,
            backoff_cap=1.0, jitter=0.1, deadline=0.0)
        self.ping_timeout = ping_timeout
        self._degraded = DegradedModeRouter(len(self._replicas))
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)  # drain waits here
        # prefix-group digest -> replica idx (sticky placements),
        # LRU-bounded so a long-tailed prompt population cannot grow it
        # without bound
        self._affinity_map: "collections.OrderedDict[bytes, int]" = \
            collections.OrderedDict()
        self._affinity_cap = 4096
        self._rr = itertools.count()
        self._registry = registry if registry is not None else get_registry()
        self._detector = FailureDetector(
            len(self._replicas), self._ping_replica,
            interval=heartbeat_interval, miss_threshold=miss_threshold,
            on_down=self._on_replica_down, on_up=self._on_replica_up)
        for r in self._replicas:
            self._gauge_state(r)

        # the tier's weights anchor.  Default: first-verified-wins —
        # the first fingerprint a replica proves becomes the tier's.
        # ``expected_weights_fp`` (BYTEPS_ROUTER_WEIGHTS_FP) lets the
        # operator PIN the anchor instead: WHICH checkpoint wins is
        # then an explicit deployment decision, not an accident of
        # which replica registered first, and a replica that cannot
        # prove the pinned fingerprint (including pre-handshake builds
        # that report none) is refused placement.
        self._expected_fp: Optional[str] = expected_weights_fp or None
        self._fp_pinned = bool(expected_weights_fp)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ServeRouter":
        """Run the registration handshake, then the heartbeat detector.

        Registration compares every reachable replica's STATS weights
        fingerprint (the same digest the prefix-store salt commits to —
        serving/prefix.py ``weights_fingerprint``): the first fingerprint
        seen becomes the tier's — unless the operator pinned the anchor
        via ``expected_weights_fp`` (BYTEPS_ROUTER_WEIGHTS_FP), in which
        case every replica must prove THAT checkpoint — and a
        disagreeing replica raises the typed
        :class:`WeightsMismatchError`: refusing to build a tier
        whose failover re-dispatch would splice tokens from different
        checkpoints.  Replicas unreachable right now are re-checked on
        their first successful ping and at failback."""
        for r in self._replicas:
            self._verify_replica_weights(r, raising=True)
        self._detector.start()
        return self

    def close(self) -> None:
        self._detector.stop()

    # -------------------------------------------------------------- metrics

    def _bump(self, name: str, n: int = 1) -> None:
        self._registry.counter(name, track="router").inc(n)

    def _gauge_state(self, r: _Replica) -> None:
        self._registry.gauge(REPLICA_STATE, track="router",
                             replica=r.idx).set(_STATE_GAUGE[r.state])

    def _gauge_inflight(self, r: _Replica) -> None:
        self._registry.gauge(REPLICA_INFLIGHT, track="router",
                             replica=r.idx).set(r.inflight)

    # --------------------------------------------------------------- health

    def _verify_replica_weights(self, r: _Replica, *,
                                raising: bool) -> bool:
        """Weights handshake against one replica: fetch its STATS
        fingerprint and compare with the tier's (the first fingerprint
        seen).  A mismatch marks the replica REFUSED — alive, heartbeat-
        tracked, but never placed — and raises the typed
        :class:`WeightsMismatchError` when ``raising`` (registration
        path).  A later matching check (operator restarted it on the
        right checkpoint) clears the refusal.  Replicas that do not
        report a fingerprint (pre-handshake builds) are accepted — the
        operator-guarantees-homogeneity contract they were deployed
        under.  Returns True when the replica is verified placeable."""
        try:
            c = RemoteServeClient(r.addr, timeout=self.ping_timeout)
            try:
                fp = c.stats().get("weights_fingerprint")
            finally:
                c.close()
        except (OSError, ValueError, RuntimeError):
            return False  # unreachable: re-checked at ping/failback
        with self._lock:
            if fp is None and not self._fp_pinned:
                # no fingerprint, no pin: the operator-guarantees-
                # homogeneity contract pre-handshake builds were
                # deployed under
                r.verified = True
                r.refused = False
                return True
            if fp is not None:
                if self._expected_fp is None:
                    self._expected_fp = fp
                if fp == self._expected_fp:
                    r.verified = True
                    r.refused = False
                    return True
            first_refusal = not r.refused
            r.refused = True
            r.verified = True
        if first_refusal:
            self._bump(WEIGHTS_REFUSED)
        self._gauge_state(r)
        if fp is None:
            msg = (f"replica {r.idx} ({r.addr}) reports no weights "
                   f"fingerprint but the operator pinned "
                   f"BYTEPS_ROUTER_WEIGHTS_FP="
                   f"{self._expected_fp[:16]}...: refusing placement — "
                   f"an unverifiable replica cannot prove it serves "
                   f"the pinned checkpoint.")
        else:
            msg = (f"replica {r.idx} ({r.addr}) serves different "
                   f"weights (fingerprint {fp[:16]}... != "
                   f"{'pinned' if self._fp_pinned else 'tier'} "
                   f"{self._expected_fp[:16]}...): refusing placement "
                   f"— a mid-stream re-dispatch onto it would splice "
                   f"a silently-wrong continuation.  Restart it on "
                   f"the tier's checkpoint to re-admit it.")
        if raising:
            raise WeightsMismatchError(msg)
        bps_log.warning("router: %s", msg)
        return False

    def _ping_replica(self, idx: int) -> bool:
        """Serve-protocol liveness probe: one short-timeout OP_PING
        round trip on a fresh connection (never contends with data
        legs).  Drives the detector's suspect/dead transitions.  Also
        the retry path of the weights handshake: an alive replica that
        was unreachable at registration (or refused since) re-verifies
        here, so fixing its checkpoint re-admits it within a ping
        interval."""
        r = self._replicas[idx]
        ok = False
        try:
            c = RemoteServeClient(r.addr, timeout=self.ping_timeout)
            try:
                ok = c.ping()
            finally:
                c.close()
        except (OSError, ValueError):
            ok = False
        if ok:
            r.suspect = False
            if not r.verified or r.refused:
                self._verify_replica_weights(r, raising=False)
        elif not r.dead:
            r.suspect = True
        self._gauge_state(r)
        return ok

    def _on_replica_down(self, idx: int) -> None:
        r = self._replicas[idx]
        r.dead, r.suspect = True, False
        # a dead replica's identity is stale the moment it dies: the
        # operator may restart it on a different checkpoint, and a
        # transiently-failing failback re-check must not leave a stale
        # verified=True letting it back in unchecked — clear it so the
        # failback/ping/dispatch paths all re-verify until a STATS
        # fetch actually succeeds
        r.verified = False
        self._degraded.mark_down(idx)
        self._gauge_state(r)
        bps_log.warning("router: replica %d (%s) DEAD", idx, r.addr)

    def _on_replica_up(self, idx: int) -> None:
        r = self._replicas[idx]
        if r.draining or r.retired:
            return  # drained replicas never re-enter placement
        r.dead = r.suspect = False
        self._degraded.mark_up(idx)
        # failback handshake: a replica that went away and came back may
        # have restarted on a different checkpoint — it must prove its
        # weights before placement resumes (a mismatch leaves it alive
        # but refused; matching again later re-admits it)
        self._verify_replica_weights(r, raising=False)
        self._gauge_state(r)
        if r.refused:
            return
        bps_log.warning("router: replica %d (%s) re-admitted (failback)",
                        idx, r.addr)

    def _note_leg_failure(self, r: _Replica) -> None:
        """A data leg to ``r`` died: feed the detector (detection at
        traffic speed, not ping cadence) and mark the replica suspect
        until a ping succeeds."""
        if not r.dead:
            r.suspect = True
            self._gauge_state(r)
        self._detector.report_failure(r.idx)

    # ------------------------------------------------------------ placement

    def _digest(self, prompt: np.ndarray) -> bytes:
        """Prefix-group key: digest of the prompt's leading affinity
        block (shorter prompts digest whole) — the rolling-block-hash
        discipline of serving/prefix.py, truncated to the one block
        that defines a shared-system-prompt group."""
        toks = np.ascontiguousarray(prompt[:self.affinity_block])
        return hashlib.blake2b(toks.tobytes(), digest_size=16).digest()

    def _hrw_order(self, digest: bytes) -> List[int]:
        """Rendezvous (highest-random-weight) order of ALL replicas for
        this prefix group: deterministic, and stable under replica-set
        changes (a dead replica's groups re-home without reshuffling
        everyone else's)."""
        scored = sorted(
            (hashlib.blake2b(digest + r.addr.encode(),
                             digest_size=8).digest(), r.idx)
            for r in self._replicas)
        return [idx for _, idx in reversed(scored)]

    def _acquire(self, digest: bytes,
                 tried: Set[int]) -> Optional[_Replica]:
        """Pick a replica for this request and take one credit.  None =
        nothing placeable right now (dead / draining / full / already
        tried this round) — the caller backs off and retries.

        Candidate order: the sticky affinity target (or the rendezvous
        winner) first — remapped around dead replicas by the reused
        ``DegradedModeRouter`` scan — then the remaining rendezvous
        order; round-robin mode replaces the whole ranking with a
        rotating scan."""
        with self._lock:
            n = len(self._replicas)
            mapped = (self._affinity_map.get(digest)
                      if self.affinity else None)
            if self.affinity:
                order = self._hrw_order(digest)
                primary = mapped if mapped is not None else order[0]
                try:
                    first = self._degraded.route(primary)
                except RuntimeError:
                    first = primary  # every replica down: scan anyway
                cands = [first] + [i for i in order if i != first]
            else:
                start = next(self._rr) % n
                cands = [(start + j) % n for j in range(n)]
            preferred = cands[0]
            preferred_full = False
            for idx in cands:
                r = self._replicas[idx]
                if idx in tried or not r.placeable:
                    continue
                if r.inflight >= self.credits:
                    if idx == preferred:
                        preferred_full = True
                    continue
                r.inflight += 1
                self._gauge_inflight(r)
                if self.affinity:
                    if mapped == idx:
                        self._bump(AFFINITY_HITS)
                    else:
                        self._bump(AFFINITY_MISSES)
                    # stickiness survives a transient shed: re-home the
                    # group only when it has no home or its home is
                    # gone (dead/draining) — one credit-full blip must
                    # not move every later request off the warm cache
                    if (mapped is None
                            or not self._replicas[mapped].placeable):
                        self._affinity_map[digest] = idx
                        while (len(self._affinity_map)
                                > self._affinity_cap):
                            self._affinity_map.popitem(last=False)
                    if digest in self._affinity_map:
                        self._affinity_map.move_to_end(digest)
                if preferred_full:
                    # the best candidate was full: we shed to the
                    # next-best instead of queueing blind behind it
                    self._bump(SHEDS)
                return r
            return None

    def _release(self, r: _Replica) -> None:
        with self._lock:
            r.inflight -= 1
            self._gauge_inflight(r)
            self._cv.notify_all()

    # ------------------------------------------------------------- dispatch

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0, deadline: Optional[float] = None,
               resume=None):
        """Token iterator: place the request, stream its tokens, and on
        replica death re-dispatch to a survivor with the emitted prefix
        — the consumer sees one uninterrupted, token-identical
        sequence.  Raises :class:`ReplicaLostError` (typed, within the
        deadline) when the serving tier cannot complete it.

        ``resume`` = tokens the CALLER already holds (a client retrying
        through the router after its own connection loss — the same
        wire contract the serve frontend speaks); they count against
        ``max_new_tokens`` and only new tokens are yielded."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        emitted: List[int] = ([int(t) for t in resume]
                              if resume is not None else [])
        if len(emitted) >= max_new_tokens:
            raise ValueError(
                f"resume carries {len(emitted)} tokens but "
                f"max_new_tokens is {max_new_tokens} — nothing left "
                f"to generate")
        self._bump(REQUESTS)
        deadline_ts = time.monotonic() + (
            deadline if deadline is not None else self.deadline)
        digest = self._digest(prompt)
        dispatched = False  # a leg reached a replica at least once
        tried: Set[int] = set()
        attempt = 0  # consecutive no-progress attempts (resets on tokens)
        stalls = 0   # consecutive no-placeable-replica waits

        def _give_up(cause: str, err=None):
            self._bump(FAILED)
            e = ReplicaLostError(
                f"request could not complete on any replica within its "
                f"deadline: {cause} (attempts without progress: "
                f"{attempt}, tokens already streamed: {len(emitted)})",
                attempts=attempt, emitted=emitted)
            if err is not None:
                raise e from err
            raise e

        def _pace(cause: str, err=None):
            # backoff before the next attempt, deadline- and
            # attempt-bounded by the RetryPolicy contract
            nonlocal attempt
            attempt += 1
            if not self.retry.should_retry(attempt, deadline_ts):
                _give_up(cause, err)
            self._bump(RETRIES)
            self.retry.sleep(attempt + 1)

        while True:
            r = self._acquire(digest, tried)
            if r is None:
                # no placeable replica this round: clear the per-round
                # exclusions and wait — states and credits change while
                # we do.  Saturation is NOT a failed attempt: it is
                # bounded by the request DEADLINE alone (the RetryPolicy
                # attempt budget counts replicas actually failing, not
                # the router waiting its turn for a credit).
                tried.clear()
                stalls += 1
                delay = max(0.005, self.retry.backoff(
                    min(stalls, self.retry.max_attempts) + 1))
                if time.monotonic() + delay > deadline_ts:
                    _give_up("no placeable replica within the deadline "
                             "(all dead, draining, or at their credit "
                             "limit)")
                self._bump(RETRIES)
                time.sleep(delay)
                continue
            stalls = 0
            if not r.verified and not self._verify_replica_weights(
                    r, raising=False):
                # registration could not reach this replica and it is
                # still unverified (or the check just refused it): an
                # unverified replica must never see traffic — a wrong-
                # checkpoint replica receiving a resume re-dispatch in
                # the window before its first successful ping is the
                # exact splice the handshake exists to prevent.  Not a
                # failed attempt: like saturation, this round simply
                # skips it (the deadline bounds the overall wait, and a
                # transiently-unreachable stats endpoint is retried on
                # the next round / ping).
                self._release(r)
                tried.add(r.idx)
                continue
            leg: Optional[RemoteServeClient] = None
            try:
                leg = RemoteServeClient(r.addr,
                                        timeout=self.stream_timeout)
                if emitted and dispatched:
                    # a router-internal re-dispatch (mid-stream
                    # failover) — caller-supplied resume tokens on the
                    # FIRST leg are not one
                    self._bump(REDISPATCHES)
                dispatched = True
                for tok in leg.stream(prompt, max_new_tokens, seed=seed,
                                      priority=priority,
                                      resume=emitted or None):
                    emitted.append(int(tok))
                    attempt = 0
                    tried.clear()
                    yield int(tok)
                self._bump(COMPLETED)
                return
            except (ServeConnectionError, OSError) as e:
                # the replica died or stalled mid-leg (connect refused,
                # reset mid-stream, no token within stream_timeout):
                # feed the detector and re-dispatch to a survivor with
                # the emitted prefix
                self._note_leg_failure(r)
                self._bump(FAILOVERS)
                if len(emitted) >= max_new_tokens:
                    # the replica died BETWEEN the final token and the
                    # terminal frame: the stream is already fully
                    # delivered — completing it is correct, and a
                    # re-dispatch would be infeasible (nothing left to
                    # generate)
                    self._bump(COMPLETED)
                    return
                tried.add(r.idx)
                _pace(f"replica {r.idx} ({r.addr}) lost mid-request: "
                      f"{e}", e)
            except RuntimeError as e:
                msg = str(e)
                if ("QueueFullError" in msg or "AdmissionError" in msg
                        or "BlocksExhaustedError" in msg):
                    # typed replica-side backpressure: shed to the next
                    # candidate instead of queueing blind behind it
                    self._bump(SHEDS)
                    tried.add(r.idx)
                    _pace(f"replica {r.idx} shedding load: {msg}", e)
                elif "ValueError" in msg:
                    # a deterministic client error (infeasible request)
                    # recurs on every replica — propagate, don't retry
                    self._bump(FAILED)
                    raise
                else:
                    # replica-side engine failure: that engine is gone
                    # for this request — treat like a dead replica
                    self._note_leg_failure(r)
                    self._bump(FAILOVERS)
                    if len(emitted) >= max_new_tokens:
                        self._bump(COMPLETED)  # already fully delivered
                        return
                    tried.add(r.idx)
                    _pace(f"replica {r.idx} failed the request: {msg}",
                          e)
            finally:
                if leg is not None:
                    leg.close()
                self._release(r)

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0, deadline: Optional[float] = None,
                 resume=None) -> np.ndarray:
        """Blocking dispatch -> the NEW tokens (the OP_SUBMIT analog
        of :meth:`stream`; with ``resume`` the caller already holds
        the prefix, so only the continuation comes back)."""
        return np.asarray(
            list(self.stream(prompt, max_new_tokens, seed=seed,
                             priority=priority, deadline=deadline,
                             resume=resume)),
            np.int32)

    # ----------------------------------------------------------------- drain

    def drain(self, idx: int, timeout: Optional[float] = None) -> None:
        """Gracefully remove replica ``idx``: stop new placements
        immediately, let in-flight requests finish, then retire it —
        zero client-visible errors.  Its affinity groups re-home on
        their next request (rendezvous keeps everyone else's placement
        stable)."""
        r = self._replicas[idx]
        deadline_ts = (time.monotonic() + timeout
                       if timeout is not None else None)
        with self._lock:
            r.draining = True
            self._gauge_state(r)
            for d in [d for d, i in self._affinity_map.items()
                      if i == idx]:
                del self._affinity_map[d]
            while r.inflight > 0:
                remaining = (None if deadline_ts is None
                             else deadline_ts - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"drain of replica {idx} timed out with "
                        f"{r.inflight} request(s) still in flight")
                self._cv.wait(remaining)
            r.retired = True
        self._bump(DRAINS)
        bps_log.info("router: replica %d (%s) drained and retired",
                     idx, r.addr)

    # ------------------------------------------------------------ inspection

    def replica_states(self) -> List[str]:
        return [r.state.value for r in self._replicas]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            reps = [{"addr": r.addr, "state": r.state.value,
                     "inflight": r.inflight} for r in self._replicas]
        out: Dict[str, object] = {"replicas": reps,
                                  "affinity": self.affinity,
                                  "credits": self.credits}
        for name in (REQUESTS, COMPLETED, FAILED, FAILOVERS,
                     REDISPATCHES, SHEDS, RETRIES, AFFINITY_HITS,
                     AFFINITY_MISSES, DRAINS, WEIGHTS_REFUSED):
            m = self._registry.get(name)
            out[name] = m.value if m is not None else 0
        return out


# --------------------------------------------------------------- wire tier


class _RouterHandler(socketserver.BaseRequestHandler):
    def handle(self):  # one connection, many requests
        router: ServeRouter = self.server.router  # type: ignore
        sock = self.request
        maybe_nodelay(sock)
        try:
            while True:
                try:
                    op, name, arr, _ = _decode(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    if op in (OP_SUBMIT, OP_STREAM):
                        # same request layout as the serve frontend
                        # (wire compatibility is the point) — ONE
                        # definition of the resume-split contract
                        params = json.loads(name) if name else {}
                        prompt, resumed = _split_resume(params, arr)
                        kw = dict(
                            seed=int(params.get("seed", 0)),
                            priority=int(params.get("priority", 0)),
                            resume=resumed)
                        mnt = int(params.get("max_new_tokens", 16))
                    if op == OP_SUBMIT:
                        new = router.generate(prompt, mnt, **kw)
                        # like the frontend: the reply is the FULL
                        # sequence, resume prefix included
                        full = (np.concatenate([resumed, new])
                                if resumed is not None else new)
                        reply = _encode(0, "", full)
                    elif op == OP_STREAM:
                        gen = router.stream(prompt, mnt, **kw)
                        emitted: List[int] = ([int(t) for t in resumed]
                                              if resumed is not None
                                              else [])
                        try:
                            try:
                                for tok in gen:
                                    emitted.append(tok)
                                    sock.sendall(_encode(
                                        0, "t",
                                        np.asarray([tok], np.int32)))
                                sock.sendall(_encode(
                                    0, "end",
                                    np.asarray(emitted, np.int32)))
                            except OSError:
                                # client went away: closing the
                                # generator tears the replica leg down,
                                # which triggers the replica-side eager
                                # cancel
                                return
                        finally:
                            gen.close()
                        continue
                    elif op == OP_STATS:
                        reply = _encode(
                            0, "", None,
                            json.dumps(router.stats()).encode())
                    elif op == OP_PING:
                        reply = _encode(0, "", None)
                    else:
                        reply = _encode(1, "", None,
                                        f"bad op {op}".encode())
                except Exception as e:
                    # typed errors (ReplicaLostError, replica-side
                    # rejections) ride the status=1 reply; the
                    # connection survives
                    reply = _encode(
                        1, "", None, f"{type(e).__name__}: {e}".encode())
                sock.sendall(reply)
        except Exception as e:  # pragma: no cover - teardown races
            bps_log.debug("router handler exit: %s", e)


class RouterFrontend(socketserver.ThreadingTCPServer):
    """TCP frontend over a :class:`ServeRouter` — wire-compatible with
    ``ServeFrontend``, so existing clients point at the router
    unchanged."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, router: ServeRouter):
        super().__init__(addr, _RouterHandler)
        self.router = router
        router.start()

    def server_close(self):
        self.router.close()
        super().server_close()


def serve_router(router: ServeRouter, port: int, host: str = "0.0.0.0",
                 in_thread: bool = False):
    """Run the router frontend.  ``in_thread=True`` returns
    ``(server, thread)`` for tests; otherwise blocks (launcher mode)."""
    srv = RouterFrontend((host, port), router)
    bps_log.info("byteps_tpu serve router listening on %s:%d over %d "
                 "replica(s)", host, srv.server_address[1],
                 len(router._replicas))
    from ..observability.scrape import maybe_start_metrics_server

    maybe_start_metrics_server(
        role="router",
        health_fn=lambda: {"replicas": router.replica_states()})
    if in_thread:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, t
    try:
        srv.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        srv.server_close()


def router_from_env(env=None) -> int:
    """Entry point for the launcher's ``router`` role: build the router
    from ``BYTEPS_ROUTER_*`` and block on the TCP frontend."""
    import os

    from ..common.config import get_config, reset_config

    if env is not None:
        os.environ.update({k: str(v) for k, v in env.items()
                           if k.startswith(("BYTEPS_", "DMLC_"))})
    reset_config()
    cfg = get_config()
    replicas = [a.strip() for a in cfg.router_replicas.split(",")
                if a.strip()]
    if not replicas:
        raise SystemExit(
            "byteps_tpu.launcher: the router role needs "
            "BYTEPS_ROUTER_REPLICAS=host:port,host:port (the serve "
            "replicas to fan out over)")
    router = ServeRouter(
        replicas,
        credits=cfg.router_credits,
        affinity=cfg.router_affinity,
        affinity_block=cfg.router_affinity_block,
        deadline=cfg.router_deadline_ms / 1e3,
        stream_timeout=cfg.router_stream_timeout_ms / 1e3,
        heartbeat_interval=cfg.router_heartbeat_ms / 1e3,
        miss_threshold=cfg.router_miss_threshold,
        ping_timeout=cfg.heartbeat_timeout_ms / 1e3,
        expected_weights_fp=cfg.router_weights_fp or None)
    serve_router(router, cfg.router_port)
    return 0
