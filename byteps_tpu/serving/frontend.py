"""Serving frontends: in-process ``ServeClient`` and a thin TCP server.

The TCP layer reuses the length-prefixed wire helpers of
``engine/ps_server.py`` (``_encode``/``_decode`` — the same u8-op,
raw-numpy-payload framing the PS tier speaks), so a serve process slots
into the launcher the way a PS shard does: ``DMLC_ROLE=serve`` runs
:func:`serve_from_env`.

Wire ops (request := the ps_server frame; one request per round trip):

    0 = SUBMIT  name = JSON {"max_new_tokens", "seed", "priority"}
                arr  = int32 prompt tokens [T]
                reply: status=0, name = request id, arr = int32 tokens;
                rejections (queue full, infeasible request) come back
                as status=1 with the typed error's message — the
                connection survives, clients can back off and retry.
    1 = STATS   reply payload = JSON engine metrics summary
    2 = PING    liveness

SUBMIT blocks the *connection* until the request finishes — per-request
streaming stays in-process (``Request.__iter__``); concurrency across
the wire comes from concurrent connections, which the engine batches
into one decode pool (that is the whole point of continuous batching).
"""

from __future__ import annotations

import json
import socketserver
import threading
from typing import List, Optional

import numpy as np

from ..common import logging as bps_log
from ..engine.ps_server import _decode, _encode
from ..engine.transport import (LocalEndpoints, maybe_nodelay,
                                resolve_transport, transport_connect)
from .engine import Request, ServingEngine
from .scheduler import AdmissionError

OP_SUBMIT, OP_STATS, OP_PING = range(3)

__all__ = ["ServeClient", "ServeFrontend", "RemoteServeClient", "serve",
           "serve_from_env", "OP_SUBMIT", "OP_STATS", "OP_PING"]


class ServeClient:
    """In-process client: submit -> stream tokens, cancel, drain.

    A thin convenience veneer over :class:`ServingEngine` that starts
    the background tick thread on first use and owns its shutdown."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0) -> Request:
        self.engine.start()
        return self.engine.submit(prompt, max_new_tokens, seed=seed,
                                  priority=priority)

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0):
        """Iterator of tokens as the engine emits them."""
        return iter(self.submit(prompt, max_new_tokens, seed=seed,
                                priority=priority))

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking submit -> full token array."""
        return self.submit(prompt, max_new_tokens, seed=seed,
                           priority=priority).result(timeout)

    def cancel(self, req: Request) -> None:
        self.engine.cancel(req)

    def drain(self, timeout: Optional[float] = None) -> None:
        self.engine.drain(timeout)

    def close(self) -> None:
        self.engine.stop()


# ------------------------------------------------------------------ TCP tier


class _ServeHandler(socketserver.BaseRequestHandler):
    def handle(self):  # one connection, many requests
        engine: ServingEngine = self.server.engine  # type: ignore
        sock = self.request
        maybe_nodelay(sock)
        try:
            while True:
                try:
                    op, name, arr, _ = _decode(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    if op == OP_SUBMIT:
                        params = json.loads(name) if name else {}
                        req = engine.submit(
                            np.asarray(arr, np.int32).reshape(-1),
                            int(params.get("max_new_tokens", 16)),
                            seed=int(params.get("seed", 0)),
                            priority=int(params.get("priority", 0)))
                        toks = req.result(
                            timeout=float(params.get("timeout", 300.0)))
                        reply = _encode(0, str(req.id), toks)
                    elif op == OP_STATS:
                        payload = json.dumps(
                            {**engine.metrics.summary(),
                             "compile_counts": engine.compile_counts(),
                             "occupancy": engine.pool.occupancy(),
                             "queue_depth": engine.scheduler.depth,
                             "prefix_cache": (engine.prefix.stats()
                                              if engine.prefix is not None
                                              else None),
                             # paged KV pool accounting (None on dense
                             # engines) — free/used/shared block counts
                             # next to the prefix stats they interact
                             # with (docs/serving.md "Paged KV cache")
                             "kv_blocks": (engine.pool.block_stats()
                                           if engine.paged else None),
                             # the same registry snapshot /metrics.json
                             # serves — one stats surface, two transports
                             # (docs/observability.md)
                             "metrics": engine.metrics.registry.snapshot()})
                        reply = _encode(0, "", None, payload.encode())
                    elif op == OP_PING:
                        reply = _encode(0, "", None)
                    else:
                        reply = _encode(1, "", None,
                                        f"bad op {op}".encode())
                except AdmissionError as e:
                    # typed backpressure: status=1 + reason, socket lives
                    reply = _encode(1, "", None,
                                    f"{type(e).__name__}: {e}".encode())
                except Exception as e:
                    reply = _encode(
                        1, "", None, f"{type(e).__name__}: {e}".encode())
                sock.sendall(reply)
        except Exception as e:  # pragma: no cover - teardown races
            bps_log.debug("serve handler exit: %s", e)


class ServeFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, engine: ServingEngine):
        super().__init__(addr, _ServeHandler)
        self.engine = engine
        # colocated fast path (docs/wire.md "Transports"): advertise a
        # UDS + shm rendezvous next to the TCP port, served by the SAME
        # handler over the same engine, unless pinned to TCP
        self.local_endpoints = None
        from ..common.config import get_config

        if get_config().transport != "tcp":
            try:
                self.local_endpoints = LocalEndpoints(
                    self.server_address[1], _ServeHandler, self)
            except ValueError:
                super().server_close()
                raise
            except OSError as e:
                bps_log.warning(
                    "serve frontend: local transport endpoints "
                    "unavailable (%s); serving TCP only", e)
        engine.start()

    def server_close(self):
        if self.local_endpoints is not None:
            self.local_endpoints.close()
        self.engine.stop()
        super().server_close()


def serve(engine: ServingEngine, port: int, host: str = "0.0.0.0",
          in_thread: bool = False):
    """Run the TCP frontend over ``engine``.  ``in_thread=True`` returns
    ``(server, thread)`` for tests; otherwise blocks (launcher mode)."""
    srv = ServeFrontend((host, port), engine)
    bps_log.info("byteps_tpu serve frontend listening on %s:%d",
                 host, srv.server_address[1])
    # live scrape endpoint (BYTEPS_METRICS_PORT; off by default) — the
    # HTTP twin of the TCP STATS op (docs/observability.md)
    from ..observability.scrape import maybe_start_metrics_server

    maybe_start_metrics_server(
        role="serve",
        health_fn=lambda: {"occupancy": engine.pool.occupancy(),
                           "queue_depth": engine.scheduler.depth})
    if in_thread:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, t
    try:
        srv.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        srv.server_close()


class RemoteServeClient:
    """Client for the serve frontend (same framing as ``RemoteStore``).
    ``transport`` is resolved per endpoint exactly like the PS
    client's (``auto`` default: UDS/shm for a colocated frontend, TCP
    otherwise — docs/wire.md "Transports")."""

    def __init__(self, addr: str, timeout: float = 300.0,
                 transport: Optional[str] = None):
        from ..common.config import get_config

        kind, path = resolve_transport(
            addr, transport if transport else get_config().transport)
        self.transport = kind
        self._sock = transport_connect(kind, path, addr, timeout=timeout)
        self._lock = threading.Lock()

    def _rpc(self, op: int, name: str = "", arr=None):
        with self._lock:
            self._sock.sendall(_encode(op, name, arr))
            status, rname, out, payload = _decode(self._sock)
        if status != 0:
            raise RuntimeError(f"serve error: {payload.decode()!r}")
        return rname, out, payload

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0) -> np.ndarray:
        params = json.dumps({"max_new_tokens": max_new_tokens,
                             "seed": seed, "priority": priority})
        _, out, _ = self._rpc(OP_SUBMIT, params,
                              np.asarray(prompt, np.int32).reshape(-1))
        return np.array(out)

    def stats(self) -> dict:
        _, _, payload = self._rpc(OP_STATS)
        return json.loads(payload.decode())

    def ping(self) -> bool:
        try:
            self._rpc(OP_PING)
            return True
        except (OSError, RuntimeError):
            return False

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------ launcher role


def _model_from_env(cfg_str: str):
    """Build a (model, variables) pair from ``BYTEPS_SERVE_MODEL``: a
    comma-separated ``k=v`` list over TransformerConfig's integer axes
    (vocab_size, num_layers, num_heads, d_model, d_ff, max_seq_len) —
    random-initialized weights unless ``BYTEPS_SERVE_CHECKPOINT`` points
    at a checkpoint produced by ``training.checkpoint``.  A serving
    process with random weights is still the real engine — that is what
    the smoke/bench tooling runs against."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import Transformer, TransformerConfig

    kw = {}
    if cfg_str:
        for pair in cfg_str.split(","):
            k, _, v = pair.partition("=")
            kw[k.strip()] = int(v)
    kw.setdefault("vocab_size", 256)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_model", 128)
    kw.setdefault("d_ff", 256)
    kw.setdefault("max_seq_len", 512)
    cfg = TransformerConfig(dtype=jnp.float32, **kw)
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    return model, variables


def serve_from_env(env=None) -> int:
    """Entry point for the launcher's ``serve`` role: build the engine
    from ``BYTEPS_SERVE_*`` and block on the TCP frontend.  An explicit
    ``env`` mapping overrides the process environment for the
    ``BYTEPS_*``/``DMLC_*`` keys it carries; either way the cached
    process config is reset first, so knobs set after an earlier
    ``get_config()`` call are honored."""
    import os

    from ..common.config import get_config, reset_config

    if env is not None:
        os.environ.update({k: str(v) for k, v in env.items()
                           if k.startswith(("BYTEPS_", "DMLC_"))})
    reset_config()
    cfg = get_config()
    model, variables = _model_from_env(cfg.serve_model)
    if cfg.serve_checkpoint:
        from ..training.checkpoint import restore_checkpoint

        variables = {"params": restore_checkpoint(
            cfg.serve_checkpoint, variables["params"], broadcast=False)}
    engine = ServingEngine(
        model, variables,
        n_slots=cfg.serve_slots,
        max_seq=(cfg.serve_max_seq or model.cfg.max_seq_len),
        temperature=cfg.serve_temperature,
        top_k=cfg.serve_top_k, top_p=cfg.serve_top_p,
        eos_id=cfg.serve_eos_id,
        max_queue=cfg.serve_max_queue,
        prefill_credits=cfg.serve_prefill_credits,
        chunk=cfg.serve_chunk,
        prefix_cache=cfg.serve_prefix_cache,
        prefix_block=cfg.serve_prefix_block,
        prefix_bytes=cfg.serve_prefix_mb << 20,
        paged=cfg.serve_paged,
        block=cfg.serve_block,
        kv_mb=cfg.serve_kv_mb)
    serve(engine, cfg.serve_port)
    return 0
