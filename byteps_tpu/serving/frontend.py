"""Serving frontends: in-process ``ServeClient`` and a thin TCP server.

The TCP layer reuses the length-prefixed wire helpers of
``engine/ps_server.py`` (``_encode``/``_decode`` — the same u8-op,
raw-numpy-payload framing the PS tier speaks), so a serve process slots
into the launcher the way a PS shard does: ``DMLC_ROLE=serve`` runs
:func:`serve_from_env`.

Wire ops (request := the ps_server frame; one request per round trip,
except STREAM whose reply is a frame *sequence*):

    0 = SUBMIT  name = JSON {"max_new_tokens", "seed", "priority",
                             "resume"}
                arr  = int32 prompt tokens [T] (with ``resume`` = k > 0
                the trailing k entries are tokens another replica
                already emitted — the router's failover re-dispatch;
                the engine resumes the stream bit-exactly)
                reply: status=0, name = request id, arr = int32 tokens;
                rejections (queue full, infeasible request) come back
                as status=1 with the typed error's message — the
                connection survives, clients can back off and retry.
    1 = STATS   reply payload = JSON engine metrics summary
    2 = PING    liveness
    3 = STREAM  same request frame as SUBMIT; the reply is one frame
                per emitted token (status=0, name="t", arr=[tok]) and
                a terminal frame (status=0, name="end", arr = the full
                token sequence).  A status=1 frame at any point carries
                a typed error message and ends the stream.  This is
                what lets the router record how far a stream got before
                a replica died — the failover re-dispatch resumes from
                exactly the tokens that crossed the wire.
    4 = CANCEL  name = JSON {"rid"}: eagerly cancel the in-flight
                request that was submitted with that caller-chosen
                ``rid`` param — from a SECOND connection, since the
                streaming one is busy relaying tokens.  The engine's
                eager cancel reclaims the slot (and paged KV blocks)
                same-tick; the cancelled stream ends with its normal
                "end" frame carrying whatever was emitted.  A rid that
                has not arrived yet is tombstoned so a cancel racing
                its own submit still lands (bounded set).  Reply
                payload = JSON {"cancelled": bool}.
    5 = JOURNAL name = JSON list of router-HA journal entries (routers
                only — serving/router.py streams active-router state to
                standbys through it; a plain serve frontend answers
                "bad op").  Reply payload = JSON {"epoch": receiver's
                epoch} — how a deposed active discovers a takeover
                happened (split-brain guard on the journal path).

SUBMIT/STREAM params may also carry ``epoch`` (the dispatching
router's fencing token — the engine refuses values below its
high-water with the typed ``EpochFencedError``, the split-brain guard
of docs/serving.md "Router HA"), ``rid`` (caller-chosen request id for
OP_CANCEL) and ``tenant`` (fair-share accounting at the router tier;
replicas ignore it).

SUBMIT blocks the *connection* until the request finishes — per-request
streaming rides OP_STREAM (or stays in-process via
``Request.__iter__``); concurrency across the wire comes from
concurrent connections, which the engine batches into one decode pool
(that is the whole point of continuous batching).

A client socket that disappears mid-STREAM triggers the engine's eager
``cancel()`` path: the slot (and on paged engines the non-shared KV
blocks and prefix references) returns to the pool the same tick the
broken pipe is noticed, not when the abandoned request would have
finished.
"""

from __future__ import annotations

import collections
import json
import socketserver
import threading
import time
from typing import List, Optional

import numpy as np

from ..common import logging as bps_log
from ..engine.ps_server import _decode, _encode
from ..engine.transport import (LocalEndpoints, maybe_nodelay,
                                resolve_transport, transport_connect)
from ..engine.wire import hard_reset
from .engine import Request, ServingEngine
from .scheduler import AdmissionError

OP_SUBMIT, OP_STATS, OP_PING, OP_STREAM, OP_CANCEL, OP_JOURNAL = range(6)
# disaggregated prefill/decode (serving/disagg, docs/serving.md
# "Disaggregated tiers"): one frame per shipped KV block — name = JSON
# {"key","i","n","pos","geom","digest"}, payload = the block's raw K/V
# bytes.  Replies: status=0 JSON ack, or status=1 with a typed
# KVShip* error name the sender maps to retry/abort.
OP_KV_BLOCKS = 6

__all__ = ["ServeClient", "ServeFrontend", "RemoteServeClient",
           "ServeConnectionError", "ServeReplyError", "serve",
           "serve_from_env", "OP_SUBMIT", "OP_STATS", "OP_PING",
           "OP_STREAM", "OP_CANCEL", "OP_JOURNAL", "OP_KV_BLOCKS"]


class ServeConnectionError(ConnectionError):
    """The serve frontend (or router) went away mid-conversation — the
    connection died or stalled past the client timeout.  Typed so
    callers can distinguish a dead endpoint (retry elsewhere / fail
    over) from a replica-side error reply (status=1 ``RuntimeError``,
    which would recur on retry)."""


# status=1 error names a multi-router client may safely re-issue to
# ANOTHER router: the refusal says "this router cannot serve you", not
# "your request is wrong".  Everything else is non-retryable by default
# — a typed refusal that would recur (WeightsMismatchError, ValueError,
# QueueFullError backpressure, a tier-wide ReplicaLostError) must
# surface to the caller, never be retried as if the router were dead.
_RETRYABLE_REPLY_NAMES = frozenset({"RouterStandbyError"})

# status=1 names that mean "shed under overload" (the router's
# SLO-class admission door — docs/serving.md "Elastic capacity & SLO
# classes"): NOT router-rotation-retryable (every router fronts the
# same saturated tier; rotating would just burn the deadline), but
# safe for the CALLER to retry with backoff — the request was never
# placed.  ``ServeReplyError.shed`` flags them.
_SHED_REPLY_NAMES = frozenset({"OverloadShedError"})


class ServeReplyError(RuntimeError):
    """A status=1 reply frame: the endpoint is alive and answered with
    a typed error.  ``name`` is the server-side error class name parsed
    off the payload; ``retryable`` tells the multi-router failover loop
    whether re-issuing the request to the NEXT router can possibly
    help (a standby refusal) or the refusal would recur anywhere
    (weights mismatch, infeasible request, tier failure) — retrying
    those as if the router were dead would burn the deadline repeating
    a deterministic error.  ``shed`` marks an SLO-class overload shed:
    back off and resubmit later (``retryable`` stays False — a
    DIFFERENT router cannot help, only time can)."""

    def __init__(self, msg: str, name: str = ""):
        self.name = name
        self.retryable = name in _RETRYABLE_REPLY_NAMES
        self.shed = name in _SHED_REPLY_NAMES
        super().__init__(msg)


class ServeClient:
    """In-process client: submit -> stream tokens, cancel, drain.

    A thin convenience veneer over :class:`ServingEngine` that starts
    the background tick thread on first use and owns its shutdown."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0) -> Request:
        self.engine.start()
        return self.engine.submit(prompt, max_new_tokens, seed=seed,
                                  priority=priority)

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0):
        """Iterator of tokens as the engine emits them."""
        return iter(self.submit(prompt, max_new_tokens, seed=seed,
                                priority=priority))

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking submit -> full token array."""
        return self.submit(prompt, max_new_tokens, seed=seed,
                           priority=priority).result(timeout)

    def cancel(self, req: Request) -> None:
        self.engine.cancel(req)

    def drain(self, timeout: Optional[float] = None) -> None:
        self.engine.drain(timeout)

    def close(self) -> None:
        self.engine.stop()


# ------------------------------------------------------------------ TCP tier


def _split_resume(params: dict, arr):
    """THE wire contract for SUBMIT/STREAM request arrays: ``resume`` =
    k > 0 marks the trailing k entries as already-emitted tokens (a
    failover re-dispatch or client retry); the rest is the prompt.
    Shared by the serve frontend and the router so the two tiers can
    never silently disagree on the frame layout."""
    toks = np.asarray(arr, np.int32).reshape(-1)
    k = int(params.get("resume", 0))
    return (toks[:-k], toks[-k:]) if k > 0 else (toks, None)


def _wire_cancel(addr: str, params: dict, timeout: Optional[float],
                 transport_pref: Optional[str] = None) -> bool:
    """One OP_CANCEL round-trip on a fresh short-lived connection —
    the single wire implementation behind ``RemoteServeClient.cancel``
    and the router's replica-side forward (which would otherwise pay a
    second, unused connection just to construct a client)."""
    kind, path = resolve_transport(addr, transport_pref)
    try:
        s = transport_connect(kind, path, addr, timeout=timeout)
    except OSError as e:
        raise ServeConnectionError(
            f"serve frontend {addr} unreachable for cancel: "
            f"{e}") from e
    try:
        s.sendall(_encode(OP_CANCEL, json.dumps(params), None))
        status, _, _, payload = _decode(s)
    except (ConnectionError, OSError, ValueError) as e:
        raise ServeConnectionError(
            f"serve frontend {addr} died mid-cancel: "
            f"{e}") from e
    finally:
        try:
            s.close()
        except OSError:
            pass
    if status != 0:
        msg = payload.decode()
        raise ServeReplyError(f"serve error: {msg!r}",
                              name=msg.split(":", 1)[0].strip())
    return bool(json.loads(payload.decode()).get("cancelled"))


def _parse_submit(engine: ServingEngine, name: str, arr, stager=None):
    """Decode a SUBMIT/STREAM frame into an engine submit.

    Disagg params (docs/serving.md "Disaggregated tiers"): a PREFILL
    dispatch carries ``ship_to`` (the decode replica's address) +
    ``kv_ship`` (the ship id) — the engine parks the finished KV for
    the post-reply ship.  A DECODE dispatch carries ``kv_ship`` alone:
    the staged blocks are claimed from the stager here and adopted at
    admission in place of re-prefill; a missing/partial staging just
    means normal (re-)prefill — never a wrong answer."""
    params = json.loads(name) if name else {}
    prompt, resumed = _split_resume(params, arr)
    kv = None
    if (stager is not None and params.get("kv_ship")
            and not params.get("ship_to")):
        staged = stager.take(str(params["kv_ship"]))
        if staged is not None:
            if staged["pos"] == int(prompt.shape[0]):
                kv = staged["ids"]
            else:
                engine.release_kv_ids(staged["ids"])
    # the router-epoch fence rides INTO the submit so check and
    # admission are atomic: a deposed router's dispatch must be refused
    # typed, never admitted (the split-brain guard — docs/serving.md
    # "Router HA")
    try:
        req = engine.submit(
            prompt, int(params.get("max_new_tokens", 16)),
            seed=int(params.get("seed", 0)),
            priority=int(params.get("priority", 0)),
            resume_tokens=resumed,
            epoch=params.get("epoch"),
            keep_kv=bool(params.get("ship_to")),
            kv_blocks=kv)
    except Exception:
        # the engine takes block ownership only on a successful return
        engine.release_kv_ids(kv)
        raise
    return req, params


class _ServeHandler(socketserver.BaseRequestHandler):
    def setup(self):
        track = getattr(self.server, "_track_conn", None)
        if track is not None:
            track(self.request)

    def _stream(self, engine: ServingEngine, sock, req: Request) -> bool:
        """Relay ``req``'s tokens as one frame each, then the terminal
        frame.  Returns False when the CLIENT went away — the caller
        must stop serving this connection; the request is eagerly
        cancelled so its slot (and paged KV blocks) free this tick."""
        try:
            for tok in req:
                sock.sendall(_encode(0, "t", np.asarray([tok], np.int32)))
            sock.sendall(_encode(0, "end",
                                 np.asarray(req.tokens, np.int32)))
            return True
        except RuntimeError as e:
            # engine died mid-stream: a typed status=1 frame ends the
            # stream loudly (the iterator already drained to _END)
            try:
                sock.sendall(_encode(1, "", None,
                                     f"{type(e).__name__}: {e}".encode()))
            except OSError:
                pass
            return True
        except OSError:
            # client disconnected mid-stream: eager-cancel so the slot
            # and non-shared blocks are reclaimed same-tick, not when
            # the abandoned stream would have finished
            engine.cancel(req)
            return False

    def handle(self):  # one connection, many requests
        engine: ServingEngine = self.server.engine  # type: ignore
        sock = self.request
        maybe_nodelay(sock)
        try:
            while True:
                try:
                    op, name, arr, payload_in = _decode(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    if op in (OP_SUBMIT, OP_STREAM):
                        req, params = _parse_submit(
                            engine, name, arr,
                            stager=self.server.kv_stager(create=False))
                        rid = params.get("rid")
                        if rid and self.server.register_rid(str(rid),
                                                            req):
                            # an OP_CANCEL for this rid raced ahead of
                            # the submit (tombstoned): honor it now
                            engine.cancel(req)
                        try:
                            if op == OP_SUBMIT:
                                toks = req.result(timeout=float(
                                    params.get("timeout", 300.0)))
                                if params.get("ship_to"):
                                    # disagg prefill leg: ship the
                                    # parked KV AFTER the request
                                    # finished, report the outcome in
                                    # the reply name (the router's
                                    # prefill_ship reads it; plain
                                    # clients never set ship_to)
                                    info = self.server.ship_kv(
                                        req, params)
                                    reply = _encode(
                                        0, json.dumps(info), toks)
                                else:
                                    reply = _encode(0, str(req.id),
                                                    toks)
                            else:
                                if not self._stream(engine, sock, req):
                                    return
                                continue
                        finally:
                            if rid:
                                self.server.unregister_rid(str(rid),
                                                           req)
                    elif op == OP_CANCEL:
                        params = json.loads(name) if name else {}
                        if "epoch" in params:
                            # a deposed router must not cancel work the
                            # takeover epoch re-dispatched; the fence
                            # stays held across the cancel so a newer
                            # epoch's re-dispatch cannot interleave
                            # between check and cancel
                            with engine.epoch_fence(
                                    int(params["epoch"])):
                                ok = self.server.cancel_rid(
                                    str(params.get("rid", "")))
                        else:
                            ok = self.server.cancel_rid(
                                str(params.get("rid", "")))
                        reply = _encode(
                            0, "", None,
                            json.dumps({"cancelled": ok}).encode())
                    elif op == OP_STATS:
                        payload = json.dumps(
                            {**engine.metrics.summary(),
                             # engine identity: the weights fingerprint
                             # the router's registration handshake
                             # compares before trusting this replica
                             # with resumes (serving/router.py)
                             "weights_fingerprint": engine.weights_fp,
                             "compile_counts": engine.compile_counts(),
                             "occupancy": engine.pool.occupancy(),
                             "queue_depth": engine.scheduler.depth,
                             "prefix_cache": (engine.prefix.stats()
                                              if engine.prefix is not None
                                              else None),
                             # paged KV pool accounting (None on dense
                             # engines) — free/used/shared block counts
                             # next to the prefix stats they interact
                             # with (docs/serving.md "Paged KV cache")
                             "kv_blocks": (engine.pool.block_stats()
                                           if engine.paged else None),
                             # the same registry snapshot /metrics.json
                             # serves — one stats surface, two transports
                             # (docs/observability.md)
                             "metrics": engine.metrics.registry.snapshot()})
                        reply = _encode(0, "", None, payload.encode())
                    elif op == OP_KV_BLOCKS:
                        # disagg decode leg: one shipped KV block into
                        # the stager (serving/disagg/ship.py owns the
                        # sequence/digest/geometry verification)
                        reply = self.server.kv_stager().handle(
                            name, payload_in)
                    elif op == OP_PING:
                        reply = _encode(0, "", None)
                    else:
                        reply = _encode(1, "", None,
                                        f"bad op {op}".encode())
                except AdmissionError as e:
                    # typed backpressure: status=1 + reason, socket lives
                    reply = _encode(1, "", None,
                                    f"{type(e).__name__}: {e}".encode())
                except Exception as e:
                    reply = _encode(
                        1, "", None, f"{type(e).__name__}: {e}".encode())
                sock.sendall(reply)
        except Exception as e:  # pragma: no cover - teardown races
            bps_log.debug("serve handler exit: %s", e)


class ServeFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, engine: ServingEngine):
        super().__init__(addr, _ServeHandler)
        self.engine = engine
        # live client sockets, so kill() can die like a crashed process
        # (sever mid-stream connections, not just stop accepting)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._killing = False
        # OP_CANCEL bookkeeping: caller-chosen rid -> in-flight Request,
        # plus a bounded tombstone set for cancels that raced ahead of
        # their own submit (the registering handler then cancels
        # immediately instead of the cancel being silently lost)
        self._rids: dict = {}
        self._rid_lock = threading.Lock()
        self._rid_tombs: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        # recently-FINISHED rids (bounded): a cancel arriving after its
        # request completed is "too late", not "too early" — without
        # this it would be tombstoned and silently cancel the next
        # request reusing the rid at admission
        self._rid_done: "collections.OrderedDict[str, None]" = \
            collections.OrderedDict()
        # disagg KV stager (decode replicas; serving/disagg/ship.py) —
        # built lazily on the first OP_KV_BLOCKS frame, because only
        # paged engines can stage and most frontends never receive one
        self._kv_stager = None
        self._kv_stager_lock = threading.Lock()
        # colocated fast path (docs/wire.md "Transports"): advertise a
        # UDS + shm rendezvous next to the TCP port, served by the SAME
        # handler over the same engine, unless pinned to TCP
        self.local_endpoints = None
        from ..common.config import get_config

        if get_config().transport != "tcp":
            try:
                self.local_endpoints = LocalEndpoints(
                    self.server_address[1], _ServeHandler, self)
            except ValueError:
                super().server_close()
                raise
            except OSError as e:
                bps_log.warning(
                    "serve frontend: local transport endpoints "
                    "unavailable (%s); serving TCP only", e)
        engine.start()

    # ------------------------------------------------- disagg KV ship

    def kv_stager(self, create: bool = True):
        """The engine's KV stager (decode side of a disagg ship).
        ``create=False`` returns None until the first OP_KV_BLOCKS
        frame built it — the submit path's claim probe must not pay a
        stager on frontends that never receive ships.  Raises typed on
        a dense engine: there is no block pool to stage into."""
        with self._kv_stager_lock:
            if self._kv_stager is None and create:
                from .disagg.ship import KVShipGeometryError, KVStager

                if not self.engine.paged:
                    raise KVShipGeometryError(
                        "this replica's engine is dense (paged=False) "
                        "— it cannot stage shipped KV blocks")
                self._kv_stager = KVStager(self.engine)
            return self._kv_stager

    def ship_kv(self, req: Request, params: dict) -> dict:
        """Prefill leg: ship ``req``'s parked KV to the decode replica
        named by ``ship_to``.  Never raises — every failure downgrades
        to ``{"shipped": False, "error": ...}`` alongside the (valid)
        token reply, and the router re-prefills decode-side."""
        from .disagg.ship import KVShipError, ship_parked

        parked = self.engine.take_parked_kv(req.id)
        if parked is None:
            return {"shipped": False,
                    "error": "no parked KV (dense engine, non-DONE "
                             "finish, or parked-cap eviction)"}
        try:
            return ship_parked(
                self.engine, str(params["ship_to"]),
                str(params.get("kv_ship", req.id)), parked,
                metrics=self.engine.metrics)
        except KVShipError as e:
            bps_log.warning("disagg ship for request %d failed: %s",
                            req.id, e)
            return {"shipped": False,
                    "error": f"{type(e).__name__}: {e}"}
        finally:
            self.engine.release_kv_ids(parked["ids"])

    # ------------------------------------------------ OP_CANCEL registry

    def register_rid(self, rid: str, req: Request) -> bool:
        """Associate a caller-chosen request id with its in-flight
        engine request.  Returns True when an OP_CANCEL for this rid
        already arrived (tombstoned) — the caller must cancel the
        request immediately."""
        with self._rid_lock:
            self._rids[rid] = req
            self._rid_done.pop(rid, None)  # the rid is live again
            tombed = rid in self._rid_tombs
            if tombed:
                del self._rid_tombs[rid]
            return tombed

    def unregister_rid(self, rid: str, req: Optional[Request] = None
                       ) -> None:
        """Drop the registration — only while it still points at
        ``req`` (a stalled old leg finishing late must not clobber a
        re-dispatch's newer registration of the same rid), and record
        the rid as recently finished."""
        with self._rid_lock:
            if req is not None and self._rids.get(rid) is not req:
                return
            self._rids.pop(rid, None)
            self._rid_done[rid] = None
            while len(self._rid_done) > 1024:
                self._rid_done.popitem(last=False)

    def cancel_rid(self, rid: str) -> bool:
        """Cancel the in-flight request registered under ``rid`` (the
        engine's eager cancel: slot + non-shared paged blocks reclaimed
        same-tick).  An unknown rid is tombstoned (bounded) so a cancel
        racing AHEAD of its own submit still lands — unless the rid
        recently FINISHED here, in which case the cancel is simply too
        late (tombstoning it would cancel the next request reusing the
        rid).  Returns whether a live request was cancelled."""
        with self._rid_lock:
            req = self._rids.get(rid)
            if req is None:
                if rid not in self._rid_done:
                    self._rid_tombs[rid] = None
                    while len(self._rid_tombs) > 1024:
                        self._rid_tombs.popitem(last=False)
                return False
        self.engine.cancel(req)
        return True

    def _track_conn(self, sock) -> None:
        with self._conns_lock:
            # the _killing check must share kill()'s critical section:
            # checked outside it, a handler could pass the check, block
            # on the lock while kill() swaps the set, and then register
            # a connection nobody will ever reset
            if not self._killing:
                self._conns.add(sock)
                # drop references the handlers already finished with
                self._conns = {s for s in self._conns
                               if s.fileno() != -1}
                return
        # a connection that slipped through between kill() and the
        # listener actually closing (socketserver's shutdown can lag a
        # poll interval): a dead process serves nobody
        hard_reset(sock)

    def kill(self) -> None:
        """Die like a crashed replica (the PSServer.kill discipline):
        hard-reset every live client connection AND stop accepting, so
        in-flight streams see ECONNRESET mid-frame — what the router's
        failover path (and RemoteServeClient's typed
        ``ServeConnectionError``) must absorb.  Connections are severed
        FIRST: ``shutdown()`` can wait up to the serve_forever poll
        interval, and a fast engine would stream a whole request's
        remaining tokens into the socket in that window — a crash cuts
        the wire mid-token, so the kill must too (and ``_killing``
        makes any connection accepted inside that window die
        unserved).  Chaos/test only."""
        self._killing = True
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            hard_reset(c)
        self.shutdown()
        if self.local_endpoints is not None:
            self.local_endpoints.close(unlink=False)
        self.server_close()

    def server_close(self):
        if self.local_endpoints is not None:
            self.local_endpoints.close()
        self.engine.stop()
        super().server_close()


def serve(engine: ServingEngine, port: int, host: str = "0.0.0.0",
          in_thread: bool = False):
    """Run the TCP frontend over ``engine``.  ``in_thread=True`` returns
    ``(server, thread)`` for tests; otherwise blocks (launcher mode)."""
    srv = ServeFrontend((host, port), engine)
    bps_log.info("byteps_tpu serve frontend listening on %s:%d",
                 host, srv.server_address[1])
    # live scrape endpoint (BYTEPS_METRICS_PORT; off by default) — the
    # HTTP twin of the TCP STATS op (docs/observability.md)
    from ..observability.scrape import maybe_start_metrics_server

    maybe_start_metrics_server(
        role="serve",
        health_fn=lambda: {"occupancy": engine.pool.occupancy(),
                           "queue_depth": engine.scheduler.depth})
    if in_thread:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, t
    try:
        srv.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        srv.server_close()


def _submit_frame(op: int, prompt, max_new_tokens: int, seed: int,
                  priority: int, resume, extra: Optional[dict] = None
                  ) -> bytes:
    """Encode a SUBMIT/STREAM request: the resume tokens (if any) ride
    the tail of the token array, counted by the ``resume`` param.
    ``extra`` carries the optional wire params (``epoch``/``rid``/
    ``tenant``) — omitted entirely when unused, so frames stay
    bit-identical to the pre-HA wire for plain clients."""
    resume = ([] if resume is None
              else [int(t) for t in resume])
    p = {"max_new_tokens": max_new_tokens, "seed": seed,
         "priority": priority, "resume": len(resume)}
    if extra:
        p.update({k: v for k, v in extra.items() if v is not None})
    toks = np.concatenate([np.asarray(prompt, np.int32).reshape(-1),
                           np.asarray(resume, np.int32)])
    return _encode(op, json.dumps(p), toks)


class RemoteServeClient:
    """Client for the serve frontend (same framing as ``RemoteStore``).
    ``transport`` is resolved per endpoint exactly like the PS
    client's (``auto`` default: UDS/shm for a colocated frontend, TCP
    otherwise — docs/wire.md "Transports").

    Every wire read is bounded by ``timeout`` (default: the
    ``BYTEPS_SERVE_CLIENT_TIMEOUT_MS`` knob), and a dead or stalled
    frontend surfaces as the typed :class:`ServeConnectionError` on
    ``generate()``/``stream()`` — promptly, never an indefinite hang.
    One in-flight ``stream()`` per client (it holds the connection).

    **Multi-router failover** (docs/serving.md "Router HA"): ``addr``
    may be a comma-separated router list.  A ``ServeConnectionError``
    mid-call (dead router) or a *retryable* typed refusal (a standby
    router answering before takeover) rotates to the next address and
    re-issues the request — mid-stream with ``resume=`` the tokens
    already received, which the PR 10 resume argument makes
    token-identical, one tier higher.  Non-retryable typed errors
    (``ServeReplyError.retryable`` False — e.g. a
    ``WeightsMismatchError`` surfaced through a router) propagate
    immediately: re-issuing a deterministic refusal elsewhere would
    only burn the deadline.  The whole failover loop is bounded by
    ``timeout``."""

    def __init__(self, addr: str, timeout: Optional[float] = None,
                 transport: Optional[str] = None):
        from ..common.config import get_config

        cfg = get_config()
        self._addrs = [a.strip() for a in str(addr).split(",")
                       if a.strip()]
        if not self._addrs:
            raise ValueError("RemoteServeClient needs at least one "
                             "address")
        self._transport_pref = (transport if transport
                                else cfg.transport)
        self.timeout = (timeout if timeout is not None
                        else cfg.serve_client_timeout_ms / 1e3)
        self._lock = threading.Lock()
        self._cur = 0
        self._sock = None
        # set when a stream() was abandoned mid-flight: the server
        # keeps sending that stream's frames, so the connection can no
        # longer pair requests with replies — every later op would
        # silently read the orphaned frames as its reply.  A
        # single-address client stays poisoned (the historical
        # contract); a multi-router client clears it by reconnecting.
        self._poisoned = False
        if len(self._addrs) == 1:
            self._connect(0)  # eager — the single-endpoint contract
        else:
            self._connect_any()

    # ------------------------------------------------------- connections

    def _connect(self, idx: int) -> None:
        a = self._addrs[idx]
        kind, path = resolve_transport(a, self._transport_pref)
        self.addr = a
        self.transport = kind
        self._sock = transport_connect(kind, path, a,
                                       timeout=self.timeout)
        self._poisoned = False
        self._cur = idx

    def _connect_any(self) -> None:
        """Connect to the first reachable address starting at the
        current cursor (lock held or single-threaded init)."""
        errs = []
        for j in range(len(self._addrs)):
            idx = (self._cur + j) % len(self._addrs)
            try:
                self._connect(idx)
                return
            except OSError as e:
                errs.append(f"{self._addrs[idx]}: {e}")
        raise ServeConnectionError(
            f"no serve endpoint reachable: {'; '.join(errs)}")

    def _rotate_locked(self) -> None:
        """Drop the current connection and point the cursor at the
        next address (lock held); the next call reconnects."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._poisoned = False
        self._cur = (self._cur + 1) % len(self._addrs)

    def _check_usable(self) -> None:
        """Call with ``self._lock`` held: the poison flag is written
        under the same lock (a check outside it could pass while the
        abandoning thread is still inside the stream's critical
        section).  A multi-router client reconnects out of a poisoned
        or dropped connection instead of failing — the failover loop
        owns bounding that."""
        if (self._poisoned or self._sock is None) \
                and len(self._addrs) > 1:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            self._connect_any()
            return
        if self._poisoned:
            raise ServeConnectionError(
                f"client for {self.addr} abandoned an in-flight "
                f"stream(); the connection is desynced — open a new "
                f"RemoteServeClient")

    def _send(self, frame: bytes) -> None:
        """One frame out, with wire-level death typed (lock held)."""
        try:
            self._sock.sendall(frame)
        except (ConnectionError, OSError) as e:
            raise ServeConnectionError(
                f"serve frontend {self.addr} unreachable: {e}") from e

    def _read_frame(self):
        """One reply frame, with wire-level death typed and status=1
        replies raised as :class:`ServeReplyError` (its ``retryable``
        flag drives the multi-router failover loop)."""
        try:
            status, rname, out, payload = _decode(self._sock)
        except (ConnectionError, OSError, ValueError) as e:
            raise ServeConnectionError(
                f"serve frontend {self.addr} died or stalled "
                f"mid-conversation ({type(e).__name__}: {e}); "
                f"timeout={self.timeout}s") from e
        if status != 0:
            msg = payload.decode()
            raise ServeReplyError(f"serve error: {msg!r}",
                                  name=msg.split(":", 1)[0].strip())
        return rname, out, payload

    def _rpc(self, op: int, name: str = "", arr=None):
        with self._lock:
            self._check_usable()
            self._send(_encode(op, name, arr))
            return self._read_frame()

    @staticmethod
    def _extra(epoch, rid, tenant, extra=None,
               slo=None) -> Optional[dict]:
        out = dict(extra) if extra else {}
        if epoch is not None:
            out["epoch"] = epoch
        if rid is not None:
            out["rid"] = rid
        if tenant is not None:
            out["tenant"] = tenant
        if slo is not None:
            out["slo"] = slo
        return out or None

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0, resume=None, epoch=None, rid=None,
                 tenant=None, slo=None, extra=None) -> np.ndarray:
        """Blocking submit -> the full token array.  Raises the typed
        :class:`ServeConnectionError` when the frontend dies first
        (after the deadline-bounded failover loop, on a multi-router
        client).  ``slo`` = the request's SLO class wire param
        (``guaranteed``/``standard``/``best-effort`` — a router may
        shed it typed, ``ServeReplyError.shed``).  ``extra`` =
        additional wire params merged into the submit frame (the
        router's disagg ``kv_ship`` hand-off rides here —
        docs/serving.md "Disaggregated tiers")."""
        if len(self._addrs) == 1:
            return self._generate_once(prompt, max_new_tokens,
                                       seed=seed, priority=priority,
                                       resume=resume, epoch=epoch,
                                       rid=rid, tenant=tenant,
                                       slo=slo, extra=extra)
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                return self._generate_once(
                    prompt, max_new_tokens, seed=seed,
                    priority=priority, resume=resume, epoch=epoch,
                    rid=rid, tenant=tenant, slo=slo, extra=extra)
            except (ServeConnectionError, ServeReplyError) as e:
                self._note_failover(e, deadline)

    def _generate_once(self, prompt, max_new_tokens: int, *, seed, priority,
                       resume, epoch, rid, tenant, slo=None,
                       extra=None) -> np.ndarray:
        with self._lock:
            self._check_usable()
            self._send(_submit_frame(OP_SUBMIT, prompt, max_new_tokens,
                                     seed, priority, resume,
                                     self._extra(epoch, rid, tenant,
                                                 extra, slo)))
            _, out, _ = self._read_frame()
        return np.array(out)

    def prefill_ship(self, prompt, *, seed: int = 0, priority: int = 0,
                     ship_to: str, kv_ship: str, epoch=None, rid=None,
                     tenant=None):
        """The router's disagg prefill leg (docs/serving.md
        "Disaggregated tiers"): submit the prompt with
        ``max_new_tokens=1`` and ``ship_to``/``kv_ship`` wire params —
        the frontend prefills, parks the finished KV, ships it to
        ``ship_to`` under key ``kv_ship``, and replies with the first
        token plus a ship report.  Returns ``(tokens, info)`` where
        ``info`` is the report dict (``{"shipped": bool, ...}``; a
        failed ship is a DOWNGRADE — the tokens are still valid, the
        decode side just re-prefills)."""
        with self._lock:
            self._check_usable()
            self._send(_submit_frame(
                OP_SUBMIT, prompt, 1, seed, priority, None,
                self._extra(epoch, rid, tenant,
                            {"ship_to": str(ship_to),
                             "kv_ship": str(kv_ship)})))
            rname, out, _ = self._read_frame()
        info = (json.loads(rname)
                if rname.startswith("{") else {"shipped": False,
                                               "error": "no ship report"})
        return np.array(out), info

    def _note_failover(self, e: BaseException,
                       deadline: float) -> BaseException:
        """One failover-loop step: propagate non-retryable typed
        refusals, enforce the deadline, otherwise rotate to the next
        router with a short pause (a standby needs its takeover window
        before it can serve).  Returns the error for chaining."""
        if isinstance(e, ServeReplyError) and not e.retryable:
            raise e
        with self._lock:
            self._rotate_locked()
        if time.monotonic() + 0.05 > deadline:
            raise ServeConnectionError(
                f"no serve endpoint of {self._addrs} could complete "
                f"the request within timeout={self.timeout}s "
                f"(last: {type(e).__name__}: {e})") from e
        time.sleep(0.05)
        return e

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0, resume=None, epoch=None, rid=None,
               tenant=None, slo=None, extra=None):
        """Token iterator over the OP_STREAM wire op: yields each token
        as its frame arrives (``resume`` = already-emitted tokens for a
        failover re-dispatch — only NEW tokens are streamed back).  A
        frontend death mid-stream raises :class:`ServeConnectionError`
        within ``timeout``; a replica-side typed error raises
        :class:`ServeReplyError` carrying the error name.  Abandoning
        the iterator mid-stream POISONS the client (the server keeps
        sending the orphaned stream's frames, so request/reply pairing
        is lost) — later calls raise ``ServeConnectionError`` instead
        of silently reading wrong replies (a multi-router client
        reconnects instead).

        With several router addresses the stream is failover-wrapped:
        a dead router (or a standby's typed refusal) re-issues the
        request to the next address with ``resume=`` the prefix already
        received — the consumer sees ONE uninterrupted token-identical
        sequence."""
        if len(self._addrs) == 1:
            return self._stream_once(prompt, max_new_tokens, seed=seed,
                                     priority=priority, resume=resume,
                                     epoch=epoch, rid=rid,
                                     tenant=tenant, slo=slo,
                                     extra=extra)
        return self._stream_failover(prompt, max_new_tokens, seed=seed,
                                     priority=priority, resume=resume,
                                     epoch=epoch, rid=rid,
                                     tenant=tenant, slo=slo,
                                     extra=extra)

    def _stream_failover(self, prompt, max_new_tokens: int, *, seed,
                         priority, resume, epoch, rid, tenant,
                         slo=None, extra=None):
        emitted: List[int] = ([int(t) for t in resume]
                              if resume is not None else [])
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                for tok in self._stream_once(
                        prompt, max_new_tokens, seed=seed,
                        priority=priority, resume=emitted or None,
                        epoch=epoch, rid=rid, tenant=tenant,
                        slo=slo, extra=extra):
                    emitted.append(int(tok))
                    # the failover budget is timeout WITHOUT PROGRESS:
                    # a healthy stream longer than self.timeout must
                    # not exhaust its own HA protection, so every
                    # token re-arms the deadline
                    deadline = time.monotonic() + self.timeout
                    yield int(tok)
                return
            except (ServeConnectionError, ServeReplyError) as e:
                if len(emitted) >= max_new_tokens:
                    # the endpoint died between the final token and the
                    # terminal frame: the stream is already fully
                    # delivered (the router tier's argument, one tier
                    # higher)
                    return
                self._note_failover(e, deadline)

    def _stream_once(self, prompt, max_new_tokens: int, *, seed,
                     priority, resume, epoch, rid, tenant, slo=None,
                     extra=None):
        with self._lock:
            self._check_usable()
            in_flight = False
            # the poison write happens INSIDE the locked region: a
            # concurrent caller blocked on the lock must observe it the
            # moment it gets in, never a window where the abandoning
            # thread has released the lock but not yet set the flag
            try:
                self._send(_submit_frame(OP_STREAM, prompt,
                                         max_new_tokens, seed,
                                         priority, resume,
                                         self._extra(epoch, rid,
                                                     tenant, extra,
                                                     slo)))
                in_flight = True
                while True:
                    try:
                        rname, out, _ = self._read_frame()
                    except RuntimeError:
                        # a typed status=1 frame TERMINATED the stream
                        # server-side: the connection stays in sync
                        in_flight = False
                        raise
                    if rname == "t":
                        yield int(out[0])
                    else:  # "end" — sequence already yielded piecewise
                        in_flight = False
                        return
            finally:
                if in_flight:
                    self._poisoned = True

    def cancel(self, rid: str, epoch=None) -> bool:
        """Wire-level cancel (OP_CANCEL) of the in-flight request
        submitted with ``rid=`` — sent on a FRESH short-lived
        connection, because the streaming connection is busy relaying
        the very stream being cancelled.  Through a router the cancel
        propagates router -> replica, so the replica's slot and paged
        KV blocks are reclaimed same-tick.  Returns whether a live
        request was found (False usually means it already finished, or
        the cancel was tombstoned ahead of a racing submit).

        Failover-aware like every other op, deadline-bounded by
        ``timeout``: with several router addresses every sweep tries
        them ALL — one router's False is not authoritative (a
        restarted or partitioned stale active answers False for a rid
        the true active is still serving), and a sweep that only met
        dead routers / standby refusals sleeps and retries so a cancel
        issued inside the takeover window still lands once the standby
        promotes (the tombstone it leaves then kills the request's own
        failover re-submit).  Returns True the moment any router
        cancels; False when every router answered without one; raises
        ``ServeConnectionError`` when none ever answered within the
        deadline.  Non-retryable typed errors propagate immediately."""
        params = {"rid": str(rid)}
        if epoch is not None:
            params["epoch"] = int(epoch)
        # snapshot WITHOUT the client lock: an in-flight stream() holds
        # it for the stream's whole lifetime, and this cancel must not
        # wait behind the very stream it is cancelling (_addrs is
        # immutable after construction; _cur is a plain int read)
        cur = self._cur
        addrs = [self._addrs[(cur + j) % len(self._addrs)]
                 for j in range(len(self._addrs))]
        if len(addrs) == 1:
            return self._cancel_once(addrs[0], params)
        deadline = time.monotonic() + self.timeout
        while True:
            answered = False
            errs = []
            for a in addrs:
                try:
                    if self._cancel_once(a, params):
                        return True
                    answered = True
                except ServeConnectionError as e:
                    errs.append(str(e))
                except ServeReplyError as e:
                    if not e.retryable:
                        raise
                    errs.append(f"{a}: {e.name}")
            if answered:
                # an active-claiming router answered and none held the
                # rid: authoritative — every other address was already
                # swept this round, so retrying buys nothing
                return False
            if time.monotonic() + 0.05 > deadline:
                raise ServeConnectionError(
                    f"no serve endpoint of {addrs} accepted cancel"
                    f"({rid!r}) within timeout={self.timeout}s: "
                    f"{'; '.join(errs)}")
            time.sleep(0.05)

    def _cancel_once(self, addr: str, params: dict) -> bool:
        return _wire_cancel(addr, params, self.timeout,
                            self._transport_pref)

    def journal(self, entries: list) -> dict:
        """Router-HA journal push (OP_JOURNAL; routers only).  Returns
        the receiver's ack — ``{"epoch": N}`` — which is how a deposed
        active router discovers a standby took over."""
        _, _, payload = self._rpc(OP_JOURNAL, json.dumps(entries))
        return json.loads(payload.decode()) if payload else {}

    def stats(self) -> dict:
        _, _, payload = self._rpc(OP_STATS)
        return json.loads(payload.decode())

    def ping(self) -> bool:
        try:
            self._rpc(OP_PING)
            return True
        except (OSError, RuntimeError):
            return False

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------ launcher role


def _model_from_env(cfg_str: str):
    """Build a (model, variables) pair from ``BYTEPS_SERVE_MODEL``: a
    comma-separated ``k=v`` list over TransformerConfig's integer axes
    (vocab_size, num_layers, num_heads, d_model, d_ff, max_seq_len) —
    random-initialized weights unless ``BYTEPS_SERVE_CHECKPOINT`` points
    at a checkpoint produced by ``training.checkpoint``.  A serving
    process with random weights is still the real engine — that is what
    the smoke/bench tooling runs against."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import Transformer, TransformerConfig

    kw = {}
    if cfg_str:
        for pair in cfg_str.split(","):
            k, _, v = pair.partition("=")
            kw[k.strip()] = int(v)
    kw.setdefault("vocab_size", 256)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_model", 128)
    kw.setdefault("d_ff", 256)
    kw.setdefault("max_seq_len", 512)
    cfg = TransformerConfig(dtype=jnp.float32, **kw)
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    return model, variables


def serve_from_env(env=None) -> int:
    """Entry point for the launcher's ``serve`` role: build the engine
    from ``BYTEPS_SERVE_*`` and block on the TCP frontend.  An explicit
    ``env`` mapping overrides the process environment for the
    ``BYTEPS_*``/``DMLC_*`` keys it carries; either way the cached
    process config is reset first, so knobs set after an earlier
    ``get_config()`` call are honored."""
    import os

    from ..common.config import get_config, reset_config

    if env is not None:
        os.environ.update({k: str(v) for k, v in env.items()
                           if k.startswith(("BYTEPS_", "DMLC_"))})
    reset_config()
    cfg = get_config()
    model, variables = _model_from_env(cfg.serve_model)
    if cfg.serve_checkpoint:
        from ..training.checkpoint import restore_checkpoint

        variables = {"params": restore_checkpoint(
            cfg.serve_checkpoint, variables["params"], broadcast=False)}
    engine = ServingEngine(
        model, variables,
        n_slots=cfg.serve_slots,
        max_seq=(cfg.serve_max_seq or model.cfg.max_seq_len),
        temperature=cfg.serve_temperature,
        top_k=cfg.serve_top_k, top_p=cfg.serve_top_p,
        eos_id=cfg.serve_eos_id,
        max_queue=cfg.serve_max_queue,
        prefill_credits=cfg.serve_prefill_credits,
        chunk=cfg.serve_chunk,
        prefix_cache=cfg.serve_prefix_cache,
        prefix_block=cfg.serve_prefix_block,
        prefix_bytes=cfg.serve_prefix_mb << 20,
        paged=cfg.serve_paged,
        block=cfg.serve_block,
        kv_mb=cfg.serve_kv_mb,
        kv_dtype=cfg.serve_kv_dtype,
        paged_kernel=cfg.serve_paged_kernel,
        spec_k=(cfg.serve_spec_k if cfg.serve_spec else 0),
        spec_ngram=cfg.serve_spec_ngram)
    serve(engine, cfg.serve_port)
    return 0
