"""Serving frontends: in-process ``ServeClient`` and a thin TCP server.

The TCP layer reuses the length-prefixed wire helpers of
``engine/ps_server.py`` (``_encode``/``_decode`` — the same u8-op,
raw-numpy-payload framing the PS tier speaks), so a serve process slots
into the launcher the way a PS shard does: ``DMLC_ROLE=serve`` runs
:func:`serve_from_env`.

Wire ops (request := the ps_server frame; one request per round trip,
except STREAM whose reply is a frame *sequence*):

    0 = SUBMIT  name = JSON {"max_new_tokens", "seed", "priority",
                             "resume"}
                arr  = int32 prompt tokens [T] (with ``resume`` = k > 0
                the trailing k entries are tokens another replica
                already emitted — the router's failover re-dispatch;
                the engine resumes the stream bit-exactly)
                reply: status=0, name = request id, arr = int32 tokens;
                rejections (queue full, infeasible request) come back
                as status=1 with the typed error's message — the
                connection survives, clients can back off and retry.
    1 = STATS   reply payload = JSON engine metrics summary
    2 = PING    liveness
    3 = STREAM  same request frame as SUBMIT; the reply is one frame
                per emitted token (status=0, name="t", arr=[tok]) and
                a terminal frame (status=0, name="end", arr = the full
                token sequence).  A status=1 frame at any point carries
                a typed error message and ends the stream.  This is
                what lets the router record how far a stream got before
                a replica died — the failover re-dispatch resumes from
                exactly the tokens that crossed the wire.

SUBMIT blocks the *connection* until the request finishes — per-request
streaming rides OP_STREAM (or stays in-process via
``Request.__iter__``); concurrency across the wire comes from
concurrent connections, which the engine batches into one decode pool
(that is the whole point of continuous batching).

A client socket that disappears mid-STREAM triggers the engine's eager
``cancel()`` path: the slot (and on paged engines the non-shared KV
blocks and prefix references) returns to the pool the same tick the
broken pipe is noticed, not when the abandoned request would have
finished.
"""

from __future__ import annotations

import json
import socketserver
import threading
from typing import List, Optional

import numpy as np

from ..common import logging as bps_log
from ..engine.ps_server import _decode, _encode
from ..engine.transport import (LocalEndpoints, maybe_nodelay,
                                resolve_transport, transport_connect)
from ..engine.wire import hard_reset
from .engine import Request, ServingEngine
from .scheduler import AdmissionError

OP_SUBMIT, OP_STATS, OP_PING, OP_STREAM = range(4)

__all__ = ["ServeClient", "ServeFrontend", "RemoteServeClient",
           "ServeConnectionError", "serve", "serve_from_env",
           "OP_SUBMIT", "OP_STATS", "OP_PING", "OP_STREAM"]


class ServeConnectionError(ConnectionError):
    """The serve frontend (or router) went away mid-conversation — the
    connection died or stalled past the client timeout.  Typed so
    callers can distinguish a dead endpoint (retry elsewhere / fail
    over) from a replica-side error reply (status=1 ``RuntimeError``,
    which would recur on retry)."""


class ServeClient:
    """In-process client: submit -> stream tokens, cancel, drain.

    A thin convenience veneer over :class:`ServingEngine` that starts
    the background tick thread on first use and owns its shutdown."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0) -> Request:
        self.engine.start()
        return self.engine.submit(prompt, max_new_tokens, seed=seed,
                                  priority=priority)

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0):
        """Iterator of tokens as the engine emits them."""
        return iter(self.submit(prompt, max_new_tokens, seed=seed,
                                priority=priority))

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0,
                 timeout: Optional[float] = None) -> np.ndarray:
        """Blocking submit -> full token array."""
        return self.submit(prompt, max_new_tokens, seed=seed,
                           priority=priority).result(timeout)

    def cancel(self, req: Request) -> None:
        self.engine.cancel(req)

    def drain(self, timeout: Optional[float] = None) -> None:
        self.engine.drain(timeout)

    def close(self) -> None:
        self.engine.stop()


# ------------------------------------------------------------------ TCP tier


def _split_resume(params: dict, arr):
    """THE wire contract for SUBMIT/STREAM request arrays: ``resume`` =
    k > 0 marks the trailing k entries as already-emitted tokens (a
    failover re-dispatch or client retry); the rest is the prompt.
    Shared by the serve frontend and the router so the two tiers can
    never silently disagree on the frame layout."""
    toks = np.asarray(arr, np.int32).reshape(-1)
    k = int(params.get("resume", 0))
    return (toks[:-k], toks[-k:]) if k > 0 else (toks, None)


def _parse_submit(engine: ServingEngine, name: str, arr):
    """Decode a SUBMIT/STREAM frame into an engine submit."""
    params = json.loads(name) if name else {}
    prompt, resumed = _split_resume(params, arr)
    req = engine.submit(
        prompt, int(params.get("max_new_tokens", 16)),
        seed=int(params.get("seed", 0)),
        priority=int(params.get("priority", 0)),
        resume_tokens=resumed)
    return req, params


class _ServeHandler(socketserver.BaseRequestHandler):
    def setup(self):
        track = getattr(self.server, "_track_conn", None)
        if track is not None:
            track(self.request)

    def _stream(self, engine: ServingEngine, sock, req: Request) -> bool:
        """Relay ``req``'s tokens as one frame each, then the terminal
        frame.  Returns False when the CLIENT went away — the caller
        must stop serving this connection; the request is eagerly
        cancelled so its slot (and paged KV blocks) free this tick."""
        try:
            for tok in req:
                sock.sendall(_encode(0, "t", np.asarray([tok], np.int32)))
            sock.sendall(_encode(0, "end",
                                 np.asarray(req.tokens, np.int32)))
            return True
        except RuntimeError as e:
            # engine died mid-stream: a typed status=1 frame ends the
            # stream loudly (the iterator already drained to _END)
            try:
                sock.sendall(_encode(1, "", None,
                                     f"{type(e).__name__}: {e}".encode()))
            except OSError:
                pass
            return True
        except OSError:
            # client disconnected mid-stream: eager-cancel so the slot
            # and non-shared blocks are reclaimed same-tick, not when
            # the abandoned stream would have finished
            engine.cancel(req)
            return False

    def handle(self):  # one connection, many requests
        engine: ServingEngine = self.server.engine  # type: ignore
        sock = self.request
        maybe_nodelay(sock)
        try:
            while True:
                try:
                    op, name, arr, _ = _decode(sock)
                except (ConnectionError, OSError):
                    return
                try:
                    if op == OP_SUBMIT:
                        req, params = _parse_submit(engine, name, arr)
                        toks = req.result(
                            timeout=float(params.get("timeout", 300.0)))
                        reply = _encode(0, str(req.id), toks)
                    elif op == OP_STREAM:
                        req, _ = _parse_submit(engine, name, arr)
                        if not self._stream(engine, sock, req):
                            return
                        continue
                    elif op == OP_STATS:
                        payload = json.dumps(
                            {**engine.metrics.summary(),
                             # engine identity: the weights fingerprint
                             # the router's registration handshake
                             # compares before trusting this replica
                             # with resumes (serving/router.py)
                             "weights_fingerprint": engine.weights_fp,
                             "compile_counts": engine.compile_counts(),
                             "occupancy": engine.pool.occupancy(),
                             "queue_depth": engine.scheduler.depth,
                             "prefix_cache": (engine.prefix.stats()
                                              if engine.prefix is not None
                                              else None),
                             # paged KV pool accounting (None on dense
                             # engines) — free/used/shared block counts
                             # next to the prefix stats they interact
                             # with (docs/serving.md "Paged KV cache")
                             "kv_blocks": (engine.pool.block_stats()
                                           if engine.paged else None),
                             # the same registry snapshot /metrics.json
                             # serves — one stats surface, two transports
                             # (docs/observability.md)
                             "metrics": engine.metrics.registry.snapshot()})
                        reply = _encode(0, "", None, payload.encode())
                    elif op == OP_PING:
                        reply = _encode(0, "", None)
                    else:
                        reply = _encode(1, "", None,
                                        f"bad op {op}".encode())
                except AdmissionError as e:
                    # typed backpressure: status=1 + reason, socket lives
                    reply = _encode(1, "", None,
                                    f"{type(e).__name__}: {e}".encode())
                except Exception as e:
                    reply = _encode(
                        1, "", None, f"{type(e).__name__}: {e}".encode())
                sock.sendall(reply)
        except Exception as e:  # pragma: no cover - teardown races
            bps_log.debug("serve handler exit: %s", e)


class ServeFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr, engine: ServingEngine):
        super().__init__(addr, _ServeHandler)
        self.engine = engine
        # live client sockets, so kill() can die like a crashed process
        # (sever mid-stream connections, not just stop accepting)
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._killing = False
        # colocated fast path (docs/wire.md "Transports"): advertise a
        # UDS + shm rendezvous next to the TCP port, served by the SAME
        # handler over the same engine, unless pinned to TCP
        self.local_endpoints = None
        from ..common.config import get_config

        if get_config().transport != "tcp":
            try:
                self.local_endpoints = LocalEndpoints(
                    self.server_address[1], _ServeHandler, self)
            except ValueError:
                super().server_close()
                raise
            except OSError as e:
                bps_log.warning(
                    "serve frontend: local transport endpoints "
                    "unavailable (%s); serving TCP only", e)
        engine.start()

    def _track_conn(self, sock) -> None:
        with self._conns_lock:
            # the _killing check must share kill()'s critical section:
            # checked outside it, a handler could pass the check, block
            # on the lock while kill() swaps the set, and then register
            # a connection nobody will ever reset
            if not self._killing:
                self._conns.add(sock)
                # drop references the handlers already finished with
                self._conns = {s for s in self._conns
                               if s.fileno() != -1}
                return
        # a connection that slipped through between kill() and the
        # listener actually closing (socketserver's shutdown can lag a
        # poll interval): a dead process serves nobody
        hard_reset(sock)

    def kill(self) -> None:
        """Die like a crashed replica (the PSServer.kill discipline):
        hard-reset every live client connection AND stop accepting, so
        in-flight streams see ECONNRESET mid-frame — what the router's
        failover path (and RemoteServeClient's typed
        ``ServeConnectionError``) must absorb.  Connections are severed
        FIRST: ``shutdown()`` can wait up to the serve_forever poll
        interval, and a fast engine would stream a whole request's
        remaining tokens into the socket in that window — a crash cuts
        the wire mid-token, so the kill must too (and ``_killing``
        makes any connection accepted inside that window die
        unserved).  Chaos/test only."""
        self._killing = True
        with self._conns_lock:
            conns, self._conns = set(self._conns), set()
        for c in conns:
            hard_reset(c)
        self.shutdown()
        if self.local_endpoints is not None:
            self.local_endpoints.close(unlink=False)
        self.server_close()

    def server_close(self):
        if self.local_endpoints is not None:
            self.local_endpoints.close()
        self.engine.stop()
        super().server_close()


def serve(engine: ServingEngine, port: int, host: str = "0.0.0.0",
          in_thread: bool = False):
    """Run the TCP frontend over ``engine``.  ``in_thread=True`` returns
    ``(server, thread)`` for tests; otherwise blocks (launcher mode)."""
    srv = ServeFrontend((host, port), engine)
    bps_log.info("byteps_tpu serve frontend listening on %s:%d",
                 host, srv.server_address[1])
    # live scrape endpoint (BYTEPS_METRICS_PORT; off by default) — the
    # HTTP twin of the TCP STATS op (docs/observability.md)
    from ..observability.scrape import maybe_start_metrics_server

    maybe_start_metrics_server(
        role="serve",
        health_fn=lambda: {"occupancy": engine.pool.occupancy(),
                           "queue_depth": engine.scheduler.depth})
    if in_thread:
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        return srv, t
    try:
        srv.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        srv.server_close()


def _submit_frame(op: int, prompt, max_new_tokens: int, seed: int,
                  priority: int, resume) -> bytes:
    """Encode a SUBMIT/STREAM request: the resume tokens (if any) ride
    the tail of the token array, counted by the ``resume`` param."""
    resume = ([] if resume is None
              else [int(t) for t in resume])
    params = json.dumps({"max_new_tokens": max_new_tokens, "seed": seed,
                         "priority": priority, "resume": len(resume)})
    toks = np.concatenate([np.asarray(prompt, np.int32).reshape(-1),
                           np.asarray(resume, np.int32)])
    return _encode(op, params, toks)


class RemoteServeClient:
    """Client for the serve frontend (same framing as ``RemoteStore``).
    ``transport`` is resolved per endpoint exactly like the PS
    client's (``auto`` default: UDS/shm for a colocated frontend, TCP
    otherwise — docs/wire.md "Transports").

    Every wire read is bounded by ``timeout`` (default: the
    ``BYTEPS_SERVE_CLIENT_TIMEOUT_MS`` knob), and a dead or stalled
    frontend surfaces as the typed :class:`ServeConnectionError` on
    ``generate()``/``stream()`` — promptly, never an indefinite hang.
    One in-flight ``stream()`` per client (it holds the connection)."""

    def __init__(self, addr: str, timeout: Optional[float] = None,
                 transport: Optional[str] = None):
        from ..common.config import get_config

        cfg = get_config()
        kind, path = resolve_transport(
            addr, transport if transport else cfg.transport)
        self.addr = addr
        self.transport = kind
        self.timeout = (timeout if timeout is not None
                        else cfg.serve_client_timeout_ms / 1e3)
        self._sock = transport_connect(kind, path, addr,
                                       timeout=self.timeout)
        self._lock = threading.Lock()
        # set when a stream() was abandoned mid-flight: the server
        # keeps sending that stream's frames, so the connection can no
        # longer pair requests with replies — every later op would
        # silently read the orphaned frames as its reply
        self._poisoned = False

    def _check_usable(self) -> None:
        """Call with ``self._lock`` held: the poison flag is written
        under the same lock (a check outside it could pass while the
        abandoning thread is still inside the stream's critical
        section)."""
        if self._poisoned:
            raise ServeConnectionError(
                f"client for {self.addr} abandoned an in-flight "
                f"stream(); the connection is desynced — open a new "
                f"RemoteServeClient")

    def _send(self, frame: bytes) -> None:
        """One frame out, with wire-level death typed (lock held)."""
        try:
            self._sock.sendall(frame)
        except (ConnectionError, OSError) as e:
            raise ServeConnectionError(
                f"serve frontend {self.addr} unreachable: {e}") from e

    def _read_frame(self):
        """One reply frame, with wire-level death typed."""
        try:
            status, rname, out, payload = _decode(self._sock)
        except (ConnectionError, OSError, ValueError) as e:
            raise ServeConnectionError(
                f"serve frontend {self.addr} died or stalled "
                f"mid-conversation ({type(e).__name__}: {e}); "
                f"timeout={self.timeout}s") from e
        if status != 0:
            raise RuntimeError(f"serve error: {payload.decode()!r}")
        return rname, out, payload

    def _rpc(self, op: int, name: str = "", arr=None):
        with self._lock:
            self._check_usable()
            self._send(_encode(op, name, arr))
            return self._read_frame()

    def generate(self, prompt, max_new_tokens: int, *, seed: int = 0,
                 priority: int = 0, resume=None) -> np.ndarray:
        """Blocking submit -> the full token array.  Raises the typed
        :class:`ServeConnectionError` when the frontend dies first."""
        with self._lock:
            self._check_usable()
            self._send(_submit_frame(OP_SUBMIT, prompt, max_new_tokens,
                                     seed, priority, resume))
            _, out, _ = self._read_frame()
        return np.array(out)

    def stream(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0, resume=None):
        """Token iterator over the OP_STREAM wire op: yields each token
        as its frame arrives (``resume`` = already-emitted tokens for a
        failover re-dispatch — only NEW tokens are streamed back).  A
        frontend death mid-stream raises :class:`ServeConnectionError`
        within ``timeout``; a replica-side typed error raises
        ``RuntimeError`` carrying the error name.  Abandoning the
        iterator mid-stream POISONS the client (the server keeps
        sending the orphaned stream's frames, so request/reply pairing
        is lost) — later calls raise ``ServeConnectionError`` instead
        of silently reading wrong replies."""
        with self._lock:
            self._check_usable()
            in_flight = False
            # the poison write happens INSIDE the locked region: a
            # concurrent caller blocked on the lock must observe it the
            # moment it gets in, never a window where the abandoning
            # thread has released the lock but not yet set the flag
            try:
                self._send(_submit_frame(OP_STREAM, prompt,
                                         max_new_tokens, seed,
                                         priority, resume))
                in_flight = True
                while True:
                    try:
                        rname, out, _ = self._read_frame()
                    except RuntimeError:
                        # a typed status=1 frame TERMINATED the stream
                        # server-side: the connection stays in sync
                        in_flight = False
                        raise
                    if rname == "t":
                        yield int(out[0])
                    else:  # "end" — sequence already yielded piecewise
                        in_flight = False
                        return
            finally:
                if in_flight:
                    self._poisoned = True

    def stats(self) -> dict:
        _, _, payload = self._rpc(OP_STATS)
        return json.loads(payload.decode())

    def ping(self) -> bool:
        try:
            self._rpc(OP_PING)
            return True
        except (OSError, RuntimeError):
            return False

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------------ launcher role


def _model_from_env(cfg_str: str):
    """Build a (model, variables) pair from ``BYTEPS_SERVE_MODEL``: a
    comma-separated ``k=v`` list over TransformerConfig's integer axes
    (vocab_size, num_layers, num_heads, d_model, d_ff, max_seq_len) —
    random-initialized weights unless ``BYTEPS_SERVE_CHECKPOINT`` points
    at a checkpoint produced by ``training.checkpoint``.  A serving
    process with random weights is still the real engine — that is what
    the smoke/bench tooling runs against."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import Transformer, TransformerConfig

    kw = {}
    if cfg_str:
        for pair in cfg_str.split(","):
            k, _, v = pair.partition("=")
            kw[k.strip()] = int(v)
    kw.setdefault("vocab_size", 256)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_model", 128)
    kw.setdefault("d_ff", 256)
    kw.setdefault("max_seq_len", 512)
    cfg = TransformerConfig(dtype=jnp.float32, **kw)
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    return model, variables


def serve_from_env(env=None) -> int:
    """Entry point for the launcher's ``serve`` role: build the engine
    from ``BYTEPS_SERVE_*`` and block on the TCP frontend.  An explicit
    ``env`` mapping overrides the process environment for the
    ``BYTEPS_*``/``DMLC_*`` keys it carries; either way the cached
    process config is reset first, so knobs set after an earlier
    ``get_config()`` call are honored."""
    import os

    from ..common.config import get_config, reset_config

    if env is not None:
        os.environ.update({k: str(v) for k, v in env.items()
                           if k.startswith(("BYTEPS_", "DMLC_"))})
    reset_config()
    cfg = get_config()
    model, variables = _model_from_env(cfg.serve_model)
    if cfg.serve_checkpoint:
        from ..training.checkpoint import restore_checkpoint

        variables = {"params": restore_checkpoint(
            cfg.serve_checkpoint, variables["params"], broadcast=False)}
    engine = ServingEngine(
        model, variables,
        n_slots=cfg.serve_slots,
        max_seq=(cfg.serve_max_seq or model.cfg.max_seq_len),
        temperature=cfg.serve_temperature,
        top_k=cfg.serve_top_k, top_p=cfg.serve_top_p,
        eos_id=cfg.serve_eos_id,
        max_queue=cfg.serve_max_queue,
        prefill_credits=cfg.serve_prefill_credits,
        chunk=cfg.serve_chunk,
        prefix_cache=cfg.serve_prefix_cache,
        prefix_block=cfg.serve_prefix_block,
        prefix_bytes=cfg.serve_prefix_mb << 20,
        paged=cfg.serve_paged,
        block=cfg.serve_block,
        kv_mb=cfg.serve_kv_mb,
        paged_kernel=cfg.serve_paged_kernel,
        spec_k=(cfg.serve_spec_k if cfg.serve_spec else 0),
        spec_ngram=cfg.serve_spec_ngram)
    serve(engine, cfg.serve_port)
    return 0
