"""Router-HA journal transport: the active router's state stream.

The active :class:`~byteps_tpu.serving.router.ServeRouter` replicates a
compact journal to its standby peers over the existing serve wire
(``frontend.py`` ``OP_JOURNAL`` — one frame per batch, one ack per
frame), so a standby that takes over already holds the affinity map,
the replica health/fingerprint verdicts, and the per-request in-flight
records (id, seed, params, replica, emitted-token COUNT — counts, not
tokens: the client holds the tokens and re-supplies them as
``resume_tokens`` on failover).  Entry layout and application live in
``router.py`` (``ServeRouter.apply_journal``); this module is only the
transport:

  * **Asynchronous, bounded, honest.**  ``publish()`` enqueues and
    returns — journaling must never sit on the dispatch path.  The
    queue is bounded; overflow drops the OLDEST batch and counts it
    (``dropped``), because a slow standby must throttle replication
    fidelity, not the serving tier.  The recovery contract tolerates
    loss by design: anything between the last applied entry and the
    takeover is recovered from the clients' ``resume_tokens``, not the
    journal (docs/serving.md "Router HA" — the honest window).
  * **Per-peer isolation.**  A dead or lagging standby costs its own
    connection a timeout and a reconnect on the next batch; other
    peers and the active's dispatch path never notice.
  * **Split-brain discovery on the ack.**  Every journal ack carries
    the receiver's epoch.  A receiver answering with a HIGHER epoch
    than the sender's means a takeover already happened — the sender
    is deposed and must demote (``on_stale`` callback), mirroring the
    replica-side ``EpochFencedError`` fence one tier up.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..common import logging as bps_log

__all__ = ["JournalSender"]

_BATCH_MAX = 256


class JournalSender:
    """Fan journal entries out to the standby peers (daemon thread).

    ``epoch_of`` is read per batch (the router's CURRENT epoch — the
    ack comparison must track promotions); ``on_stale(higher_epoch)``
    fires when any peer acks with a higher epoch than ours."""

    def __init__(self, peers: Sequence[str], *, timeout: float = 1.0,
                 epoch_of: Callable[[], int] = lambda: 0,
                 on_stale: Optional[Callable[[int], None]] = None,
                 snapshot_fn: Optional[Callable[[], List[dict]]] = None,
                 max_queue: int = 4096):
        self.peers = list(peers)
        self.timeout = timeout
        self._epoch_of = epoch_of
        self._on_stale = on_stale
        # full-state dump sent to a peer on every (re)connect: a
        # standby that boots AFTER the active (or drops and comes
        # back) must not miss the verdicts/affinity that were
        # journaled while it was away
        self._snapshot_fn = snapshot_fn
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._conns: Dict[str, object] = {}
        # per-peer reconnect backoff: a dead standby must cost at most
        # one connect timeout per backoff window, not one per batch
        # (head-of-line isolation for the healthy peers); batches
        # skipped while a peer is down are recovered by the snapshot
        # its reconnect always starts with
        self._down_until: Dict[str, float] = {}
        self.retry_after = max(0.2, timeout)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Condition()
        self._inflight = 0
        self.dropped = 0
        self.sent = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "JournalSender":
        if self._thread is None and self.peers:
            self._thread = threading.Thread(
                target=self._loop, name="bps-router-journal", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        for c in self._conns.values():
            try:
                c.close()
            except OSError:
                pass
        self._conns.clear()

    def kill(self) -> None:
        """Crash semantics (chaos): stop NOW and drop everything still
        queued — a crashed router flushes nothing, and the takeover
        contract must be proven against exactly that (the standby's
        orphaned in-flight records are recovered from client
        ``resume_tokens``, not from a last-gasp flush)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
                self.dropped += 1
        except queue.Empty:
            pass
        with self._idle:
            self._inflight = 0
            self._idle.notify_all()

    # -------------------------------------------------------------- produce

    def publish(self, entry: dict) -> None:
        """Enqueue one journal entry (never blocks the caller).  On
        overflow the OLDEST entry is dropped and counted — replication
        lag must never backpressure dispatch."""
        with self._idle:
            self._inflight += 1
        while True:
            try:
                self._q.put_nowait(entry)
                return
            except queue.Full:
                try:
                    self._q.get_nowait()
                    self.dropped += 1
                    with self._idle:
                        self._inflight -= 1
                except queue.Empty:
                    pass

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every published entry has been offered to every
        peer (or ``timeout``).  Test/diagnostic hook — production
        callers rely on the honest-window contract instead."""
        with self._idle:
            return self._idle.wait_for(lambda: self._inflight == 0,
                                       timeout)

    # --------------------------------------------------------------- consume

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                # idle tier: still (re)connect+snapshot disconnected
                # peers — a standby that boots AFTER the active (with
                # no traffic flowing) must not sit cold until the
                # first dispatch happens to publish something
                self._probe_disconnected()
                continue
            batch: List[dict] = [first]
            while len(batch) < _BATCH_MAX:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            try:
                self._send_batch(batch)
            finally:
                with self._idle:
                    self._inflight -= len(batch)
                    self._idle.notify_all()

    def _ensure_conn(self, peer: str, snap: Optional[list] = None):
        """(Re)connect one peer, sending the full-state snapshot first
        (the snapshot reflects NOW, so everything a downed peer missed
        is covered; ``snap`` lets a caller that already built one pass
        it in rather than serializing the state twice).  Returns
        (conn, snapshotted) — conn None while the peer is in its
        reconnect-backoff window or unreachable."""
        from .frontend import RemoteServeClient

        c = self._conns.get(peer)
        if c is not None:
            return c, False
        if time.monotonic() < self._down_until.get(peer, 0.0):
            return None, False
        c = RemoteServeClient(peer, timeout=self.timeout)
        self._conns[peer] = c
        snapshotted = False
        if self._snapshot_fn is not None:
            if snap is None:
                snap = self._snapshot_fn()
            if snap:
                self._check_ack(c.journal(snap))
                self.sent += len(snap)
                snapshotted = True
        return c, snapshotted

    def _drop_conn(self, peer: str, why: BaseException) -> None:
        bps_log.debug("router journal: peer %s unreachable (%s); "
                      "entries dropped for it until reconnect",
                      peer, why)
        c = self._conns.pop(peer, None)
        if c is not None:
            try:
                c.close()
            except OSError:
                pass
        self._down_until[peer] = time.monotonic() + self.retry_after

    def _probe_disconnected(self) -> None:
        from .frontend import ServeConnectionError, ServeReplyError

        # cheap gates FIRST: the snapshot serializes the whole state
        # under the router lock, so it must not be built on every
        # 100ms idle tick while a crashed peer sits in its backoff
        # window (the normal post-takeover steady state)
        now = time.monotonic()
        due = [p for p in self.peers
               if p not in self._conns
               and now >= self._down_until.get(p, 0.0)]
        if not due:
            return
        snap = (self._snapshot_fn() if self._snapshot_fn is not None
                else None)
        if self._snapshot_fn is not None and not snap:
            return  # nothing to warm peers with (standby / killed)
        for peer in due:
            try:
                self._ensure_conn(peer, snap=snap)
            except (ServeConnectionError, ServeReplyError, OSError,
                    ValueError) as e:
                self._drop_conn(peer, e)

    def _send_batch(self, batch: List[dict]) -> None:
        from .frontend import ServeConnectionError, ServeReplyError

        for peer in self.peers:
            try:
                c, snapshotted = self._ensure_conn(peer)
                if c is None:
                    continue  # backoff window: snapshot covers it later
                if snapshotted:
                    # the snapshot was built NOW, so it already
                    # reflects (supersedes) every entry in this batch —
                    # sending the older batch after it could regress a
                    # replica verdict the snapshot just updated
                    continue
                self._check_ack(c.journal(batch))
                self.sent += len(batch)
            except (ServeConnectionError, ServeReplyError, OSError,
                    ValueError) as e:
                # this peer missed the batch; its journal is behind
                # until the reconnect snapshot — the takeover contract
                # absorbs that (clients re-supply emitted tokens)
                self._drop_conn(peer, e)

    def _check_ack(self, ack: dict) -> None:
        higher = int(ack.get("epoch", 0))
        if higher > self._epoch_of() and self._on_stale:
            # the peer lives in a NEWER epoch: we are deposed
            self._on_stale(higher)
