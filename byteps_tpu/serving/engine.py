"""Continuous-batching engine: jitted slot-pool step functions + tick loop.

Two compiled programs serve steady state, regardless of how many
requests flow through:

  * **decode step** — one token for EVERY slot per tick: the model's
    per-row ``Transformer.decode`` is ``vmap``-ed over the slot axis
    with per-slot position scalars (slots sit at different depths), so
    the whole pool advances in one program with static ``[N_slots]``
    token/pos vectors and an active-slot mask.  Inactive slots compute
    garbage into their (freed) rows — the price of static shapes — and
    their sampled tokens are masked to ``pad_id``.
  * **prefill** — one request's padded prompt into its slot row:
    ``dynamic_slice`` the row out, run the model's cached prefill
    (static ``pos=0`` — the same dense-prefill path ``generate()``
    takes), gather the true last position's logits, ``dynamic_update_
    slice`` the row back.  Prompts are right-padded to power-of-two
    buckets so the compile count is O(log max_seq), not O(#lengths).
  * **chunked prefill** (``chunk > 0``) — the prefill generalized to
    position-offset chunks (``Transformer.prefill_chunk``): a long
    prompt runs as a sequence of ``[p0, p0 + C)`` chunk calls spread
    over consecutive ticks, each debiting the SAME credit pool the
    admission grants use, so no tick's prefill work exceeds the budget
    and decoding requests keep emitting between chunks (SARATHI-style
    stall bounding).  Requests sit in the ``PREFILLING`` state (slot
    assigned, excluded from decode) until their final chunk samples
    the first token.  Chunk buckets are powers of two capped at the
    chunk size — O(log chunk) compiled programs.
  * **prefix reuse** (``prefix_cache``) — before the first chunk, the
    longest block-aligned cached prefix of the prompt (serving/
    prefix.py) is copied device-side into the slot row by a jitted
    copy program (one trace — entries are full-row buffers), and
    prefill resumes at the boundary.  Bit-exact by construction: the
    K/V bytes are copied, not recomputed.
  * **paged KV cache** (``paged=True``, serving/blocks.py) — slot
    memory as fixed-size blocks with per-slot block tables: the decode
    and chunk programs gather each slot's rows through its table and
    scatter writes back to ``(table[pos // block], pos % block)``,
    blocks are granted lazily at boundary crossings, a prefix hit
    SHARES refcounted blocks (zero device copies — the copy/extract
    programs are never built), and pool exhaustion evicts prefix
    entries then preempts the newest request back to QUEUED (resume is
    bit-exact; docs/serving.md "Paged KV cache").  The gather is
    pos-capped: each tick streams only the block high-water bucket,
    never the null-padded table width.  With the **fused kernel**
    (``paged_kernel``, ops/paged_attention.py) decode and spec-verify
    skip the gather entirely — the Pallas kernel reads allocated,
    position-covered blocks in place through the block table
    (docs/serving.md "Fused paged attention").
  * **speculative decoding** (``spec_k > 0``, serving/spec.py) — the
    decode step generalized from 1 to ``k + 1`` query positions: a
    CPU-side n-gram proposer guesses up to ``k`` continuations from
    each request's own prompt + emitted history (no draft model), ONE
    batched ``Transformer.verify_tokens`` pass scores every proposal,
    and the longest prefix the model itself would have produced is
    accepted — several tokens per tick on repetitive workloads, one
    (exactly the plain decode's token) otherwise.  Rejected positions
    roll back for free: dense, the cursor simply does not advance past
    the accepted count and the stale K/V beyond it is overwritten
    before the causal mask can admit it (the freed-rows argument one
    position wider); paged, writes scatter per position to the slot's
    own granted blocks only (ungranted span positions aim at the null
    block and cap acceptance), so shared prefix blocks are never
    touched.  One verify program per speculation-depth bucket, pinned
    by ``compile_counts()`` exactly like chunk buckets; ticks where no
    slot proposes run the plain decode program untouched.

**Determinism / parity contract** (the correctness anchor, pinned by
tests/test_serving.py and scripts/serve_smoke.py): per request, the
engine reproduces sequential ``generate()`` token for token — greedy
trivially, and under sampling by replaying ``generate()``'s exact key
chain (``PRNGKey(seed)``; split once at prefill, once per decode step).
The numerics match because (a) every per-slot computation is
row-independent under ``vmap``, and (b) a longer cache than
``generate()``'s only adds *masked* attention slots, whose
``exp(-1e30 - max)`` scores underflow to exactly 0.0 and contribute
nothing to any softmax sum or PV dot.  Batch composition therefore
cannot leak between requests.

Tick order is fixed: cancellations, then credit-bounded admissions (in
scheduler grant order), then one decode pass over the pool (slot
order), then credits return.  Given an admission order, the engine's
entire output is deterministic.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import hashlib
import queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import logging as bps_log
from ..inference import sample_logits
from ..models.transformer import Transformer
from . import metrics as sm
from .blocks import BlocksExhaustedError, PagedSlotPool
from .metrics import ServeMetrics, get_serve_metrics
from .prefix import PagedPrefixCache, PrefixCache, weights_fingerprint
from .scheduler import ServeScheduler
from .slots import SlotPool
from .spec import NgramProposer

__all__ = ["EpochFencedError", "Request", "RequestState", "ServingEngine"]


class EpochFencedError(RuntimeError):
    """A dispatch carried a router epoch LOWER than one this engine has
    already served: the sender is a deposed active router that does not
    yet know a standby took over (serving/router.py "Router HA").  The
    refusal is the split-brain guard — accepting the stale dispatch
    could double-serve a request the new epoch's router already
    re-dispatched.  Typed so the stale router can recognize the fence
    and demote itself instead of treating this replica as dead."""

    def __init__(self, epoch: int, high_water: int):
        self.epoch = epoch
        self.high_water = high_water
        super().__init__(
            f"dispatch fenced: epoch {epoch} < this engine's epoch "
            f"high-water {high_water} — a newer router epoch has taken "
            f"over this tier; the sending router must demote")


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"  # slot assigned, chunked prefill in flight
    ACTIVE = "active"
    DONE = "done"
    CANCELLED = "cancelled"
    FAILED = "failed"  # engine tick raised; see Request.error


_END = object()  # stream sentinel


@dataclasses.dataclass
class Request:
    """One in-flight generation request.  Stream tokens with ``for tok
    in req:`` (blocks until the engine emits them) or block for the
    whole sequence with ``result()``."""

    id: int
    prompt: np.ndarray          # [T] int32
    max_new_tokens: int
    seed: int = 0
    priority: int = 0
    state: RequestState = RequestState.QUEUED
    cancelled: bool = False
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    prefill_pos: int = 0  # prompt tokens already in the slot's K/V rows
    _pf_paid: bool = dataclasses.field(default=False, repr=False)
    # the token sequence the current prefill covers: the prompt, or —
    # after a preemption (paged engine, block pressure) — the prompt
    # plus the already-emitted tokens minus the last one, whose K/V is
    # rebuilt by re-prefill while the token itself stays the next
    # decode input (docs/serving.md "Preemption")
    _seq: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False)
    # preemption resume state: the last emitted token (next decode
    # input) and the carried sampling key at preemption time — restored
    # after the resume prefill so the per-request key chain continues
    # exactly where it stopped (bit-exact seeded parity)
    _resume_tok: Optional[int] = dataclasses.field(
        default=None, repr=False)
    _resume_key: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False)
    # disaggregated serving (serving/disagg): keep_kv parks the
    # finished request's paged blocks for a ship instead of freeing
    # them; _kv_blocks carries staged block ids a decode-side admit
    # adopts in place of re-running prefill
    _keep_kv: bool = dataclasses.field(default=False, repr=False)
    _kv_blocks: Optional[List[int]] = dataclasses.field(
        default=None, repr=False)
    # anti-thrash watermark: a preempted request is re-admitted only
    # once this many blocks are free (its worst-case remaining need) —
    # eagerly re-admitting it would re-prefill, collide with the same
    # pressure, and be preempted again every tick
    _hold_blocks: int = dataclasses.field(default=0, repr=False)
    # tokens pre-seeded by a cross-replica resume submit: ANOTHER
    # engine emitted them, so this engine's latency/token metrics must
    # not claim them (TPOT would under-read exactly during failover)
    _resumed_n: int = dataclasses.field(default=0, repr=False)
    # rolling prefix-block digests, computed once at admit and reused
    # for the post-prefill insert (one blake2b per block per pass —
    # recomputing them three times per request sits on the tick thread)
    _prefix_digs: Optional[List[bytes]] = dataclasses.field(
        default=None, repr=False)
    _task: Optional[object] = dataclasses.field(default=None, repr=False)
    # speculative-decoding proposer context (prompt + emitted tokens,
    # appended incrementally — rebuilding it per tick would put an
    # O(T) copy per request on the tick thread; serving/spec.py)
    _spec_ctx: Optional[np.ndarray] = dataclasses.field(
        default=None, repr=False)
    _spec_n: int = dataclasses.field(default=0, repr=False)
    # distributed tracing (docs/observability.md): hex trace id minted
    # at submit when RPC tracing is on; the request's serve span
    # carries it so trace_merge can line serving work up with the PS
    # ops the same logical operation issued
    trace_id: str = ""
    _t_pc: float = dataclasses.field(default=0.0, repr=False)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0
    error: Optional[BaseException] = None
    _out: "queue.Queue" = dataclasses.field(default_factory=queue.Queue)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def __iter__(self):
        while True:
            item = self._out.get()
            if item is _END:
                # an engine failure must not masquerade as a clean,
                # short completion to streaming consumers
                if self.error is not None:
                    raise RuntimeError(
                        f"serving engine failed while request {self.id} "
                        f"was in flight: {self.error!r}") from self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns the emitted tokens
        (CANCELLED requests return whatever was emitted before).
        Raises if the engine failed while this request was in flight."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        if self.error is not None:
            raise RuntimeError(
                f"serving engine failed while request {self.id} was in "
                f"flight: {self.error!r}") from self.error
        return np.asarray(self.tokens, np.int32)

    @property
    def done(self) -> bool:
        return self._done.is_set()


def _prefill_forward(mdl: Transformer, tokens, caches, true_len):
    """Padded-prompt prefill returning the logits at ``true_len - 1``.

    Structurally identical to ``Transformer.decode(..., last_only=True)``
    — embed, blocks at static ``pos=0``, slice ONE position, ``ln_f``,
    head — except the slice lands on the true last prompt token instead
    of the literal last row, so right-padding never reaches the LM head.
    Pad K/V beyond ``true_len`` does enter the cache, but decode's
    causal mask admits position ``p`` only once the request's own write
    cursor passes it — by which point the pad row has been overwritten
    by a real token's K/V (see docs/serving.md).
    """
    cfg = mdl.cfg
    x = mdl.embed(tokens)
    if cfg.pos_emb == "learned":
        x = x + mdl.pos(jnp.arange(tokens.shape[1])[None, :])
    new_caches = []
    for block, c in zip(mdl.blocks, caches):
        x, nc = block(x, cache=c, pos=0)
        new_caches.append(nc)
    x = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)
    return mdl.logits(mdl.ln_f(x)), tuple(new_caches)


def _next_bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power-of-two >= n, floored at lo, clamped to hi."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi)


def _resume_key_chain(seed: int, k: int) -> np.ndarray:
    """Carried sampling key after ``k`` emitted tokens: ``generate()``
    (and ``_select_token``) split once per emitted token and carry
    ``split(key)[0]``, so the key state is a pure function of ``(seed,
    k)`` — which is what makes a dead replica's key state recoverable
    by any other engine (serving/router.py failover; docs/serving.md
    "Router tier")."""
    key = jax.random.PRNGKey(seed)
    for _ in range(k):
        key = jax.random.split(key)[0]
    return np.asarray(key)


class ServingEngine:
    """Continuous-batching serving over a ``SlotPool``.

    Sampling parameters (``temperature``/``top_k``/``top_p``) are fixed
    per engine — they are *static* arguments of the compiled step
    functions, which is what makes steady-state serving retrace-free.
    Per-request variation rides the ``seed`` (and greedy engines ignore
    it).  ``eos_id`` stops a request early; every request also carries
    its own ``max_new_tokens`` budget.

    Drive it either by calling :meth:`step` yourself (tests, fully
    deterministic single-threaded use) or via :meth:`start`'s background
    tick thread (the frontend's mode).
    """

    def __init__(self, model: Transformer, variables, *,
                 n_slots: int = 8, max_seq: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 kv_quant: bool = False, cache_layout: str = "grouped",
                 max_queue: int = 64,
                 prefill_credits: Optional[int] = None,
                 min_prefill_bucket: int = 8,
                 chunk: int = 0,
                 prefix_cache=False,
                 prefix_block: int = 16,
                 prefix_bytes: int = 256 << 20,
                 paged: bool = False,
                 block: int = 16,
                 kv_mb: int = 0,
                 kv_blocks: Optional[int] = None,
                 kv_dtype: str = "",
                 paged_kernel: str = "auto",
                 tp: int = 0,
                 spec_k: int = 0,
                 spec_ngram: int = 3,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.variables = variables
        cfg = model.cfg
        self.max_seq = max_seq if max_seq is not None else cfg.max_seq_len
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.greedy = temperature == 0
        self.min_prefill_bucket = max(1, min_prefill_bucket)
        # chunked prefill: normalize the chunk size onto the prefill
        # bucket grid (power-of-two multiple of min_prefill_bucket) so
        # every mid chunk hits one compiled program; 0 = whole-prompt
        # prefill (the PR 2 path, bit-identical)
        self.chunk = (_next_bucket(chunk, self.min_prefill_bucket,
                                   self.max_seq) if chunk and chunk > 0
                      else 0)
        # paged KV cache (serving/blocks.py): block-granular slot
        # memory with zero-copy prefix sharing.  Every paged prefill
        # runs through the position-offset chunk path (whole prompt as
        # one chunk when chunk == 0) so ONE write discipline — gather,
        # write the span, scatter the touched blocks back — covers all
        # prefill, and the traced-position constraints below apply.
        self.paged = bool(paged)
        # int8 paged pool (kv_dtype="int8", BYTEPS_SERVE_KV_DTYPE):
        # blocks store s8 values + per-(position, head) f32 scale rows,
        # quantized AT WRITE time on every path (fused scatter, chunk
        # prefill, gather fallback) — every read at a traced position
        # sees the same quantized bytes, so preempt/resume re-prefill
        # and the disagg fallback reproduce identical int8 blocks.
        # This is exactly the discipline the legacy dense kv_quant knob
        # LACKS (its static-pos=0 whole-prompt prefill attends
        # pre-quantization values), hence the two are mutually
        # exclusive rather than composable.
        if kv_dtype not in ("", "int8"):
            raise ValueError(
                f"kv_dtype must be '' or 'int8', got {kv_dtype!r}")
        if kv_dtype and kv_quant:
            raise ValueError(
                "kv_quant and kv_dtype are mutually exclusive: kv_quant "
                "quantizes the DENSE cache (whole-prompt prefill "
                "attends pre-quantization values — incompatible with "
                "paging/chunking/resume), kv_dtype quantizes the PAGED "
                "block pool with write-time determinism.  Pick one: "
                "kv_quant=True for dense engines, kv_dtype='int8' for "
                "paged engines.")
        if kv_dtype and not self.paged:
            raise ValueError(
                "kv_dtype='int8' quantizes the paged block pool and "
                "requires paged=True; dense engines quantize with "
                "kv_quant=True instead")
        self.kv_dtype = kv_dtype
        # fused paged-attention kernel (ops/paged_attention.py): decode
        # and spec-verify read allocated, position-covered blocks IN
        # PLACE through the block table instead of gathering a dense
        # row per slot per tick — the cache-stream copy the gather
        # path pays is gone.  "auto" = on for paged engines on TPU
        # (where the Mosaic kernel is compiled; the CPU fallback would
        # run interpret-mode Pallas per tick and crawl), "on" forces
        # it (CPU CI runs it in interpret mode for parity tests),
        # "off" keeps the XLA gather.  Prefill chunks always ride the
        # gather path — they run once per chunk, not once per tick.
        pk = paged_kernel
        if isinstance(pk, bool):
            pk = "on" if pk else "off"
        if pk not in ("auto", "on", "off"):
            raise ValueError(
                f"paged_kernel must be 'auto'|'on'|'off', got "
                f"{paged_kernel!r}")
        if self.paged and pk == "auto" and jax.default_backend() == "tpu":
            # VMEM gate: the widest verify program's f32 accumulator
            # ([ (spec_k+1)*H pad 16, KV*D ]) plus the double-buffered
            # block pair must fit; an oversized config keeps the
            # pos-capped gather instead of failing the Mosaic compile
            # at the first decode tick ("on" forces past the gate)
            from ..ops.paged_attention import paged_attention_usable

            tq_max = (spec_k if spec_k and spec_k > 0 else 0) + 1
            pk = ("auto" if paged_attention_usable(
                (n_slots, tq_max, cfg.num_heads, cfg.d_head), block,
                cfg.kv_heads * cfg.d_head) else "off")
        self.paged_kernel = self.paged and (
            pk == "on"
            or (pk == "auto" and jax.default_backend() == "tpu"))
        if self.paged and not self.paged_kernel and cache_layout == "flat":
            raise ValueError(
                "cache_layout='flat' on a paged engine requires the "
                "fused paged-attention kernel (paged_kernel='on'): the "
                "gather fallback would route flat rows through the "
                "dense decode kernel under vmap")
        # chunk (and prefix-resumed, and every paged) prefill attends
        # at a TRACED position, which under kv_quant reads the
        # already-quantized int8 K/V — whole-prompt prefill at static
        # pos=0 reads the pre-quantization values instead
        # (models/transformer.py dense fallback), so the combination
        # would silently diverge from generate() and from a chunk=0
        # engine.  Refuse loudly.
        if kv_quant and (self.chunk or prefix_cache or self.paged):
            raise ValueError(
                "chunked prefill / prefix cache / paged KV cache "
                "require a dense KV cache: a chunk at a traced "
                "position attends int8 K/V where whole-prompt prefill "
                "attends the pre-quantization values, breaking the "
                "bit-exact parity contract.  Run kv_quant engines with "
                "chunk=0, prefix_cache=False, paged=False — or, to "
                "quantize a PAGED engine, use kv_dtype='int8' "
                "(BYTEPS_SERVE_KV_DTYPE), whose quantize-at-write "
                "discipline is consistent at traced positions and "
                "composes with chunking, prefix reuse, and resume.")
        # same hazard class for flash prefill: whole-prompt prefill at
        # static pos=0 can take the Pallas flash kernel (attn_impl=
        # "flash" + the gcd bucket gate), while a chunk at a traced
        # position always takes dense cached attention — the two differ
        # in accumulation order, so greedy tokens could silently
        # diverge from generate().  max_seq < 128 can never produce a
        # flash-eligible bucket (the gate needs gcd(bucket, 1024) >=
        # 128 and buckets never exceed max_seq), so tiny configs pass.
        if (self.chunk or prefix_cache or self.paged) and (
                cfg.attn_impl == "flash" and not cfg.has_sp
                and self.max_seq >= 128):
            raise ValueError(
                "chunked prefill / prefix cache / paged KV cache "
                "require the dense prefill path: this config's "
                "whole-prompt prefill can take the flash kernel while "
                "chunks always take dense cached attention, and the "
                "two differ in accumulation order — token streams "
                "could silently diverge from generate().  Serve "
                "attn_impl='flash' models with chunk=0, "
                "prefix_cache=False, paged=False.")
        # cross-replica resume (serving/router.py failover): a
        # resume-with-prefix submit re-prefills prompt + already-emitted
        # tokens and continues the parked token/key chain — bit-exact
        # only when prefill of the emitted region reproduces the K/V the
        # ORIGINAL run's decode wrote.  kv_quant breaks that (prefill
        # attends pre-quantization values where decode attended int8),
        # and a flash-eligible whole-prompt prefill differs from dense
        # decode in accumulation order — both are refused at submit.
        # (kv_dtype="int8" is deliberately NOT resume-unsafe: the paged
        # pool quantizes at write time on every path, so a resume's
        # chunked re-prefill reproduces the original run's int8 blocks
        # byte-for-byte — the determinism the dense knob lacks.)
        if kv_quant:
            self._resume_unsafe = (
                "kv_quant: resume prefill attends pre-quantization K/V "
                "where the original decode attended the quantized values")
        elif (cfg.attn_impl == "flash" and not cfg.has_sp
                and self.max_seq >= 128):
            self._resume_unsafe = (
                "attn_impl='flash': resume prefill can take the flash "
                "kernel while the original run's emitted-token K/V came "
                "from dense decode — accumulation orders differ")
        else:
            self._resume_unsafe = ""
        # speculative decoding (serving/spec.py): depth rounds DOWN to
        # a power of two so a tick capped by row space can halve its
        # bucket and stay on the compiled-bucket grid ({1, 2, 4, ...}),
        # the same discipline as prefill buckets.
        if spec_k and spec_k > 0:
            if kv_quant:
                # conservative twin of the chunk/prefix/paged refusal:
                # spec's whole value is multi-token parity guarantees,
                # and the int8 cache's flat-layout decode kernel (tq=1)
                # vs the dense tq>1 verify is exactly the accumulation-
                # order divergence that breaks them
                raise ValueError(
                    "speculative decoding requires a dense fp KV cache "
                    "(kv_quant=False): the verify pass must be bit-"
                    "exact against single-token decode, which the "
                    "quantized cache paths do not guarantee across "
                    "query widths")
            if cache_layout != "grouped":
                raise ValueError(
                    f"speculative decoding requires cache_layout="
                    f"'grouped' (got {cache_layout!r}): a flat-layout "
                    f"pool decodes tq=1 through the fused Pallas "
                    f"kernel while the tq>1 verify always runs dense "
                    f"cached attention — the two differ in "
                    f"accumulation order, so accepted tokens could "
                    f"silently diverge from the non-speculative stream")
            if (kv_dtype and not self.paged_kernel
                    and jax.default_backend() == "tpu"):
                # the int8 pool forces flat storage, and on TPU the
                # gather fallback's tq=1 tick takes the fused decode
                # kernel while the tq>1 verify runs dense q8 attention
                # — the same accumulation-order divergence the
                # cache_layout refusal above guards.  The fused paged
                # kernel serves BOTH widths identically, so spec +
                # int8 is fine with paged_kernel on (and off-TPU both
                # widths run dense q8).
                raise ValueError(
                    "speculative decoding on an int8 paged pool "
                    "(kv_dtype='int8') requires the fused paged kernel "
                    "on TPU (paged_kernel='on'/'auto'): the gather "
                    "fallback decodes tq=1 through the fused dense "
                    "kernel while the tq>1 verify runs dense q8 "
                    "attention, which differ in accumulation order")
            k = 1
            while k * 2 <= spec_k:
                k *= 2
            # ngram floors at 2 (the documented contract): single-token
            # matches fire on any vocabulary reuse, and every false
            # proposal costs a widened verify forward — exactly the
            # overhead bound the non-repetitive bench leg gates
            self.spec = NgramProposer(k, max(2, spec_ngram))
        else:
            self.spec = None
        # tensor-parallel serving: tp > 1 shards the paged block pool
        # into per-KV-head-slice sub-pools ([tp, n_blocks, block,
        # (KV/tp)*D] — serving/blocks.py).  0 defers to the BYTEPS_TP
        # config knob; 1 serves unsharded.  Attention is exactly
        # partitioned by KV head (docs/parallel.md), so the sharded
        # engine's token stream is identical to the unsharded one.
        if not tp:
            from ..common.config import get_config as _gc
            tp = max(1, int(getattr(_gc(), "serve_tp", 1)))
        if tp > 1:
            if not self.paged:
                raise ValueError(
                    f"tp ({tp}) > 1 requires paged=True: tensor-"
                    f"parallel serving shards the paged block pool per "
                    f"KV-head slice; dense slot caches shard through "
                    f"init_cache's mesh path instead")
            if cfg.num_heads % tp:
                raise ValueError(
                    f"tp ({tp}) must divide num_heads "
                    f"({cfg.num_heads}) so query head slices align "
                    f"with KV head slices")
        self.tp = tp
        if self.paged:
            self.pool = PagedSlotPool(
                cfg, n_slots, self.max_seq, block=block,
                n_blocks=kv_blocks, kv_bytes=kv_mb << 20,
                kv_quant=kv_quant, kv_dtype=kv_dtype, tp=tp,
                layout=("flat" if (self.paged_kernel or tp > 1)
                        else cache_layout))
        else:
            self.pool = SlotPool(cfg, n_slots, self.max_seq,
                                 kv_quant=kv_quant, layout=cache_layout)
        # prefix-reuse KV cache: True builds a private store, or pass a
        # PrefixCache to share one across engines with IDENTICAL pool
        # geometry (entries are full cache-row buffers).  Every key is
        # salted with a fingerprint of THIS engine's weights, so
        # engines serving different checkpoints through a shared store
        # occupy disjoint key spaces — one model's K/V can never be
        # copied into another model's slot.  A PAGED engine's store
        # references its own block pool (entries are block-id lists, a
        # hit is a refcount bump, not a copy — serving/prefix.py
        # PagedPrefixCache), so it is always private: block ids are
        # meaningless in any other engine's pool.
        if self.paged and prefix_cache:
            if isinstance(prefix_cache, PrefixCache):
                raise ValueError(
                    "a paged engine's prefix store references its own "
                    "KV block pool (entries are block ids, not copied "
                    "buffers) and cannot be shared across engines; "
                    "pass prefix_cache=True")
            self.prefix = PagedPrefixCache(
                self.pool.alloc, block=self.pool.block,
                block_bytes=self.pool.block_bytes,
                max_bytes=prefix_bytes,
                on_evict=lambda n: self.metrics.bump(
                    sm.BLOCK_EVICTIONS, n))
        elif isinstance(prefix_cache, PagedPrefixCache):
            # the mirror refusal: a dense engine fed a paged store
            # would call insert() (refused) or copy entry.buffer — a
            # tuple of block ids, not a row pytree — into its cache
            raise ValueError(
                "a PagedPrefixCache references a paged engine's block "
                "pool and cannot back a dense engine; pass "
                "prefix_cache=True (or a plain PrefixCache)")
        elif isinstance(prefix_cache, PrefixCache):
            self.prefix = prefix_cache
        elif prefix_cache:
            self.prefix = PrefixCache(block=prefix_block,
                                      max_bytes=prefix_bytes)
        else:
            self.prefix = None
        # every prefix entry is one full cache row, so its size is fixed
        # by the pool geometry; when even one can never fit the byte
        # budget, _maybe_insert_prefix skips the device-side extract
        # entirely instead of paying it per request just for insert()
        # to refuse
        self._prefix_row_bytes = (sum(
            leaf.nbytes // n_slots
            for leaf in jax.tree_util.tree_leaves(self.pool.caches))
            if self.prefix is not None and not self.paged else 0)
        # the store salt commits to the weights AND the per-slot cache
        # row geometry (shape past the slot dim, dtype): an engine with
        # a different max_seq / layout / kv_quant sharing the store
        # sees a harmless miss instead of copying an incompatible
        # buffer and crashing the tick
        self._prefix_salt = b""
        self._weights_fp: Optional[str] = None
        if self.prefix is not None:
            geom = hashlib.blake2b(digest_size=16)
            for leaf in jax.tree_util.tree_leaves(self.pool.caches):
                geom.update(f"{leaf.shape[1:]}{leaf.dtype}".encode())
            wfp = weights_fingerprint(variables)
            self._weights_fp = wfp.hex()
            self._prefix_salt = wfp + geom.digest()
        # credit budget in padded prefill tokens per tick; default = one
        # max-length prefill (or, with chunking on, one chunk — the
        # whole point is bounding per-tick prefill), i.e. "a tick admits
        # at most one worst-case prompt's worth of prefill work" —
        # decode latency stays bounded while short prompts can still
        # batch several admissions per tick.  With chunking the budget
        # is floored at the chunk size so a continuation chunk can
        # always make progress on a fresh tick.
        budget = (prefill_credits if prefill_credits and prefill_credits > 0
                  else (self.chunk or self.max_seq))
        if self.chunk:
            budget = max(budget, self.chunk)
        self.scheduler = ServeScheduler(
            max_queue=max_queue, credit_budget=budget)
        self.metrics = metrics if metrics is not None else get_serve_metrics()
        # per-request trace ids (docs/observability.md) — resolved once;
        # submit pays one attribute check when tracing is off
        from ..observability.trace import rpc_tracing_enabled

        self._trace_rpc = rpc_tracing_enabled()

        self._lock = threading.RLock()
        # router-epoch fence (serving/router.py "Router HA"): the
        # highest epoch any dispatch has carried.  Its own small lock —
        # the fence check runs on frontend handler threads before
        # submit and must never contend with the tick loop
        self._epoch_lock = threading.Lock()
        self._epoch_hw = 0
        self._req_seq = 0
        self._slot_req: List[Optional[Request]] = [None] * n_slots
        # slots mid-chunked-prefill: assigned (cache rows being written)
        # but excluded from the decode pass until the final chunk
        # samples their first token
        self._prefilling: Dict[int, Request] = {}
        self._tick_chunk_debt = 0   # take_credits() debits to return
        self._tick_prefill = 0      # padded prefill tokens this tick
        self._tok = jnp.zeros((n_slots,), jnp.int32)
        self._keys = jnp.zeros((n_slots, 2), jnp.uint32)
        self._outstanding = 0
        self._drain_cv = threading.Condition(self._lock)
        self._wake = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = False
        self._engine_error: Optional[BaseException] = None
        # trace-time counters: the Python body of a jitted fn runs only
        # when jax (re)traces, so these count compilations portably —
        # steady-state stability is asserted on them
        self.decode_traces = 0
        self.prefill_traces = 0
        self.chunk_traces = 0
        self.prefix_copy_traces = 0
        self.prefix_extract_traces = 0
        self.block_cow_traces = 0
        self.verify_traces = 0
        # donate the cache pool into each step: the pool is replaced by
        # the step's output, and without donation XLA would copy every
        # layer's full [N, S, ...] cache (or [n_blocks, block, ...]
        # block pool) per tick just to write one row.  Dense engines
        # compile ONE decode program; paged engines compile one per
        # gather high-water bucket (the chunk-bucket discipline —
        # ``compile_counts()["decode_buckets"]`` pins it), or exactly
        # one on the fused-kernel path.
        self._decode_step = (
            None if self.paged
            else jax.jit(self._make_decode_fn(), donate_argnums=(1,)))
        self._paged_decode_fns: Dict[object, object] = {}
        self._prefill_fns: Dict[int, object] = {}
        self._chunk_fns: Dict[int, object] = {}
        # verify programs, keyed by query width tq = depth + 1 — one
        # compiled program per speculation-depth bucket (pinned by
        # compile_counts, the chunk-bucket discipline)
        self._verify_fns: Dict[int, object] = {}
        self._copy_fn = None
        self._extract_fn = None
        self._cow_fn = None
        # disaggregated serving (serving/disagg): finished-but-unshipped
        # parked KV — req.id -> {"ids": [block ids, incref'd], "pos": T}
        # — plus the lazily-jitted single-block scatter the decode-side
        # stager writes received blocks with.  Bounded by
        # BYTEPS_DISAGG_PARKED_CAP (oldest evicted + released).
        from collections import OrderedDict

        from ..common.config import get_config

        self._kv_write_fn = None
        self._parked_kv: "OrderedDict[int, dict]" = OrderedDict()
        self._parked_cap = max(1, get_config().disagg_parked_cap)

    # ---------------------------------------------------- jitted programs
    #
    # The decode, prefill, and chunk programs all end with the same
    # "pick a token, write the slot's row back" tail; it lives in ONE
    # place (_select_token/_slot_row/_write_row) so a fix to the
    # sampling key chain or the write-back discipline cannot silently
    # diverge between paths — the bit-exact parity anchor depends on
    # every path agreeing.

    def _select_token(self, logits_last, key):
        """Greedy/sampled token pick from ``[1, vocab]`` last-position
        logits, returning ``(token, carried_key)``.  Sampled mode
        replays generate()'s exact per-step key chain: carry split[0],
        sample with split[1]; greedy carries the key untouched."""
        if self.greedy:
            return jnp.argmax(logits_last[0], axis=-1).astype(jnp.int32), key
        nk, sub = jax.random.split(key)
        tok = sample_logits(logits_last, sub, self.temperature,
                            self.top_k, self.top_p)[0].astype(jnp.int32)
        return tok, nk

    @staticmethod
    def _slot_row(caches, slot):
        """Slice one slot's ``[1, ...]`` cache row out of the pool."""
        return jax.tree_util.tree_map(
            lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0),
            caches)

    @staticmethod
    def _write_row(caches, new_row, slot):
        """Write a ``[1, ...]`` row back into the (donated) pool."""
        return jax.tree_util.tree_map(
            lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                c, r, slot, axis=0),
            caches, new_row)

    def _make_decode_fn(self):
        model, greedy = self.model, self.greedy
        pad_id = self.pad_id
        select = self._select_token

        def one(variables, row, tok, pos, key):
            rowb = jax.tree_util.tree_map(lambda c: c[None], row)
            logits, new = model.apply(
                variables, tok[None, None], rowb, pos,
                method=Transformer.decode)
            nxt, nk = select(logits[:, -1], key)
            return jax.tree_util.tree_map(lambda c: c[0], new), nxt, nk

        def decode_fn(variables, caches, tok, pos, active, keys):
            self.decode_traces += 1  # trace-time only
            caches, nxt, keys2 = jax.vmap(
                one, in_axes=(None, 0, 0, 0, 0))(
                    variables, caches, tok, pos, keys)
            nxt = jnp.where(active, nxt, pad_id)
            if not greedy:
                keys2 = jnp.where(active[:, None], keys2, keys)
            else:
                keys2 = keys
            return caches, nxt, keys2

        return decode_fn

    def _paged_decode_fn(self, hw: Optional[int]):
        """Jitted paged decode step, two flavors:

        * ``hw`` an int — the XLA **gather** fallback at that block
          high-water bucket: per slot, gather ``table[:hw]``'s blocks
          into a ``hw * block``-row dense view (NOT the full
          ``max_seq`` width — the pos-capped gather stops streaming
          null-block / unwritten padding), run the SAME per-row decode
          (one attention implementation — ``Transformer.decode_paged``
          delegates to ``decode``), then scatter every slot's fresh
          K/V into the pool at its ``(write block, offset)`` target.
          One compiled program per bucket, the chunk-bucket
          discipline.
        * ``hw is None`` — the **fused kernel** path: one un-vmapped
          ``decode_paged_fused`` call serves the whole pool; fresh K/V
          scatters into the pool inside the forward and the Pallas
          kernel reads blocks in place through the table — no gather
          exists.

        Masked slots (free or PREFILLING) scatter into the null block
        either way, so their garbage write can never touch a shared
        prefix block or a mid-prefill row — simpler than the dense
        path's aim-at-the-cursor discipline."""
        key = "kernel" if hw is None else hw
        fn = self._paged_decode_fns.get(key)
        if fn is not None:
            return fn
        model, greedy = self.model, self.greedy
        pad_id = self.pad_id
        select = self._select_token
        tp = self.tp

        if hw is None:
            def decode_fn(variables, pcaches, tok, pos, active, keys,
                          tables, wblk, woff):
                self.decode_traces += 1  # trace-time only
                logits, new_pc = model.apply(
                    variables, tok[:, None], pcaches, tables, pos,
                    wblk, woff, True,
                    method=Transformer.decode_paged_fused)
                nxt, keys2 = jax.vmap(
                    lambda lg, k: select(lg[None], k))(
                        logits[:, -1], keys)
                nxt = jnp.where(active, nxt, pad_id)
                if not greedy:
                    keys2 = jnp.where(active[:, None], keys2, keys)
                else:
                    keys2 = keys
                return new_pc, nxt, keys2
        else:
            def one(variables, pcaches, table, tok, pos, key):
                logits, new_rows = model.apply(
                    variables, tok[None, None], pcaches, table, pos,
                    hw_blocks=hw, tp=tp,
                    method=Transformer.decode_paged)
                nxt, nk = select(logits[:, -1], key)
                # the one written position, sliced back out of the
                # gathered row for the pool scatter below
                fresh = tuple(
                    {n: jax.lax.dynamic_slice_in_dim(r[n], pos, 1,
                                                     axis=1)[0, 0]
                     for n in r} for r in new_rows)
                return fresh, nxt, nk

            def decode_fn(variables, pcaches, tok, pos, active, keys,
                          tables, wblk, woff):
                self.decode_traces += 1  # trace-time only
                # the hw cap is applied in ONE place: decode_paged's
                # hw_blocks slices each slot's table inside the vmap
                fresh, nxt, keys2 = jax.vmap(
                    one, in_axes=(None, None, 0, 0, 0, 0))(
                        variables, pcaches, tables, tok, pos, keys)
                nxt = jnp.where(active, nxt, pad_id)
                if not greedy:
                    keys2 = jnp.where(active[:, None], keys2, keys)
                else:
                    keys2 = keys
                if tp == 1:
                    new_pc = tuple(
                        {n: pc[n].at[wblk, woff].set(fr[n]) for n in pc}
                        for pc, fr in zip(pcaches, fresh))
                else:
                    # fresh leaves are head-major ([N, KV, D] values /
                    # [N, KV] scales): splitting the head axis into tp
                    # contiguous slices is exactly the per-shard
                    # partition of the unsharded row's bytes
                    new_pc = tuple(
                        {n: pc[n].at[:, wblk, woff].set(
                            fr[n].reshape(fr[n].shape[0], tp, -1)
                            .transpose(1, 0, 2)) for n in pc}
                        for pc, fr in zip(pcaches, fresh))
                return new_pc, nxt, keys2

        fn = jax.jit(decode_fn, donate_argnums=(1,))
        self._paged_decode_fns[key] = fn
        return fn

    def _verify_accept(self, props, tmat, kchain, prop_len, active,
                       tok, keys, budget):
        """The in-program accept/truncate tail shared by the dense and
        paged verify steps: given the candidate tokens ``tmat [N, tq]``
        (the model's pick at every position) and the proposals that fed
        positions ``1..d`` (``props [N, d]``), compute per slot the
        accepted count (1 + the leading run of proposals that equal the
        model's own tokens — position 0 IS the plain decode step, so a
        slot can never emit less than the non-speculative engine), then
        truncate at the request's remaining ``budget`` and at the first
        EOS, and pick the carried next-input token and sampling-key
        state matching EXACTLY the tokens that will be emitted —
        rejected positions' key splits are discarded with them, so the
        per-request chain stays generate()'s (seeded parity by replay).
        Running on device keeps ``_tok``/``_keys`` resident: the host
        reads back only the small (tmat, counts) arrays to emit."""
        d = tmat.shape[1] - 1
        ok = ((props == tmat[:, :-1])
              & (jnp.arange(d)[None, :] < prop_len[:, None]))
        lead = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
        m = jnp.minimum(1 + lead, jnp.maximum(budget, 1))
        if self.eos_id is not None:
            is_eos = tmat == self.eos_id
            m = jnp.where(jnp.any(is_eos, axis=1),
                          jnp.minimum(m, jnp.argmax(is_eos, axis=1) + 1),
                          m)
        idx = (m - 1)[:, None]
        nxt = jnp.take_along_axis(tmat, idx, axis=1)[:, 0]
        nxt = jnp.where(active, nxt, tok)
        if self.greedy:
            nkeys = keys
        else:
            nkeys = jnp.take_along_axis(kchain, idx[:, :, None],
                                        axis=1)[:, 0]
            nkeys = jnp.where(active[:, None], nkeys, keys)
        m = jnp.where(active, m, 0)
        accepted = jnp.where(active, lead, 0)
        return nxt, nkeys, tmat, m, accepted

    def _verify_fn(self, tq: int):
        """Jitted speculative verify for one depth bucket (``tq`` =
        depth + 1 query positions): every slot runs the SAME per-row
        multi-token decode (``Transformer.verify_tokens`` — one
        attention implementation), vmapped over the pool exactly like
        the one-token step, then the in-program accept/truncate tail
        (``_verify_accept``) picks each slot's emitted prefix and
        carried token/key state.  Returns ``(caches, tok, keys,
        tmat, m_emit, accepted)`` — the host emits ``tmat[s, :m_emit]``
        per active slot and advances cursors; everything else stays on
        device."""
        fn = self._verify_fns.get(tq)
        if fn is not None:
            return fn
        model = self.model
        select = self._select_token

        def one(variables, row, toks, pos, key):
            rowb = jax.tree_util.tree_map(lambda c: c[None], row)
            logits, new = model.apply(
                variables, toks[None, :], rowb, pos,
                method=Transformer.verify_tokens)
            ts, ks, k = [], [], key
            for i in range(tq):
                t_i, k = select(logits[:, i], k)
                ts.append(t_i)
                ks.append(k)
            return (jax.tree_util.tree_map(lambda c: c[0], new),
                    jnp.stack(ts), jnp.stack(ks))

        def verify_fn(variables, caches, props, prop_len, pos, active,
                      tok, keys, budget):
            self.verify_traces += 1  # trace-time only
            toks = jnp.concatenate([tok[:, None], props], axis=1)
            caches, tmat, kchain = jax.vmap(
                one, in_axes=(None, 0, 0, 0, 0))(
                    variables, caches, toks, pos, keys)
            return (caches,) + self._verify_accept(
                props, tmat, kchain, prop_len, active, tok, keys,
                budget)

        fn = jax.jit(verify_fn, donate_argnums=(1,))
        self._verify_fns[tq] = fn
        return fn

    def _paged_verify_fn(self, tq: int, hw: Optional[int]):
        """Paged twin of ``_verify_fn``, two flavors like the decode
        step.  Gather (``hw`` an int): gather ``table[:hw]``'s blocks
        per slot (the pos-capped high-water bucket — never the full
        null-padded width), verify the ``tq``-position span, then
        scatter the span's fresh K/V back **per position** to the
        host-computed ``(block, offset)`` targets — touched blocks
        only, never a whole-block rewrite, so a shared prefix block can
        never be written (ungranted or masked positions aim at the null
        block, and ``prop_len`` is pre-capped at the granted coverage
        so acceptance can never advance a cursor onto an unwritten
        position).  Fused kernel (``hw is None``): the same program
        shape as the kernel decode step, one query width wider —
        plain decode and verify ride the SAME kernel, which is what
        keeps spec-on token-identical to spec-off on this path."""
        key = ("kernel", tq) if hw is None else (tq, hw)
        fn = self._verify_fns.get(key)
        if fn is not None:
            return fn
        model = self.model
        select = self._select_token
        tp = self.tp

        def chain(lg, key):
            """Per-slot select chain over ``lg [tq, vocab]``."""
            ts, ks, k = [], [], key
            for i in range(tq):
                t_i, k = select(lg[i][None], k)
                ts.append(t_i)
                ks.append(k)
            return jnp.stack(ts), jnp.stack(ks)

        if hw is None:
            def verify_fn(variables, pcaches, props, prop_len, pos,
                          active, tok, keys, budget, tables, wblk,
                          woff):
                self.verify_traces += 1  # trace-time only
                toks = jnp.concatenate([tok[:, None], props], axis=1)
                logits, new_pc = model.apply(
                    variables, toks, pcaches, tables, pos, wblk, woff,
                    method=Transformer.verify_tokens_paged_fused)
                tmat, kchain = jax.vmap(chain)(logits, keys)
                return (new_pc,) + self._verify_accept(
                    props, tmat, kchain, prop_len, active, tok, keys,
                    budget)
        else:
            def one(variables, pcaches, table, toks, pos, key):
                logits, new_rows = model.apply(
                    variables, toks[None, :], pcaches, table, pos,
                    hw_blocks=hw, tp=tp,
                    method=Transformer.verify_tokens_paged)
                ts, ks = chain(logits[0], key)
                # the tq written positions, sliced back out of the
                # gathered row for the per-position pool scatter below
                fresh = tuple(
                    {n: jax.lax.dynamic_slice_in_dim(r[n], pos, tq,
                                                     axis=1)[0]
                     for n in r} for r in new_rows)
                return fresh, ts, ks

            def verify_fn(variables, pcaches, props, prop_len, pos,
                          active, tok, keys, budget, tables, wblk,
                          woff):
                self.verify_traces += 1  # trace-time only
                toks = jnp.concatenate([tok[:, None], props], axis=1)
                fresh, tmat, kchain = jax.vmap(
                    one, in_axes=(None, None, 0, 0, 0, 0))(
                        variables, pcaches, tables, toks, pos, keys)
                if tp == 1:
                    new_pc = tuple(
                        {n: pc[n].at[wblk, woff].set(fr[n]) for n in pc}
                        for pc, fr in zip(pcaches, fresh))
                else:
                    # per-position head-major split, as in the decode
                    # scatter, one query-width axis wider
                    new_pc = tuple(
                        {n: pc[n].at[:, wblk, woff].set(
                            fr[n].reshape(fr[n].shape[0], tq, tp, -1)
                            .transpose(2, 0, 1, 3)) for n in pc}
                        for pc, fr in zip(pcaches, fresh))
                return (new_pc,) + self._verify_accept(
                    props, tmat, kchain, prop_len, active, tok, keys,
                    budget)

        fn = jax.jit(verify_fn, donate_argnums=(1,))
        self._verify_fns[key] = fn
        return fn

    def _paged_chunk_fn(self, bucket: int):
        """Paged twin of ``_chunk_fn``: gather the slot's rows through
        its block table, run the position-offset chunk, then scatter
        the written span's blocks back into the pool.  The span covers
        at most ``1 + ceil((bucket - 1) / block)`` consecutive logical
        blocks (static count); the scatter writes exactly those —
        out-of-range or untouched trailing entries write their own
        unchanged bytes (or land on the null block), which is a no-op
        by value, so shared blocks outside the span are never
        altered."""
        fn = self._chunk_fns.get(bucket)
        if fn is not None:
            return fn
        model, select = self.model, self._select_token
        blk = self.pool.block
        mb = self.pool.max_blocks
        null = self.pool.null_block
        nb_touch = (bucket - 1) // blk + 2
        tp = self.tp

        def chunk_fn(variables, pcaches, tokens, table, start, last_idx,
                     key):
            self.chunk_traces += 1  # trace-time only
            logits, new_rows = model.apply(
                variables, tokens, pcaches, table, start, last_idx,
                tp=tp, method=Transformer.prefill_chunk_paged)
            tok0, nk = select(logits[:, -1], key)
            first = start // blk
            new_pc = []
            for pc, nr in zip(pcaches, new_rows):
                out = {}
                for n, c in pc.items():
                    for i in range(nb_touch):
                        idx = first + i
                        safe = jnp.minimum(idx, mb - 1)
                        src = jax.lax.dynamic_slice_in_dim(
                            nr[n], safe * blk, blk, axis=1)[0]
                        bid = jnp.where(idx < mb, table[safe], null)
                        if tp == 1:
                            c = c.at[bid].set(src)
                        else:
                            c = c.at[:, bid].set(
                                src.reshape(blk, tp, -1)
                                .transpose(1, 0, 2))
                    out[n] = c
                new_pc.append(out)
            return tuple(new_pc), tok0, nk

        fn = jax.jit(chunk_fn, donate_argnums=(1,))
        self._chunk_fns[bucket] = fn
        return fn

    def _cow_copy(self, src: int, dst: int) -> None:
        """Device-side block copy backing a copy-on-write fork
        (``PagedSlotPool.make_writable``): one compiled program for
        every (src, dst) pair."""
        if self._cow_fn is None:
            if self.tp == 1:
                def cow(pcaches, src, dst):
                    self.block_cow_traces += 1  # trace-time only
                    return tuple(
                        {n: c[n].at[dst].set(c[n][src]) for n in c}
                        for c in pcaches)
            else:
                def cow(pcaches, src, dst):
                    self.block_cow_traces += 1  # trace-time only
                    # block axis is axis 1 behind the shard axis; the
                    # copy replicates the fork on every shard
                    return tuple(
                        {n: c[n].at[:, dst].set(c[n][:, src])
                         for n in c}
                        for c in pcaches)

            self._cow_fn = jax.jit(cow, donate_argnums=(0,))
        self.pool.caches = self._cow_fn(self.pool.caches,
                                        jnp.int32(src), jnp.int32(dst))

    # ------------------------------------------- disagg KV ship seam
    #
    # The prefill side of a disaggregated ship reads parked blocks out
    # of the pool (extract_kv_blocks); the decode side scatters received
    # blocks in (write_kv_block).  Both run under ``self._lock``: the
    # tick thread DONATES ``pool.caches`` into every step, so an
    # unlocked reader could hold a deleted buffer mid-copy.

    def take_parked_kv(self, req_id: int) -> Optional[dict]:
        """Claim (and remove) the parked KV entry a finished ``keep_kv``
        request left behind.  The caller owns the returned block refs
        and must ``release_kv_ids`` them when done."""
        with self._lock:
            return self._parked_kv.pop(req_id, None)

    def release_kv_ids(self, ids) -> None:
        """Drop one reference per block id (parked entries, refused
        adoptions, aborted stagings)."""
        if not ids:
            return
        with self._lock:
            for b in ids:
                self.pool.alloc.decref(int(b))

    def stage_alloc(self, n: int) -> List[int]:
        """Allocate ``n`` pool blocks for an incoming ship (decode
        side); raises ``BlocksExhaustedError`` when the pool cannot
        cover it — the sender aborts and the router re-prefills."""
        with self._lock:
            return self.pool.alloc.alloc(n)

    def extract_kv_blocks(self, ids) -> List[Dict[str, np.ndarray]]:
        """Host copies of the pool rows backing ``ids``: one dict per
        layer, each value ``[len(ids), ...block row]`` — the ship
        payload.  Row-major bytes are layout-identical between the
        grouped and flat pool layouts (same trailing element count), so
        the wire format does not encode the layout.  A tp-sharded pool
        reassembles each block's per-shard slices head-major into the
        unsharded flat row bytes, so ships are tp-count independent:
        a tp=2 prefill tier can feed an unsharded (or tp=4) decode
        tier."""
        idx = jnp.asarray(list(ids), jnp.int32)
        with self._lock:
            if self.tp == 1:
                return [{n: np.asarray(jnp.take(c[n], idx, axis=0))
                         for n in c} for c in self.pool.caches]
            out = []
            for c in self.pool.caches:
                layer = {}
                for n in c:
                    g = jnp.take(c[n], idx, axis=1)  # [tp, nb, blk, X]
                    layer[n] = np.asarray(
                        g.transpose(1, 2, 0, 3).reshape(
                            g.shape[1], g.shape[2], -1))
                out.append(layer)
            return out

    def write_kv_block(self, bid: int, layers) -> None:
        """Scatter ONE received block into the pool at physical id
        ``bid``.  ``layers`` is ``extract_kv_blocks``'s per-layer dict
        shape for a single block (leading axis dropped).  One compiled
        program total — the block id is a traced scalar."""
        if self._kv_write_fn is None:
            if self.tp == 1:
                def kv_write(pcaches, bid, blk):
                    return tuple(
                        {n: c[n].at[bid].set(blk[i][n]) for n in c}
                        for i, c in enumerate(pcaches))
            else:
                tp = self.tp

                def kv_write(pcaches, bid, blk):
                    # wire rows arrive in the unsharded head-major flat
                    # format (extract_kv_blocks); split the minor axis
                    # back into per-shard KV-head slices
                    return tuple(
                        {n: c[n].at[:, bid].set(
                            blk[i][n].reshape(
                                blk[i][n].shape[0], tp, -1)
                            .transpose(1, 0, 2)) for n in c}
                        for i, c in enumerate(pcaches))

            self._kv_write_fn = jax.jit(kv_write, donate_argnums=(0,))
        with self._lock:
            self.pool.caches = self._kv_write_fn(
                self.pool.caches, jnp.int32(bid), tuple(layers))

    def _park_kv_locked(self, req: Request) -> None:
        """Park a finished ``keep_kv`` request's blocks (incref BEFORE
        the slot free releases the table's own refs) so the frontend
        can ship them after the reply.  Cap-bounded: the oldest parked
        entry is evicted and released, never silently grown."""
        ids = list(self.pool.tables[req.slot].blocks)
        if not ids:
            return
        for b in ids:
            self.pool.alloc.incref(b)
        seq = req._seq if req._seq is not None else req.prompt
        self._parked_kv[req.id] = {"ids": ids, "pos": int(len(seq))}
        while len(self._parked_kv) > self._parked_cap:
            _, old = self._parked_kv.popitem(last=False)
            for b in old["ids"]:
                self.pool.alloc.decref(int(b))

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        model, select = self.model, self._select_token

        def prefill_fn(variables, caches, prompt, slot, true_len, key):
            self.prefill_traces += 1  # trace-time only
            logits, new_row = model.apply(
                variables, prompt, self._slot_row(caches, slot), true_len,
                method=_prefill_forward)
            tok0, nk = select(logits[:, -1], key)
            return self._write_row(caches, new_row, slot), tok0, nk

        fn = jax.jit(prefill_fn, donate_argnums=(1,))
        self._prefill_fns[bucket] = fn
        return fn

    def _chunk_fn(self, bucket: int):
        """Jitted position-offset chunk prefill for one bucket size:
        writes the chunk's K/V at ``[start, start + bucket)`` of the
        slot's row and returns the sampled token at chunk-local
        ``last_idx`` (meaningful only for a request's final chunk —
        mid-chunk callers discard it, and the carried key, so the
        sampling key chain still splits exactly once per request)."""
        fn = self._chunk_fns.get(bucket)
        if fn is not None:
            return fn
        model, select = self.model, self._select_token

        def chunk_fn(variables, caches, tokens, slot, start, last_idx, key):
            self.chunk_traces += 1  # trace-time only
            logits, new_row = model.apply(
                variables, tokens, self._slot_row(caches, slot), start,
                last_idx, method=Transformer.prefill_chunk)
            tok0, nk = select(logits[:, -1], key)
            return self._write_row(caches, new_row, slot), tok0, nk

        fn = jax.jit(chunk_fn, donate_argnums=(1,))
        self._chunk_fns[bucket] = fn
        return fn

    def _prefix_copy_fn(self):
        """Jitted device-side prefix restore: overwrite a slot's whole
        cache row with a stored full-row buffer.  Rows past the match
        length are the buffer's zero padding — safe stale content, the
        request's own prefill/decode overwrites them before the causal
        mask can admit them.  Full-row entries keep this ONE compiled
        program regardless of prefix length."""
        if self._copy_fn is None:
            def copy_fn(caches, buffer, slot):
                self.prefix_copy_traces += 1  # trace-time only
                return self._write_row(caches, buffer, slot)

            self._copy_fn = jax.jit(copy_fn, donate_argnums=(0,))
        return self._copy_fn

    def _prefix_extract_fn(self):
        """Jitted prefix capture: copy a slot's cache row with positions
        ``>= length`` zero-masked (one compiled program for every
        length).  NOT donated — the pool keeps its buffers."""
        if self._extract_fn is None:
            def extract_fn(caches, slot, length):
                self.prefix_extract_traces += 1  # trace-time only

                def ext(c):
                    row = jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0)
                    idx = jnp.arange(row.shape[1]).reshape(
                        (1, -1) + (1,) * (row.ndim - 2))
                    return jnp.where(idx < length, row,
                                     jnp.zeros_like(row))

                return jax.tree_util.tree_map(ext, caches)

            self._extract_fn = jax.jit(extract_fn)
        return self._extract_fn

    # ------------------------------------------------------------- submit

    @contextlib.contextmanager
    def epoch_fence(self, epoch: int):
        """Check-and-record a router dispatch's epoch ATOMICALLY with
        the admission or cancel performed inside the ``with`` block: any
        epoch LOWER than the high-water already seen raises the typed
        :class:`EpochFencedError` (the frontend turns it into a status=1
        reply).  Equal epochs are fine — the active router stamps every
        dispatch with its current epoch.  The fencing-token discipline:
        once a takeover router's first dispatch lands here, the deposed
        epoch can never place (or cancel) work on this engine again, so
        a request the new epoch re-dispatched cannot also be driven by
        its old leg (docs/serving.md "Router HA").  The lock is held
        across the body — a bare check-then-act would leave a window
        where a deposed router's dispatch passes the check, the takeover
        epoch's first dispatch lands, and the stale action still runs
        afterward, the exact interleaving the fence exists to refuse."""
        epoch = int(epoch)
        with self._epoch_lock:
            if epoch < self._epoch_hw:
                raise EpochFencedError(epoch, self._epoch_hw)
            self._epoch_hw = epoch
            yield

    def fence_epoch(self, epoch: int) -> None:
        """Point-in-time epoch check (see :meth:`epoch_fence`; dispatch
        paths that admit or cancel work must use the context-manager
        form so the check is atomic with the action)."""
        with self.epoch_fence(epoch):
            pass

    @property
    def epoch_high_water(self) -> int:
        with self._epoch_lock:
            return self._epoch_hw

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0, resume_tokens=None,
               epoch: Optional[int] = None, keep_kv: bool = False,
               kv_blocks=None) -> Request:
        """Enqueue a generation request.  Raises ``ValueError`` on an
        infeasible request and ``QueueFullError`` (typed backpressure)
        when the bounded admission queue is at capacity.

        ``epoch`` (router dispatches only) runs the whole admission
        under :meth:`epoch_fence`, so a stale-epoch dispatch racing the
        takeover epoch's first dispatch is refused, never admitted.

        ``resume_tokens`` resumes a request another engine already
        emitted ``k`` tokens for (the router's cross-replica failover,
        serving/router.py): this engine re-prefills prompt + emitted
        tokens (position-wise determinism rebuilds the exact K/V the
        original decode wrote — the PR 9 preempt/resume argument, one
        engine hop wider), restores the parked next-input token, and —
        under sampling — recomputes the carried key as the ``k``-fold
        split chain of ``PRNGKey(seed)``, so the continued stream is
        token-identical to a never-interrupted run.  The key state is
        recoverable by construction (a pure function of ``seed`` and
        ``k``); ``max_new_tokens`` stays the request's TOTAL budget and
        the resumed tokens count against it (only new tokens are
        streamed; ``result()`` returns the full sequence).

        ``keep_kv`` (disagg prefill replicas) parks the finished
        request's paged blocks for a post-reply ship instead of freeing
        them; ``kv_blocks`` (disagg decode replicas) carries staged,
        already-written block ids whose adoption replaces the prefill
        pass entirely (docs/serving.md "Disaggregated tiers")."""
        if epoch is not None:
            with self.epoch_fence(epoch):
                return self._submit(prompt, max_new_tokens, seed=seed,
                                    priority=priority,
                                    resume_tokens=resume_tokens,
                                    keep_kv=keep_kv, kv_blocks=kv_blocks)
        return self._submit(prompt, max_new_tokens, seed=seed,
                            priority=priority, resume_tokens=resume_tokens,
                            keep_kv=keep_kv, kv_blocks=kv_blocks)

    def _submit(self, prompt, max_new_tokens: int, *, seed: int,
                priority: int, resume_tokens, keep_kv: bool = False,
                kv_blocks=None) -> Request:
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        T = int(prompt.shape[0])
        if T < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        if T + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({T}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine max_seq {self.max_seq}")
        resumed: List[int] = ([int(t) for t in resume_tokens]
                              if resume_tokens is not None else [])
        if (resumed and self.eos_id is not None
                and resumed[-1] == self.eos_id):
            # the stream already ended at EOS on the engine that died:
            # there is nothing to generate (and decoding past EOS would
            # emit tokens a never-interrupted run never produces).
            # Answer an already-finished request — no slot, no prefill,
            # safe even on configs that refuse recompute-based resume.
            with self._lock:
                self._req_seq += 1
                req = Request(id=self._req_seq, prompt=prompt,
                              max_new_tokens=max_new_tokens, seed=seed,
                              priority=priority,
                              t_submit=time.monotonic())
                req.tokens = resumed
                req.state = RequestState.DONE
                req._out.put(_END)
                req._done.set()
                if kv_blocks is not None and self.paged:
                    # staged disagg blocks for a request that needs no
                    # decoding: nothing will adopt them — release now
                    for b in kv_blocks:
                        self.pool.alloc.decref(int(b))
                    kv_blocks = None
            self.metrics.bump(sm.SUBMITTED)
            self.metrics.bump(sm.COMPLETED)  # 0 tokens generated here
            return req
        if resumed:
            if self._resume_unsafe:
                raise ValueError(
                    f"this engine cannot resume a partially-emitted "
                    f"request bit-exactly ({self._resume_unsafe}); "
                    f"serve resumable replicas with a dense, "
                    f"non-flash-prefill config")
            if max_new_tokens <= len(resumed):
                raise ValueError(
                    f"resume carries {len(resumed)} tokens but "
                    f"max_new_tokens is {max_new_tokens} — nothing "
                    f"left to generate")
        # the admission grant is denominated in the padded tokens the
        # prefill will actually run: prompt plus (on resume) the
        # emitted tokens minus the parked last one
        bucket = _next_bucket(T + max(0, len(resumed) - 1),
                              self.min_prefill_bucket, self.max_seq)
        if self.chunk:
            # the admission grant pays for the FIRST chunk only; each
            # continuation chunk debits the same pool at process time
            bucket = min(bucket, self.chunk)
        # dead-engine check AND enqueue under the engine lock, which
        # _fail_all holds while draining: a submit racing the failure
        # path must either land before the drain (and be failed by it)
        # or see the error — never enqueue into a dead engine's queue.
        # The outstanding counter also increments here, BEFORE the tick
        # thread can see the request: a fast request could otherwise
        # finish (decrementing) first, and a concurrent drain() would
        # see a transiently-zero counter with work still in flight.
        with self._lock:
            if self._engine_error is not None:
                raise RuntimeError(
                    f"serving engine is dead (tick failed with "
                    f"{self._engine_error!r}); restart it") \
                    from self._engine_error
            self._req_seq += 1
            req = Request(id=self._req_seq, prompt=prompt,
                          max_new_tokens=max_new_tokens, seed=seed,
                          priority=priority, t_submit=time.monotonic())
            if resumed:
                # pre-seed the emitted tokens and park the resume state
                # exactly as _preempt would have: _admit then prefills
                # prompt + tokens[:-1] and the final chunk restores the
                # parked next-input token and carried key instead of
                # emitting a fresh "first" token
                req.tokens = resumed
                req._resumed_n = len(resumed)
                req._resume_tok = resumed[-1]
                if not self.greedy:
                    req._resume_key = _resume_key_chain(seed, len(resumed))
            req._keep_kv = bool(keep_kv and self.paged)
            if kv_blocks is not None and self.paged:
                req._kv_blocks = [int(b) for b in kv_blocks]
                kv_blocks = None  # ownership moved to the request
            if self._trace_rpc:
                # join the caller's active trace (a submit inside a
                # traced client op) or mint a fresh id for this request
                from ..observability.trace import (current_trace_id,
                                                   mint_trace_id)

                req.trace_id = (current_trace_id() or mint_trace_id()).hex()
                req._t_pc = time.perf_counter()
            self._outstanding += 1
            try:
                req._task = self.scheduler.submit(req, bucket)
            except Exception:
                self._outstanding -= 1
                self._drain_cv.notify_all()  # same lock; wake waiters
                self.metrics.bump(sm.REJECTED)
                raise
        self.metrics.bump(sm.SUBMITTED)
        with self._wake:
            self._wake.notify_all()
        return req

    def cancel(self, req: Request) -> None:
        """Request cancellation.  A still-QUEUED request is dropped from
        the admission queue immediately (it stops holding queue depth
        and never consumes a grant); the eager drop races admission
        under the engine lock, and the grant-time cancelled check stays
        as the fallback.  An in-flight (PREFILLING/DECODING) request is
        retired eagerly too: ``cancel()`` serializes with ``step()``
        on the engine lock, so no decode or chunk program is mid-
        flight, and the slot — and in the paged engine its non-shared
        KV blocks and prefix block references — returns to the pool
        *now*, admissible by the very next tick rather than one tick
        later.  The tick-start sweep remains as a belt-and-braces
        fallback for the flag-only path."""
        req.cancelled = True
        with self._lock:
            if (req.state is RequestState.QUEUED and req._task is not None
                    and self.scheduler.remove(req._task)):
                self._finish(req, RequestState.CANCELLED)
            elif (req.state in (RequestState.PREFILLING,
                                RequestState.ACTIVE)
                    and req.slot is not None
                    and self._engine_error is None):
                self._finish(req, RequestState.CANCELLED)
        with self._wake:
            self._wake.notify_all()

    # --------------------------------------------------------------- tick

    def step(self) -> Dict[str, int]:
        """One engine tick: cancellations -> credit-bounded admissions ->
        one batched decode pass -> credits return.  Returns tick stats."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> Dict[str, int]:
        emitted = 0
        admitted = 0
        granted: List = []
        self._tick_chunk_debt = 0
        self._tick_prefill = 0
        try:
            # 0. retire cancelled active/prefilling requests (frees
            # their slots for this tick's admissions)
            for slot in self.pool.active_slots():
                req = self._slot_req[slot]
                if req is not None and req.cancelled:
                    self._finish(req, RequestState.CANCELLED)
            # 1. continue in-flight chunked prefills (slot order) —
            # BEFORE new admissions: finishing started work frees
            # capacity soonest, and the continuation debits shrink the
            # credit pool the admission scan below sees
            for slot in sorted(self._prefilling):
                req = self._prefilling.get(slot)
                if req is not None:
                    emitted += self._advance_prefill(req)
            # 2. admissions, in scheduler grant order (priority desc,
            # FIFO)
            free = self.pool.free_count
            if free:
                granted = self.scheduler.admit(free)
                for task in granted:
                    req = task.request
                    if req.cancelled:
                        self._finish(req, RequestState.CANCELLED)
                    elif (self.paged and req._hold_blocks
                          and self.pool.alloc.free_count
                          < req._hold_blocks
                          and self.pool.active_count > 0):
                        # preempted request waiting out block pressure:
                        # stay QUEUED until its worst-case need fits
                        # (others are still freeing); with nothing else
                        # active it admits regardless — the pressure
                        # path then evicts the prefix store or fails
                        # loudly.  FCFS head-of-line: everything granted
                        # AFTER it goes back too, or a sustained stream
                        # of newer short requests would consume each
                        # tick's freed blocks and starve it forever
                        idx = granted.index(task)
                        for later in granted[idx:]:
                            self.scheduler.resubmit(later)
                        break
                    else:
                        req._hold_blocks = 0
                        admitted += 1
                        emitted += self._admit(req)
            # 3. one decode pass over the pool (PREFILLING slots are
            # assigned but not yet decodable — their first token comes
            # from their final prefill chunk)
            active = [s for s in self.pool.active_slots()
                      if s not in self._prefilling]
            if active:
                emitted += self._decode_tick(active)
        except Exception as e:
            # granted tasks are already popped from the queue: a
            # request whose _admit never ran (or raised before its slot
            # assignment) is invisible to both the queue drain and the
            # active-slot scan in _fail_all — fail it here or its
            # result()/drain() callers hang forever
            for task in granted:
                req = task.request
                if req.state is RequestState.QUEUED:
                    # a preempt-requeued request's task is back in the
                    # queue — pull the corpse so _fail_all's drain (or
                    # a later tick) cannot retire it a second time
                    self.scheduler.remove(task)
                    req.error = e
                    self._finish(req, RequestState.FAILED)
            raise
        finally:
            # 4. credits back — in normal ticks AFTER decode, so the
            # budget truly bounds the prefill work interleaved between
            # consecutive decode passes; on a failed tick, so the
            # credits of granted work (and continuation-chunk debits)
            # are never leaked
            for task in granted:
                self.scheduler.finish(task)
            if self._tick_chunk_debt:
                self.scheduler.return_credits(self._tick_chunk_debt)
                self._tick_chunk_debt = 0
        # idle ticks (background poll with nothing in flight) emit no
        # gauges — a traced long-lived server would otherwise append
        # two counter events per 50ms poll to the Tracer's in-memory
        # list forever
        if granted or emitted or self.pool.active_count \
                or self.scheduler.depth:
            self.metrics.observe_tick(self.pool.occupancy(),
                                      self.scheduler.depth, emitted)
            # live credit level (post-return = the budget the next
            # tick's admission scan starts from)
            self.metrics.gauge(sm.PREFILL_CREDITS, self.scheduler.credits)
            if self.paged:
                bs = self.pool.block_stats()
                self.metrics.gauge(sm.KV_BLOCKS_FREE, bs["free"])
                self.metrics.gauge(sm.KV_BLOCKS_USED, bs["used"])
                self.metrics.gauge(sm.KV_BLOCKS_SHARED, bs["shared"])
        # "admitted" counts requests actually assigned a slot this tick
        # — NOT cancelled grants or held (resubmitted) tasks
        return {"admitted": admitted, "emitted": emitted,
                "active": self.pool.active_count,
                "queued": self.scheduler.depth,
                "prefill_tokens": self._tick_prefill}

    def _admit(self, req: Request) -> int:
        # the sequence this admission must prefill: the prompt, or —
        # when resuming a preempted request — prompt + emitted tokens
        # minus the last (its K/V is unwritten; it is the next decode
        # input, parked in _resume_tok)
        k = len(req.tokens)
        seq = (req.prompt if k == 0 else
               np.concatenate([req.prompt,
                               np.asarray(req.tokens[:-1], np.int32)]))
        req._seq = seq
        T = int(seq.shape[0])
        slot = self.pool.assign(req.id, T)
        assert slot is not None, "admit() granted beyond free slots"
        req.slot = slot
        if not req.t_admit:  # keep the first admission's queue-wait
            req.t_admit = time.monotonic()
            self.metrics.bump(sm.ADMITTED)
        self._slot_req[slot] = req
        if req._kv_blocks is not None:
            # disagg adoption: a shipped prefill's staged blocks replace
            # the prefill pass.  The table adopts them (ownership
            # transfer — the stager's refs become the table's), the
            # cursor is already at T from assign(), and the parked
            # resume pair seeds the next decode input exactly like the
            # chunked-resume path below — bit-exact by the position-wise
            # determinism argument (docs/serving.md "Disaggregated
            # tiers").  Any geometry surprise refuses adoption and falls
            # through to normal (re-)prefill — never a wrong answer.
            ids, req._kv_blocks = req._kv_blocks, None
            if (self.paged and req._resume_tok is not None
                    and len(ids) == -(-T // self.pool.block)
                    and len(ids) <= self.pool.tables[slot].max_blocks
                    and not self.pool.tables[slot].blocks):
                self.pool.adopt_blocks(slot, ids)
                req.state = RequestState.ACTIVE
                self._tok = self._tok.at[slot].set(req._resume_tok)
                if not self.greedy and req._resume_key is not None:
                    self._keys = self._keys.at[slot].set(
                        jnp.asarray(req._resume_key))
                req._resume_tok = None
                req._resume_key = None
                return 0
            bps_log.warning(
                "disagg: refusing adoption of %d staged block(s) for "
                "request %d (want %d for T=%d) — re-prefilling",
                len(ids), req.id, -(-T // self.pool.block)
                if self.paged else -1, T)
            for b in ids:
                self.pool.alloc.decref(int(b))
        p0 = 0
        if self.prefix is not None:
            req._prefix_digs = self.prefix.digests_for(
                seq, salt=self._prefix_salt)
            m = self.prefix.match(seq, salt=self._prefix_salt,
                                  digests=req._prefix_digs)
            if m is not None:
                entry, p0 = m
                # pin across the attach/copy, then resume prefill at
                # the boundary — the shared (or copied) bytes ARE the
                # K/V whole prefill would recompute, so parity is by
                # construction
                self.prefix.acquire(entry)
                try:
                    if self.paged:
                        # zero-copy prefix hit: the slot's table adopts
                        # the entry's blocks (refcount bumps, no device
                        # work — the acceptance criterion the compile
                        # counters pin)
                        self.pool.share_prefix(
                            slot, entry.buffer[:p0 // self.pool.block])
                    else:
                        self.pool.caches = self._prefix_copy_fn()(
                            self.pool.caches, entry.buffer, slot)
                finally:
                    self.prefix.release(entry)
                self.metrics.bump(sm.PREFIX_HITS)
                self.metrics.bump(sm.PREFIX_HIT_TOKENS, p0)
            else:
                self.metrics.bump(sm.PREFIX_MISSES)
        if p0 == 0 and not self.chunk and not self.paged:
            # whole-prompt prefill (the pre-chunking path, bit-identical)
            req.state = RequestState.ACTIVE
            bucket = _next_bucket(T, self.min_prefill_bucket, self.max_seq)
            padded = np.full((1, bucket), self.pad_id, np.int32)
            padded[0, :T] = seq
            key = (jnp.zeros((2,), jnp.uint32) if self.greedy
                   else jax.random.PRNGKey(req.seed))
            fn = self._prefill_fn(bucket)
            caches, tok0, nk = fn(self.variables, self.pool.caches,
                                  jnp.asarray(padded), slot, T, key)
            self.pool.caches = caches
            self.metrics.bump(sm.PREFILL_TOKENS, bucket)
            self._tick_prefill += bucket
            if req._resume_tok is not None:
                # resuming a request another engine emitted tokens for
                # (router failover): the prefill's sampled token and key
                # split are discarded — the parked next-input token and
                # the recomputed carried key continue the original
                # chain, same discipline as the chunked resume path
                self._tok = self._tok.at[slot].set(req._resume_tok)
                if not self.greedy and req._resume_key is not None:
                    self._keys = self._keys.at[slot].set(
                        jnp.asarray(req._resume_key))
                req._resume_tok = None
                req._resume_key = None
                self._maybe_insert_prefix(req)
                return 0
            self._tok = self._tok.at[slot].set(tok0)
            if not self.greedy:
                self._keys = self._keys.at[slot].set(nk)
            self._maybe_insert_prefix(req)
            self._emit(req, int(tok0))
            return 1
        # chunked (or prefix-resumed, or paged) prefill: the request
        # parks in PREFILLING with the slot held; the admission grant
        # pre-paid its first chunk, later chunks debit the shared
        # credit pool
        req.state = RequestState.PREFILLING
        req.prefill_pos = p0
        req._pf_paid = True
        self._prefilling[slot] = req
        return self._advance_prefill(req)

    def _advance_prefill(self, req: Request) -> int:
        """Run as many prefill chunks for ``req`` as the tick's credits
        allow.  Returns 1 when the final chunk completed (first token
        emitted), else 0 — the request stays PREFILLING and resumes on
        the next tick's continuation pass with a fresh budget (0 is
        also the answer when block pressure preempted or failed the
        request mid-pass; the slot is gone then)."""
        seq = req._seq if req._seq is not None else req.prompt
        T = int(seq.shape[0])
        slot = req.slot
        S = self.max_seq
        while True:
            p0 = req.prefill_pos
            csize = (min(T - p0, self.chunk) if self.chunk else T - p0)
            bucket = _next_bucket(csize, self.min_prefill_bucket,
                                  self.chunk or self.max_seq)
            if p0 and p0 + bucket > S and p0 + self.min_prefill_bucket <= S:
                # a covering bucket would overrun the row, and the
                # boundary guard below would then shift the chunk left
                # across positions the prefix copy (or earlier chunks)
                # already wrote — recomputing exactly what the reuse
                # saved.  Split instead: take the largest bucket that
                # fits at p0 and leave the tail to the next loop pass
                fit = self.min_prefill_bucket
                while fit * 2 <= S - p0:
                    fit *= 2
                bucket = fit
                csize = min(csize, bucket)
            # clamp the debit to the whole budget, exactly like
            # ServeScheduler.submit clamps an admission grant — a
            # bucket larger than the budget could otherwise NEVER be
            # paid for and the request would sit in PREFILLING forever
            need = (min(bucket, self.scheduler.credit_budget)
                    if self.scheduler.credit_budget > 0 else bucket)
            if req._pf_paid:
                req._pf_paid = False
            elif self.scheduler.take_credits(need):
                self._tick_chunk_debt += need
            else:
                return 0  # budget spent; next tick continues
            # boundary guard: a padded final bucket must not write past
            # the cache row.  Shift the chunk start left instead and
            # RE-FEED the overlapped prompt tokens — recomputing K/V
            # already in the row rewrites identical bytes (position-wise
            # determinism, docs/serving.md), so the overlap is bit-exact
            start = min(p0, S - bucket)
            if self.paged:
                # lazy block grant for the chunk's REAL tokens only
                # (min(..., T)): the padded bucket tail's writes route
                # to the null block through the table's null-filled
                # entries, so granting blocks for pure padding would
                # hold ghost memory for the slot's whole lifetime.
                # Then copy-on-write forks for any shared block the
                # span touches (only the shift-left re-feed can reach
                # one; the fork copy makes the identical-bytes rewrite
                # land in a private clone, keeping shared blocks
                # immutable)
                if not self._with_block_pressure(
                        req, lambda: self.pool.ensure_blocks(
                            slot, min(start + bucket, T))):
                    return 0
                if not self._with_block_pressure(
                        req, lambda: self.pool.make_writable(
                            slot, start, start + bucket,
                            self._cow_copy)):
                    return 0
            toks = np.full((1, bucket), self.pad_id, np.int32)
            end = min(start + bucket, T)
            toks[0, :end - start] = seq[start:end]
            final = p0 + csize >= T
            last_idx = (T - 1 - start) if final else (bucket - 1)
            key = (jnp.zeros((2,), jnp.uint32) if self.greedy
                   else jax.random.PRNGKey(req.seed))
            if self.paged:
                fn = self._paged_chunk_fn(bucket)
                caches, tok0, nk = fn(self.variables, self.pool.caches,
                                      jnp.asarray(toks),
                                      self.pool.table_row(slot), start,
                                      last_idx, key)
            else:
                fn = self._chunk_fn(bucket)
                caches, tok0, nk = fn(self.variables, self.pool.caches,
                                      jnp.asarray(toks), slot, start,
                                      last_idx, key)
            self.pool.caches = caches
            req.prefill_pos = p0 + csize
            self.metrics.bump(sm.PREFILL_TOKENS, bucket)
            self.metrics.bump(sm.PREFILL_CHUNKS)
            self._tick_prefill += bucket
            if final:
                del self._prefilling[slot]
                req.state = RequestState.ACTIVE
                if req._resume_tok is not None:
                    # resuming a preempted request: the K/V for every
                    # already-emitted token is rebuilt; the final
                    # chunk's sampled token AND its key split are
                    # discarded, and the parked next-input token plus
                    # the carried key are restored — the per-request
                    # key chain continues exactly once-per-step, so
                    # seeded streams stay bit-exact across preemption
                    self._tok = self._tok.at[slot].set(req._resume_tok)
                    if not self.greedy and req._resume_key is not None:
                        self._keys = self._keys.at[slot].set(
                            jnp.asarray(req._resume_key))
                    req._resume_tok = None
                    req._resume_key = None
                    self._maybe_insert_prefix(req)
                    return 0  # nothing emitted; decode resumes next
                self._tok = self._tok.at[slot].set(tok0)
                if not self.greedy:
                    self._keys = self._keys.at[slot].set(nk)
                self._maybe_insert_prefix(req)
                self._emit(req, int(tok0))
                return 1

    def _with_block_pressure(self, req: Request, fn) -> bool:
        """Run ``fn()`` (a block allocation on behalf of ``req``); on
        :class:`BlocksExhaustedError`, reclaim memory and retry:

          1. evict unpinned prefix-cache entries (cheapest — cached
             prefixes can always be recomputed);
          2. preempt the NEWEST other in-flight request back to QUEUED
             (vLLM's recompute preemption: oldest work finishes first,
             so the system always makes forward progress);
          3. if ``req`` is itself the newest, it yields — preempted
             back to QUEUED to resume when older requests finish;
          4. a request that cannot fit the pool even alone fails
             loudly with the typed error attached.

        True = ``fn`` succeeded.  False = ``req`` lost its slot
        (preempted or failed); the caller abandons it this tick."""
        while True:
            try:
                fn()
                return True
            except BlocksExhaustedError as e:
                if self.prefix is not None and self.prefix.evict_for(
                        max(1, e.needed - e.free)):
                    continue
                others = [self._slot_req[s]
                          for s in self.pool.active_slots()
                          if self._slot_req[s] is not None
                          and self._slot_req[s] is not req]
                newer = [r for r in others if r.id > req.id]
                if newer:
                    self._preempt(max(newer, key=lambda r: r.id))
                    continue
                if others:
                    # req is the newest holder: it yields rather than
                    # deadlocking requests admitted before it
                    self._preempt(req)
                    return False
                # alone and still short: the pool can never fit this
                # request — fail it with the typed error
                req.error = e
                self._finish(req, RequestState.FAILED)
                return False

    def _preempt(self, victim: Request) -> None:
        """Preempt an in-flight request back to QUEUED (paged engine,
        KV block pressure): its slot and non-shared blocks return to
        the pool NOW; on re-admission it re-prefills prompt + emitted
        tokens (position-wise determinism makes the rebuilt K/V
        bit-identical to what incremental decode wrote) and resumes
        decoding from its parked next-input token and sampling key.
        Already-streamed tokens are kept — consumers see a stall, never
        a replay.  Re-queued via the ORIGINAL scheduler task, so it
        re-enters ahead of later submissions."""
        slot = victim.slot
        if victim.state is RequestState.ACTIVE and victim.tokens:
            victim._resume_tok = int(np.asarray(self._tok[slot]))
            if not self.greedy:
                victim._resume_key = np.asarray(self._keys[slot])
        # a PREFILLING victim keeps whatever resume state it carries: a
        # request preempted a SECOND time mid-resume still owes exactly
        # the parked token and key it owed before — clobbering them
        # would re-emit the parked token as a fresh "first" token
        self._prefilling.pop(slot, None)
        self._slot_req[slot] = None
        self.pool.free(slot)  # releases the table's block references
        victim.slot = None
        victim.prefill_pos = 0
        victim._pf_paid = False
        victim._seq = None
        # re-admission watermark: worst-case blocks to complete (prefix
        # sharing can only shrink the real need, so this is safe-side)
        victim._hold_blocks = -(-(int(victim.prompt.shape[0])
                                  + victim.max_new_tokens)
                                // self.pool.block)
        victim.state = RequestState.QUEUED
        self.scheduler.resubmit(victim._task)
        self.metrics.bump(sm.PREEMPTIONS)

    def _maybe_insert_prefix(self, req: Request) -> None:
        """After a completed prefill, capture the sequence's block-
        aligned prefix K/V into the store (skipped when already
        indexed).  Paged engines register the slot's own blocks —
        refcount bumps, zero device-side copies; dense engines pay the
        jitted zero-masked row extract."""
        if self.prefix is None:
            return
        seq = req._seq if req._seq is not None else req.prompt
        ins = self.prefix.insertable_len(seq,
                                         salt=self._prefix_salt,
                                         digests=req._prefix_digs)
        if ins <= 0:
            return
        if self.paged:
            ids = self.pool.tables[req.slot].blocks[
                :ins // self.pool.block]
            if (len(ids) == ins // self.pool.block
                    and self.prefix.insert_blocks(
                        seq[:ins], ids, salt=self._prefix_salt,
                        digests=req._prefix_digs)):
                self.metrics.bump(sm.PREFIX_INSERTIONS)
            return
        if (self.prefix.max_bytes
                and self._prefix_row_bytes > self.prefix.max_bytes):
            return
        buf = self._prefix_extract_fn()(self.pool.caches, req.slot, ins)
        if self.prefix.insert(seq[:ins], buf,
                              salt=self._prefix_salt,
                              digests=req._prefix_digs):
            self.metrics.bump(sm.PREFIX_INSERTIONS)

    def _gather_hw(self, tq: int) -> int:
        """Block high-water bucket for the XLA gather fallback: the
        smallest power-of-two block count (capped at ``max_blocks``)
        covering every assigned slot's ``[0, pos + tq)`` span this tick
        — masked slots sit at pos 0 and still land their ``tq``-wide
        garbage write inside the view.  Bucketing keeps the compile
        count O(log max_blocks) (the prefill-bucket discipline) while
        the gather stops streaming the null-block / unwritten padding
        beyond the highest live cursor."""
        blk = self.pool.block
        need = tq
        for slot in self.pool.active_slots():
            if slot in self._prefilling:
                # PREFILLING slots are masked out of paged decode AND
                # verify (their pos vector entry is 0, their garbage
                # write aims at the null block), so their — possibly
                # deep — prefill cursor must not drag every
                # interleaved decode tick back to full gather width
                continue
            need = max(need, self.pool.pos[slot] + tq)
        return _next_bucket(-(-need // blk), 1, self.pool.max_blocks)

    def _decode_tick(self, active: List[int]) -> int:
        n = self.pool.n_slots
        if self.paged:
            # lazy block grant at the boundary crossing: a slot whose
            # cursor enters an uncovered block gets one here — under
            # pressure this is where prefix eviction / preemption fires
            for slot in list(active):
                req = self._slot_req[slot]
                if req is None:
                    continue  # a victim of an earlier preemption
                if not self._with_block_pressure(
                        req, lambda s=slot: self.pool.ensure_blocks(
                            s, self.pool.pos[s] + 1)):
                    continue
            active = [s for s in active
                      if self._slot_req[s] is not None
                      and s not in self._prefilling]
            if not active:
                return 0
        if self.spec is not None:
            props = self._collect_proposals(active)
            if props:
                out = self._verify_tick(active, props)
                if out is not None:
                    return out
        self.metrics.bump(sm.DECODE_TICKS)
        pos = np.zeros((n,), np.int32)
        mask = np.zeros((n,), bool)
        for slot in active:
            pos[slot] = self.pool.pos[slot]
            mask[slot] = True
        if self.paged:
            # scatter targets: each active slot writes its cursor's
            # (block, offset); masked slots (free or PREFILLING) write
            # the null block, so their garbage can never land in a
            # shared prefix block or a mid-prefill row
            wblk = np.full((n,), self.pool.null_block, np.int32)
            woff = np.zeros((n,), np.int32)
            for slot in active:
                wblk[slot], woff[slot] = self.pool.write_target(slot)
            if self.paged_kernel:
                # fused kernel: one program, write targets per (slot,
                # query) — tq = 1 here — and NO gather anywhere
                fn = self._paged_decode_fn(None)
                caches, nxt, keys = fn(
                    self.variables, self.pool.caches, self._tok,
                    jnp.asarray(pos), jnp.asarray(mask), self._keys,
                    self.pool.tables_device(),
                    jnp.asarray(wblk[:, None]),
                    jnp.asarray(woff[:, None]))
            else:
                # pos-capped gather: stream each slot's high-water
                # bucket, not the full null-padded table width
                hw = self._gather_hw(1)
                self.metrics.bump(sm.GATHERED_BLOCKS, n * hw)
                fn = self._paged_decode_fn(hw)
                caches, nxt, keys = fn(
                    self.variables, self.pool.caches, self._tok,
                    jnp.asarray(pos), jnp.asarray(mask), self._keys,
                    self.pool.tables_device(), jnp.asarray(wblk),
                    jnp.asarray(woff))
        else:
            # PREFILLING slots ride the decode step masked-off like
            # freed slots do, but their garbage K/V write must NOT land
            # at pos 0 (it would corrupt the copied prefix / already-
            # written chunks): aim it at the slot's post-prefill
            # cursor, which the request's own first real decode
            # overwrites before the causal mask can ever admit it
            for slot in self._prefilling:
                pos[slot] = self.pool.pos[slot]
            caches, nxt, keys = self._decode_step(
                self.variables, self.pool.caches, self._tok,
                jnp.asarray(pos), jnp.asarray(mask), self._keys)
        self.pool.caches = caches
        self._tok = nxt
        self._keys = keys
        nxt_host = np.asarray(nxt)
        emitted = 0
        for slot in active:
            req = self._slot_req[slot]
            self.pool.advance(slot)
            self._emit(req, int(nxt_host[slot]))
            emitted += 1
        return emitted

    def _collect_proposals(self, active: List[int]) -> Dict[int, List[int]]:
        """CPU-side prompt-lookup pass: per active slot, match the
        request's trailing n-gram against its own prompt + emitted
        history and propose up to ``k`` continuations (serving/spec.py).
        Proposals are capped at the slot's remaining row space and the
        request's remaining token budget minus one — tokens past either
        could never be emitted, so verifying them would be pure waste.
        Empty when nothing matched anywhere: the tick then runs the
        plain decode program, paying zero verify overhead."""
        props: Dict[int, List[int]] = {}
        S = self.max_seq
        for slot in active:
            req = self._slot_req[slot]
            if req is None or not req.tokens:
                continue
            cap = min(S - self.pool.pos[slot] - 1,
                      req.max_new_tokens - len(req.tokens) - 1)
            if cap < 1:
                continue
            buf = req._spec_ctx
            P = int(req.prompt.shape[0])
            if buf is None:
                buf = np.empty(P + req.max_new_tokens, np.int32)
                buf[:P] = req.prompt
                req._spec_ctx = buf
                req._spec_n = P
            k = len(req.tokens)
            have = req._spec_n - P
            if k > have:
                buf[P + have:P + k] = req.tokens[have:]
                req._spec_n = P + k
            p = self.spec.propose(buf[:req._spec_n], cap)
            if p:
                # SPEC_PROPOSED is bumped in _verify_tick from the
                # post-truncation lengths actually fed to the verifier
                # (a depth-bucket halving or paged coverage clip — or a
                # row-cap fallback to plain decode — drops tokens that
                # must not inflate the acceptance-rate denominator)
                props[slot] = p
        return props

    def _verify_tick(self, active: List[int],
                     props: Dict[int, List[int]]) -> Optional[int]:
        """One speculative tick: every slot rides a single ``tq = d + 1``
        verify pass (``d`` = this tick's depth bucket), and each active
        slot accepts the longest prefix of its proposals the model
        itself produced — at least one token (position 0 IS the plain
        decode step, so a tick can never emit less than the
        non-speculative engine).  Returns None when the depth bucket
        cannot fit every slot's row (the caller falls back to the plain
        decode program).

        Rollback of rejected positions is free by construction.  Dense:
        the cursor advances only past accepted tokens; the rejected
        span's K/V sits beyond it, never attended before the request's
        own later writes replace it (the freed-rows argument).  Paged:
        the scatter targets each span position's own granted block
        (host-computed), ungranted positions aim at the null block and
        cap acceptance, so shared prefix blocks are untouchable."""
        n = self.pool.n_slots
        S = self.max_seq
        d = _next_bucket(max(len(p) for p in props.values()), 1,
                         self.spec.k)
        # row cap: every slot whose write rides the program — active,
        # and (dense) PREFILLING slots whose masked garbage write is
        # aimed at their cursor — must fit [pos, pos + tq) inside its
        # row, or dynamic_update_slice would clamp the write leftward
        # over real K/V.  Halving stays on the compiled bucket grid.
        cap = S
        for slot in range(n):
            if self._slot_req[slot] is not None and (
                    not self.paged or slot not in self._prefilling):
                cap = min(cap, S - self.pool.pos[slot] - 1)
        while d > cap and d > 1:
            d //= 2
        if d > cap:
            return None
        tq = d + 1
        pmat = np.full((n, d), self.pad_id, np.int32)
        plen = np.zeros((n,), np.int32)
        posv = np.zeros((n,), np.int32)
        mask = np.zeros((n,), bool)
        budget = np.ones((n,), np.int32)
        for slot in active:
            req = self._slot_req[slot]
            posv[slot] = self.pool.pos[slot]
            mask[slot] = True
            budget[slot] = req.max_new_tokens - len(req.tokens)
            p = props.get(slot)
            if p:
                m = min(len(p), d)
                pmat[slot, :m] = p[:m]
                plen[slot] = m
        if self.paged:
            blk = self.pool.block
            null = self.pool.null_block
            wblk = np.full((n, tq), null, np.int32)
            woff = np.zeros((n, tq), np.int32)
            for slot in active:
                # span grant, best-effort: speculation must never evict
                # prefix entries or preempt live requests just to hold
                # guess-width — on exhaustion acceptance simply caps at
                # the granted coverage (>= pos + 1, ensured above)
                want = int(posv[slot]) + 1 + int(plen[slot])
                try:
                    self.pool.ensure_blocks(slot, min(want, S))
                except BlocksExhaustedError:
                    pass
                table = self.pool.tables[slot].blocks
                cov = len(table) * blk - int(posv[slot])
                # a proposal whose acceptance would advance the cursor
                # onto an ungranted (null-aimed, unwritten) position is
                # clipped BEFORE the verify, so the in-program accept
                # can never outrun the granted coverage
                plen[slot] = min(int(plen[slot]), cov - 1)
                for j in range(min(tq, cov)):
                    p_ = int(posv[slot]) + j
                    wblk[slot, j] = table[p_ // blk]
                    woff[slot, j] = p_ % blk
            if self.paged_kernel:
                fn = self._paged_verify_fn(tq, None)
            else:
                hw = self._gather_hw(tq)
                self.metrics.bump(sm.GATHERED_BLOCKS, n * hw)
                fn = self._paged_verify_fn(tq, hw)
            out = fn(self.variables, self.pool.caches,
                     jnp.asarray(pmat), jnp.asarray(plen),
                     jnp.asarray(posv), jnp.asarray(mask), self._tok,
                     self._keys, jnp.asarray(budget),
                     self.pool.tables_device(), jnp.asarray(wblk),
                     jnp.asarray(woff))
        else:
            # PREFILLING slots' masked garbage span aims at their
            # cursor, same discipline as the one-token step (the span
            # fits by the row cap above)
            for slot in self._prefilling:
                posv[slot] = self.pool.pos[slot]
            fn = self._verify_fn(tq)
            out = fn(self.variables, self.pool.caches,
                     jnp.asarray(pmat), jnp.asarray(plen),
                     jnp.asarray(posv), jnp.asarray(mask), self._tok,
                     self._keys, jnp.asarray(budget))
        caches, self._tok, self._keys, tmat, m_emit, lead = out
        self.pool.caches = caches
        # ONE host transfer for everything the emit loop needs — three
        # separate np.asarray calls would block three times
        tmat_h, me_h, lead_h = jax.device_get((tmat, m_emit, lead))
        emitted = 0
        accepted = 0
        for slot in active:
            req = self._slot_req[slot]
            if req is None:
                continue
            n_emit = int(me_h[slot])
            accepted += int(lead_h[slot])
            # cursor advances over EXACTLY the emitted tokens' inputs:
            # accepted-but-truncated tokens (budget/EOS) advance
            # nothing and are counted nowhere — the next-input token
            # and key chain were already picked to match on device
            self.pool.advance(slot, n_emit)
            for tk in tmat_h[slot, :n_emit]:
                self._emit(req, int(tk))
                emitted += 1
        self.metrics.bump(sm.DECODE_TICKS)
        self.metrics.bump(sm.SPEC_VERIFY_TICKS)
        self.metrics.bump(sm.SPEC_PROPOSED, int(plen.sum()))
        if accepted:
            self.metrics.bump(sm.SPEC_ACCEPTED, accepted)
        return emitted

    def _emit(self, req: Request, tok: int) -> None:
        now = time.monotonic()
        if not req.t_first:  # first token THIS engine emitted (a
            req.t_first = now  # resumed request pre-seeds req.tokens)
        req.t_last = now
        req.tokens.append(tok)
        req._out.put(tok)
        done = (len(req.tokens) >= req.max_new_tokens
                or (self.eos_id is not None and tok == self.eos_id))
        if done:
            self._finish(req, RequestState.DONE)

    def _finish(self, req: Request, state: RequestState) -> None:
        req.state = state
        if req.trace_id:
            # the request's whole-lifetime span, stamped with its trace
            # id — the serving-side anchor trace_merge's by-trace view
            # groups client/server spans under
            from ..common.tracing import get_tracer

            tracer = get_tracer()
            if tracer.enabled:
                tracer.complete(
                    f"serve:req{req.id}", "serve", req._t_pc,
                    time.perf_counter() - req._t_pc,
                    trace_id=req.trace_id, state=state.value,
                    tokens=len(req.tokens))
        if req.slot is not None:
            if (req._keep_kv and state is RequestState.DONE
                    and self.paged):
                # disagg prefill replica: park the finished request's
                # blocks (extra refs, taken BEFORE the free below drops
                # the table's own) so the frontend can ship them
                self._park_kv_locked(req)
            self._prefilling.pop(req.slot, None)
            self._slot_req[req.slot] = None
            self.pool.free(req.slot)
            req.slot = None
        if req._kv_blocks is not None:
            # staged blocks that were never adopted (cancel/failure
            # before admission): release, never leak
            for b in req._kv_blocks:
                self.pool.alloc.decref(int(b))
            req._kv_blocks = None
        req._out.put(_END)
        req._done.set()
        if state is RequestState.DONE:
            # count only THIS engine's emissions: a resumed request's
            # pre-seeded tokens belong to the engine that died, and
            # t_first/t_last span only the local ones — folding the
            # resumed count in would under-read TPOT exactly during
            # failover windows and double-count the tier's tokens
            n = len(req.tokens) - req._resumed_n
            tpot = ((req.t_last - req.t_first) / (n - 1) if n > 1 else None)
            self.metrics.observe_request(
                queue_wait_s=req.t_admit - req.t_submit,
                ttft_s=req.t_first - req.t_submit, tpot_s=tpot, tokens=n)
        elif state is RequestState.FAILED:
            self.metrics.bump(sm.FAILED)
        else:
            self.metrics.bump(sm.CANCELLED)
        with self._drain_cv:
            self._outstanding -= 1
            self._drain_cv.notify_all()

    # ---------------------------------------------------------- lifecycle

    def _idle(self) -> bool:
        return self.pool.active_count == 0 and self.scheduler.depth == 0

    def start(self) -> "ServingEngine":
        """Run the tick loop on a background thread (frontend mode)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_flag = False
            self._thread = threading.Thread(
                target=self._run, name="byteps-serve-engine", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_flag:
            try:
                self.step()
            except Exception as e:
                # a dead tick thread must not look like a hung one:
                # fail every in-flight and queued request loudly and
                # refuse new submissions — blocked result()/drain()
                # callers get the error instead of waiting forever
                bps_log.warning("serving engine tick failed: %r", e)
                self._fail_all(e)
                return
            with self._wake:
                if self._idle() and not self._stop_flag:
                    self._wake.wait(timeout=0.05)

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            self._engine_error = exc
            for slot in self.pool.active_slots():
                req = self._slot_req[slot]
                if req is not None:
                    req.error = exc
                    self._finish(req, RequestState.FAILED)
            # credit-FREE drain: admit() would skip queued tasks larger
            # than whatever credits the failed tick left, hanging their
            # result() callers forever
            for task in self.scheduler.drain_pending():
                task.request.error = exc
                self._finish(task.request, RequestState.FAILED)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_flag = True
        with self._wake:
            self._wake.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                # a wedged tick (e.g. a long compile) must not be
                # abandoned: clearing _thread would let a later start()
                # reset _stop_flag and spawn a SECOND tick loop beside
                # this one — leave it tracked, not restartable
                bps_log.warning(
                    "serving engine tick thread still running after "
                    "%.1fs; engine not restartable until it exits",
                    timeout)
            else:
                self._thread = None

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted request has finished.  Without a
        background thread, drives :meth:`step` inline (deterministic
        single-threaded mode)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._thread is None:
            while True:
                with self._lock:
                    if self._outstanding == 0:
                        return
                self.step()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("drain timed out")
        else:
            with self._drain_cv:
                while self._outstanding > 0:
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        raise TimeoutError("drain timed out")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    self._drain_cv.wait(remaining)

    # --------------------------------------------------------- inspection

    @property
    def weights_fp(self) -> str:
        """Hex fingerprint of this engine's weights (serving/prefix.py
        ``weights_fingerprint`` — the same digest the prefix-store salt
        commits to).  Carried on the STATS reply as the engine's
        identity, so a ``ServeRouter`` can refuse a replica serving
        different weights instead of splicing silently-wrong resumes
        (docs/serving.md "Router tier").  Computed lazily and cached:
        prefix-cache engines already paid for it at construction."""
        if self._weights_fp is None:
            self._weights_fp = weights_fingerprint(self.variables).hex()
        return self._weights_fp

    def compile_counts(self) -> Dict[str, int]:
        """Trace counts of the step programs — steady-state serving must
        keep ``decode`` at ``decode_buckets`` (1 for dense engines and
        the fused-kernel paged path; the number of gather high-water
        buckets touched on the paged XLA fallback),
        ``prefill``/``chunk``/``verify`` at the number of distinct
        buckets touched, and the prefix copy/extract programs at 1 each
        (asserted by tests and bench_serve.py)."""
        return {"decode": self.decode_traces,
                "decode_buckets": (len(self._paged_decode_fns)
                                   if self.paged else 1),
                "prefill": self.prefill_traces,
                "prefill_buckets": len(self._prefill_fns),
                "chunk": self.chunk_traces,
                "chunk_buckets": len(self._chunk_fns),
                "verify": self.verify_traces,
                "verify_buckets": len(self._verify_fns),
                "prefix_copy": self.prefix_copy_traces,
                "prefix_extract": self.prefix_extract_traces,
                "block_cow": self.block_cow_traces}
