"""Continuous-batching engine: jitted slot-pool step functions + tick loop.

Two compiled programs serve steady state, regardless of how many
requests flow through:

  * **decode step** — one token for EVERY slot per tick: the model's
    per-row ``Transformer.decode`` is ``vmap``-ed over the slot axis
    with per-slot position scalars (slots sit at different depths), so
    the whole pool advances in one program with static ``[N_slots]``
    token/pos vectors and an active-slot mask.  Inactive slots compute
    garbage into their (freed) rows — the price of static shapes — and
    their sampled tokens are masked to ``pad_id``.
  * **prefill** — one request's padded prompt into its slot row:
    ``dynamic_slice`` the row out, run the model's cached prefill
    (static ``pos=0`` — the same dense-prefill path ``generate()``
    takes), gather the true last position's logits, ``dynamic_update_
    slice`` the row back.  Prompts are right-padded to power-of-two
    buckets so the compile count is O(log max_seq), not O(#lengths).

**Determinism / parity contract** (the correctness anchor, pinned by
tests/test_serving.py and scripts/serve_smoke.py): per request, the
engine reproduces sequential ``generate()`` token for token — greedy
trivially, and under sampling by replaying ``generate()``'s exact key
chain (``PRNGKey(seed)``; split once at prefill, once per decode step).
The numerics match because (a) every per-slot computation is
row-independent under ``vmap``, and (b) a longer cache than
``generate()``'s only adds *masked* attention slots, whose
``exp(-1e30 - max)`` scores underflow to exactly 0.0 and contribute
nothing to any softmax sum or PV dot.  Batch composition therefore
cannot leak between requests.

Tick order is fixed: cancellations, then credit-bounded admissions (in
scheduler grant order), then one decode pass over the pool (slot
order), then credits return.  Given an admission order, the engine's
entire output is deterministic.
"""

from __future__ import annotations

import dataclasses
import enum
import queue
import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..common import logging as bps_log
from ..inference import sample_logits
from ..models.transformer import Transformer
from . import metrics as sm
from .metrics import ServeMetrics, get_serve_metrics
from .scheduler import ServeScheduler
from .slots import SlotPool

__all__ = ["Request", "RequestState", "ServingEngine"]


class RequestState(enum.Enum):
    QUEUED = "queued"
    ACTIVE = "active"
    DONE = "done"
    CANCELLED = "cancelled"
    FAILED = "failed"  # engine tick raised; see Request.error


_END = object()  # stream sentinel


@dataclasses.dataclass
class Request:
    """One in-flight generation request.  Stream tokens with ``for tok
    in req:`` (blocks until the engine emits them) or block for the
    whole sequence with ``result()``."""

    id: int
    prompt: np.ndarray          # [T] int32
    max_new_tokens: int
    seed: int = 0
    priority: int = 0
    state: RequestState = RequestState.QUEUED
    cancelled: bool = False
    tokens: List[int] = dataclasses.field(default_factory=list)
    slot: Optional[int] = None
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0
    error: Optional[BaseException] = None
    _out: "queue.Queue" = dataclasses.field(default_factory=queue.Queue)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def __iter__(self):
        while True:
            item = self._out.get()
            if item is _END:
                # an engine failure must not masquerade as a clean,
                # short completion to streaming consumers
                if self.error is not None:
                    raise RuntimeError(
                        f"serving engine failed while request {self.id} "
                        f"was in flight: {self.error!r}") from self.error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns the emitted tokens
        (CANCELLED requests return whatever was emitted before).
        Raises if the engine failed while this request was in flight."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id} not done within {timeout}s")
        if self.error is not None:
            raise RuntimeError(
                f"serving engine failed while request {self.id} was in "
                f"flight: {self.error!r}") from self.error
        return np.asarray(self.tokens, np.int32)

    @property
    def done(self) -> bool:
        return self._done.is_set()


def _prefill_forward(mdl: Transformer, tokens, caches, true_len):
    """Padded-prompt prefill returning the logits at ``true_len - 1``.

    Structurally identical to ``Transformer.decode(..., last_only=True)``
    — embed, blocks at static ``pos=0``, slice ONE position, ``ln_f``,
    head — except the slice lands on the true last prompt token instead
    of the literal last row, so right-padding never reaches the LM head.
    Pad K/V beyond ``true_len`` does enter the cache, but decode's
    causal mask admits position ``p`` only once the request's own write
    cursor passes it — by which point the pad row has been overwritten
    by a real token's K/V (see docs/serving.md).
    """
    cfg = mdl.cfg
    x = mdl.embed(tokens)
    if cfg.pos_emb == "learned":
        x = x + mdl.pos(jnp.arange(tokens.shape[1])[None, :])
    new_caches = []
    for block, c in zip(mdl.blocks, caches):
        x, nc = block(x, cache=c, pos=0)
        new_caches.append(nc)
    x = jax.lax.dynamic_slice_in_dim(x, true_len - 1, 1, axis=1)
    return mdl.logits(mdl.ln_f(x)), tuple(new_caches)


def _next_bucket(n: int, lo: int, hi: int) -> int:
    """Smallest power-of-two >= n, floored at lo, clamped to hi."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi)


class ServingEngine:
    """Continuous-batching serving over a ``SlotPool``.

    Sampling parameters (``temperature``/``top_k``/``top_p``) are fixed
    per engine — they are *static* arguments of the compiled step
    functions, which is what makes steady-state serving retrace-free.
    Per-request variation rides the ``seed`` (and greedy engines ignore
    it).  ``eos_id`` stops a request early; every request also carries
    its own ``max_new_tokens`` budget.

    Drive it either by calling :meth:`step` yourself (tests, fully
    deterministic single-threaded use) or via :meth:`start`'s background
    tick thread (the frontend's mode).
    """

    def __init__(self, model: Transformer, variables, *,
                 n_slots: int = 8, max_seq: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 kv_quant: bool = False, cache_layout: str = "grouped",
                 max_queue: int = 64,
                 prefill_credits: Optional[int] = None,
                 min_prefill_bucket: int = 8,
                 metrics: Optional[ServeMetrics] = None):
        self.model = model
        self.variables = variables
        cfg = model.cfg
        self.max_seq = max_seq if max_seq is not None else cfg.max_seq_len
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.pad_id = pad_id
        self.greedy = temperature == 0
        self.min_prefill_bucket = max(1, min_prefill_bucket)
        self.pool = SlotPool(cfg, n_slots, self.max_seq,
                             kv_quant=kv_quant, layout=cache_layout)
        # credit budget in padded prefill tokens per tick; default = one
        # max-length prefill, i.e. "a tick admits at most one worst-case
        # prompt's worth of prefill work" — decode latency stays bounded
        # while short prompts can still batch several admissions per tick
        budget = (prefill_credits if prefill_credits and prefill_credits > 0
                  else self.max_seq)
        self.scheduler = ServeScheduler(
            max_queue=max_queue, credit_budget=budget)
        self.metrics = metrics if metrics is not None else get_serve_metrics()

        self._lock = threading.RLock()
        self._req_seq = 0
        self._slot_req: List[Optional[Request]] = [None] * n_slots
        self._tok = jnp.zeros((n_slots,), jnp.int32)
        self._keys = jnp.zeros((n_slots, 2), jnp.uint32)
        self._outstanding = 0
        self._drain_cv = threading.Condition(self._lock)
        self._wake = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stop_flag = False
        self._engine_error: Optional[BaseException] = None
        # trace-time counters: the Python body of a jitted fn runs only
        # when jax (re)traces, so these count compilations portably —
        # steady-state stability is asserted on them
        self.decode_traces = 0
        self.prefill_traces = 0
        # donate the cache pool into each step: the pool is replaced by
        # the step's output, and without donation XLA would copy every
        # layer's full [N, S, ...] cache per tick just to write one row
        self._decode_step = jax.jit(self._make_decode_fn(),
                                    donate_argnums=(1,))
        self._prefill_fns: Dict[int, object] = {}

    # ---------------------------------------------------- jitted programs

    def _make_decode_fn(self):
        model, greedy = self.model, self.greedy
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        pad_id = self.pad_id

        def one(variables, row, tok, pos, key):
            rowb = jax.tree_util.tree_map(lambda c: c[None], row)
            logits, new = model.apply(
                variables, tok[None, None], rowb, pos,
                method=Transformer.decode)
            if greedy:
                nxt = jnp.argmax(logits[0, -1], axis=-1).astype(jnp.int32)
                nk = key
            else:
                # generate()'s exact per-step key chain: carry split[0],
                # sample with split[1]
                nk, sub = jax.random.split(key)
                nxt = sample_logits(logits[:, -1], sub, temperature,
                                    top_k, top_p)[0].astype(jnp.int32)
            return jax.tree_util.tree_map(lambda c: c[0], new), nxt, nk

        def decode_fn(variables, caches, tok, pos, active, keys):
            self.decode_traces += 1  # trace-time only
            caches, nxt, keys2 = jax.vmap(
                one, in_axes=(None, 0, 0, 0, 0))(
                    variables, caches, tok, pos, keys)
            nxt = jnp.where(active, nxt, pad_id)
            if not greedy:
                keys2 = jnp.where(active[:, None], keys2, keys)
            else:
                keys2 = keys
            return caches, nxt, keys2

        return decode_fn

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        model, greedy = self.model, self.greedy
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        def prefill_fn(variables, caches, prompt, slot, true_len, key):
            self.prefill_traces += 1  # trace-time only
            row = jax.tree_util.tree_map(
                lambda c: jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=0),
                caches)
            logits, new_row = model.apply(
                variables, prompt, row, true_len, method=_prefill_forward)
            if greedy:
                tok0 = jnp.argmax(logits[0, -1], axis=-1).astype(jnp.int32)
                nk = key
            else:
                nk, sub = jax.random.split(key)
                tok0 = sample_logits(logits[:, -1], sub, temperature,
                                     top_k, top_p)[0].astype(jnp.int32)
            caches = jax.tree_util.tree_map(
                lambda c, r: jax.lax.dynamic_update_slice_in_dim(
                    c, r, slot, axis=0),
                caches, new_row)
            return caches, tok0, nk

        fn = jax.jit(prefill_fn, donate_argnums=(1,))
        self._prefill_fns[bucket] = fn
        return fn

    # ------------------------------------------------------------- submit

    def submit(self, prompt, max_new_tokens: int, *, seed: int = 0,
               priority: int = 0) -> Request:
        """Enqueue a generation request.  Raises ``ValueError`` on an
        infeasible request and ``QueueFullError`` (typed backpressure)
        when the bounded admission queue is at capacity."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        T = int(prompt.shape[0])
        if T < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        if T + max_new_tokens > self.max_seq:
            raise ValueError(
                f"prompt ({T}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine max_seq {self.max_seq}")
        bucket = _next_bucket(T, self.min_prefill_bucket, self.max_seq)
        # dead-engine check AND enqueue under the engine lock, which
        # _fail_all holds while draining: a submit racing the failure
        # path must either land before the drain (and be failed by it)
        # or see the error — never enqueue into a dead engine's queue.
        # The outstanding counter also increments here, BEFORE the tick
        # thread can see the request: a fast request could otherwise
        # finish (decrementing) first, and a concurrent drain() would
        # see a transiently-zero counter with work still in flight.
        with self._lock:
            if self._engine_error is not None:
                raise RuntimeError(
                    f"serving engine is dead (tick failed with "
                    f"{self._engine_error!r}); restart it") \
                    from self._engine_error
            self._req_seq += 1
            req = Request(id=self._req_seq, prompt=prompt,
                          max_new_tokens=max_new_tokens, seed=seed,
                          priority=priority, t_submit=time.monotonic())
            self._outstanding += 1
            try:
                self.scheduler.submit(req, bucket)
            except Exception:
                self._outstanding -= 1
                self._drain_cv.notify_all()  # same lock; wake waiters
                self.metrics.bump(sm.REJECTED)
                raise
        self.metrics.bump(sm.SUBMITTED)
        with self._wake:
            self._wake.notify_all()
        return req

    def cancel(self, req: Request) -> None:
        """Request cancellation; the engine retires the request on its
        next tick (queued requests are dropped at grant time)."""
        req.cancelled = True
        with self._wake:
            self._wake.notify_all()

    # --------------------------------------------------------------- tick

    def step(self) -> Dict[str, int]:
        """One engine tick: cancellations -> credit-bounded admissions ->
        one batched decode pass -> credits return.  Returns tick stats."""
        with self._lock:
            return self._step_locked()

    def _step_locked(self) -> Dict[str, int]:
        emitted = 0
        granted: List = []
        try:
            # 0. retire cancelled active requests (frees their slots
            # for this tick's admissions)
            for slot in self.pool.active_slots():
                req = self._slot_req[slot]
                if req is not None and req.cancelled:
                    self._finish(req, RequestState.CANCELLED)
            # 1. admissions, in scheduler grant order (priority desc,
            # FIFO)
            free = self.pool.free_count
            if free:
                granted = self.scheduler.admit(free)
                for task in granted:
                    if task.request.cancelled:
                        self._finish(task.request, RequestState.CANCELLED)
                    else:
                        emitted += self._admit(task.request)
            # 2. one decode pass over the pool
            active = self.pool.active_slots()
            if active:
                emitted += self._decode_tick(active)
        except Exception as e:
            # granted tasks are already popped from the queue: a
            # request whose _admit never ran (or raised before its slot
            # assignment) is invisible to both the queue drain and the
            # active-slot scan in _fail_all — fail it here or its
            # result()/drain() callers hang forever
            for task in granted:
                req = task.request
                if req.state is RequestState.QUEUED:
                    req.error = e
                    self._finish(req, RequestState.FAILED)
            raise
        finally:
            # 3. credits back — in normal ticks AFTER decode, so the
            # budget truly bounds the prefill work interleaved between
            # consecutive decode passes; on a failed tick, so the
            # credits of granted work are never leaked
            for task in granted:
                self.scheduler.finish(task)
        # idle ticks (background poll with nothing in flight) emit no
        # gauges — a traced long-lived server would otherwise append
        # two counter events per 50ms poll to the Tracer's in-memory
        # list forever
        if granted or emitted or self.pool.active_count \
                or self.scheduler.depth:
            self.metrics.observe_tick(self.pool.occupancy(),
                                      self.scheduler.depth, emitted)
        return {"admitted": len(granted), "emitted": emitted,
                "active": self.pool.active_count,
                "queued": self.scheduler.depth}

    def _admit(self, req: Request) -> int:
        T = int(req.prompt.shape[0])
        slot = self.pool.assign(req.id, T)
        assert slot is not None, "admit() granted beyond free slots"
        req.slot = slot
        req.state = RequestState.ACTIVE
        req.t_admit = time.monotonic()
        self._slot_req[slot] = req
        bucket = _next_bucket(T, self.min_prefill_bucket, self.max_seq)
        padded = np.full((1, bucket), self.pad_id, np.int32)
        padded[0, :T] = req.prompt
        key = (jnp.zeros((2,), jnp.uint32) if self.greedy
               else jax.random.PRNGKey(req.seed))
        fn = self._prefill_fn(bucket)
        caches, tok0, nk = fn(self.variables, self.pool.caches,
                              jnp.asarray(padded), slot, T, key)
        self.pool.caches = caches
        self._tok = self._tok.at[slot].set(tok0)
        if not self.greedy:
            self._keys = self._keys.at[slot].set(nk)
        self.metrics.bump(sm.ADMITTED)
        self.metrics.bump(sm.PREFILL_TOKENS, bucket)
        self._emit(req, int(tok0))
        return 1

    def _decode_tick(self, active: List[int]) -> int:
        n = self.pool.n_slots
        pos = np.zeros((n,), np.int32)
        mask = np.zeros((n,), bool)
        for slot in active:
            pos[slot] = self.pool.pos[slot]
            mask[slot] = True
        caches, nxt, keys = self._decode_step(
            self.variables, self.pool.caches, self._tok,
            jnp.asarray(pos), jnp.asarray(mask), self._keys)
        self.pool.caches = caches
        self._tok = nxt
        self._keys = keys
        nxt_host = np.asarray(nxt)
        emitted = 0
        for slot in active:
            req = self._slot_req[slot]
            self.pool.advance(slot)
            self._emit(req, int(nxt_host[slot]))
            emitted += 1
        return emitted

    def _emit(self, req: Request, tok: int) -> None:
        now = time.monotonic()
        if not req.tokens:
            req.t_first = now
        req.t_last = now
        req.tokens.append(tok)
        req._out.put(tok)
        done = (len(req.tokens) >= req.max_new_tokens
                or (self.eos_id is not None and tok == self.eos_id))
        if done:
            self._finish(req, RequestState.DONE)

    def _finish(self, req: Request, state: RequestState) -> None:
        req.state = state
        if req.slot is not None:
            self._slot_req[req.slot] = None
            self.pool.free(req.slot)
            req.slot = None
        req._out.put(_END)
        req._done.set()
        if state is RequestState.DONE:
            n = len(req.tokens)
            tpot = ((req.t_last - req.t_first) / (n - 1) if n > 1 else None)
            self.metrics.observe_request(
                queue_wait_s=req.t_admit - req.t_submit,
                ttft_s=req.t_first - req.t_submit, tpot_s=tpot, tokens=n)
        elif state is RequestState.FAILED:
            self.metrics.bump(sm.FAILED)
        else:
            self.metrics.bump(sm.CANCELLED)
        with self._drain_cv:
            self._outstanding -= 1
            self._drain_cv.notify_all()

    # ---------------------------------------------------------- lifecycle

    def _idle(self) -> bool:
        return self.pool.active_count == 0 and self.scheduler.depth == 0

    def start(self) -> "ServingEngine":
        """Run the tick loop on a background thread (frontend mode)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_flag = False
            self._thread = threading.Thread(
                target=self._run, name="byteps-serve-engine", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_flag:
            try:
                self.step()
            except Exception as e:
                # a dead tick thread must not look like a hung one:
                # fail every in-flight and queued request loudly and
                # refuse new submissions — blocked result()/drain()
                # callers get the error instead of waiting forever
                bps_log.warning("serving engine tick failed: %r", e)
                self._fail_all(e)
                return
            with self._wake:
                if self._idle() and not self._stop_flag:
                    self._wake.wait(timeout=0.05)

    def _fail_all(self, exc: BaseException) -> None:
        with self._lock:
            self._engine_error = exc
            for slot in self.pool.active_slots():
                req = self._slot_req[slot]
                if req is not None:
                    req.error = exc
                    self._finish(req, RequestState.FAILED)
            # credit-FREE drain: admit() would skip queued tasks larger
            # than whatever credits the failed tick left, hanging their
            # result() callers forever
            for task in self.scheduler.drain_pending():
                task.request.error = exc
                self._finish(task.request, RequestState.FAILED)

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_flag = True
        with self._wake:
            self._wake.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
            if t.is_alive():
                # a wedged tick (e.g. a long compile) must not be
                # abandoned: clearing _thread would let a later start()
                # reset _stop_flag and spawn a SECOND tick loop beside
                # this one — leave it tracked, not restartable
                bps_log.warning(
                    "serving engine tick thread still running after "
                    "%.1fs; engine not restartable until it exits",
                    timeout)
            else:
                self._thread = None

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every submitted request has finished.  Without a
        background thread, drives :meth:`step` inline (deterministic
        single-threaded mode)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._thread is None:
            while True:
                with self._lock:
                    if self._outstanding == 0:
                        return
                self.step()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("drain timed out")
        else:
            with self._drain_cv:
                while self._outstanding > 0:
                    if (deadline is not None
                            and time.monotonic() >= deadline):
                        raise TimeoutError("drain timed out")
                    remaining = (None if deadline is None
                                 else deadline - time.monotonic())
                    self._drain_cv.wait(remaining)

    # --------------------------------------------------------- inspection

    def compile_counts(self) -> Dict[str, int]:
        """Trace counts of the step programs — steady-state serving must
        keep ``decode`` at 1 and ``prefill`` at the number of distinct
        buckets touched (asserted by tests and bench_serve.py)."""
        return {"decode": self.decode_traces,
                "prefill": self.prefill_traces,
                "prefill_buckets": len(self._prefill_fns)}
