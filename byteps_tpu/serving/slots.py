"""Fixed-capacity KV-cache slot pool.

JAX's static-shape world cannot grow a batch: the serving engine instead
pre-allocates ONE cache pytree of ``n_slots`` rows (``init_cache(cfg,
n_slots, max_seq)``) and treats the batch dimension as a pool of
*slots*.  Admitting a request assigns a free slot and writes its prompt
K/V into that row (``engine._prefill``); freeing returns the index.  No
allocation, no recompilation — the decode step's shapes never change.

Why freed rows are NOT zeroed: the decode attention mask
(``kidx <= pos + i`` in ``models.transformer._cached_attention``) admits
only positions at or below the request's own write cursor, and every
position up to the cursor has been overwritten by this request's prefill
or decode writes before the mask can reach it.  Stale K/V from a
previous tenant is therefore never attended — masked scores contribute
exactly-zero probability mass (``exp(-1e30 - max)`` underflows to 0.0
in fp32), so reuse is bit-exact, not just approximately safe.  The
parity tests pin this.

Slot assignment is lowest-free-index (a heap), which makes the engine's
tick order — and therefore its whole output — deterministic given the
admission order.
"""

from __future__ import annotations

import heapq
import threading
from typing import List, Optional

from ..models.transformer import TransformerConfig, init_cache


class SlotPool:
    """``n_slots`` KV-cache rows plus per-slot position bookkeeping.

    The cache pytree itself (``self.caches``) is functional state: the
    engine threads it through the jitted prefill/decode steps and stores
    the result back.  The pool owns only the host-side bookkeeping
    (free set, per-slot cursor) — device state and bookkeeping advance
    together inside ``ServingEngine.step()`` under the engine lock.
    """

    def __init__(self, cfg: TransformerConfig, n_slots: int, max_seq: int,
                 *, kv_quant: bool = False, layout: str = "grouped"):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        if max_seq < 2:
            raise ValueError(f"max_seq must be >= 2, got {max_seq}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_seq = max_seq
        self.kv_quant = kv_quant
        self.layout = layout
        # one cache pytree, batch dim = slot index.  The serving pool
        # defaults to the grouped layout: the decode step vmaps the
        # model's per-row decode over slots, and the grouped dense path
        # batches cleanly under vmap on every backend (the flat Pallas
        # kernel is a TPU-only single-program fast path).  Subclasses
        # override _init_caches to swap the storage layout (the paged
        # block pool, serving/blocks.py) while inheriting the slot
        # bookkeeping unchanged.
        self.caches = self._init_caches()
        self._lock = threading.Lock()
        self._free: List[int] = list(range(n_slots))
        heapq.heapify(self._free)
        # per-slot cursor: absolute position of the next K/V write
        # (== number of real tokens the slot's row currently holds)
        self.pos: List[int] = [0] * n_slots
        self.request_ids: List[Optional[int]] = [None] * n_slots

    def _init_caches(self):
        return init_cache(self.cfg, self.n_slots, self.max_seq,
                          quantized=self.kv_quant, layout=self.layout)

    # ------------------------------------------------------------ lifecycle

    def assign(self, request_id: int, prompt_len: int) -> Optional[int]:
        """Claim the lowest free slot for ``request_id``; None when full.
        ``prompt_len`` seeds the slot's cursor (prefill writes [0, T))."""
        if prompt_len < 1 or prompt_len >= self.max_seq:
            raise ValueError(
                f"prompt_len {prompt_len} not in [1, max_seq={self.max_seq})")
        with self._lock:
            if not self._free:
                return None
            slot = heapq.heappop(self._free)
            self.request_ids[slot] = request_id
            self.pos[slot] = prompt_len
            return slot

    def free(self, slot: int) -> None:
        """Return a slot to the pool (cache row left as-is — see module
        docstring for why stale K/V is safe)."""
        with self._lock:
            if self.request_ids[slot] is None:
                raise ValueError(f"slot {slot} is not assigned")
            self.reset_locked(slot)
            heapq.heappush(self._free, slot)

    def reset_locked(self, slot: int) -> None:
        self.request_ids[slot] = None
        self.pos[slot] = 0

    def advance(self, slot: int, n: int = 1) -> int:
        """Move a slot's write cursor after a decode step; returns the
        new position.  Raising rather than clamping: a cursor past
        ``max_seq`` means the engine failed to retire the request at its
        token budget — ``dynamic_update_slice`` would silently clamp the
        write onto the last row and corrupt the newest K/V."""
        with self._lock:
            new = self.pos[slot] + n
            if new > self.max_seq:
                raise RuntimeError(
                    f"slot {slot} cursor {new} overran max_seq "
                    f"{self.max_seq}")
            self.pos[slot] = new
            return new

    # ---------------------------------------------------------- inspection

    def active_slots(self) -> List[int]:
        with self._lock:
            return [i for i in range(self.n_slots)
                    if self.request_ids[i] is not None]

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def active_count(self) -> int:
        return self.n_slots - self.free_count

    def occupancy(self) -> float:
        return self.active_count / self.n_slots
