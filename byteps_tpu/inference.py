"""Autoregressive generation with a KV cache.

The reference is a training-communication library and ships no inference
path; a complete framework needs one.  TPU-first design:

* the KV cache is an explicit functional pytree (``models.transformer.
  init_cache``) threaded through ``lax.scan`` — not mutable module state —
  so the whole generation loop is one compiled XLA program;
* prefill and per-token decode share one static-shape program shape
  ("tq tokens at offset pos"), so a full generate compiles exactly two
  programs (prefill tq=T, decode tq=1) regardless of sequence length;
* sampling (temperature / top-k / top-p) runs on device inside the scan;
  EOS handling is a carried ``done`` mask (static shapes — finished rows
  emit ``pad_id`` for the remaining steps).

Typical use::

    fn = make_generate_fn(model, max_new_tokens=64, temperature=0.8,
                          top_p=0.9, eos_id=2)
    out = fn(variables, prompt_tokens, jax.random.PRNGKey(0))
    # out["tokens"]: [B, max_new_tokens]
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .models.transformer import Transformer, init_cache

__all__ = ["make_generate_fn", "generate", "sample_logits",
           "quantize_params", "beam_search", "speculative_generate",
           "truncated_draft", "classify_divergence"]


def classify_divergence(model: Transformer, variables, prompt,
                        tokens_a, tokens_b, *, tie_rtol: float = 0.02,
                        tie_atol: float = 0.05):
    """Diagnose the first disagreement between two greedy decodes of the
    same model (e.g. cached vs no-cache, or bf16 vs int8 storage).

    A raw agreement fraction cannot distinguish "bf16 reduction-order
    flipped a near-tie argmax" (benign, expected) from "the KV cache
    returned wrong context" (a bug).  This teacher-forces path A's
    tokens through a single full forward — causal attention makes the
    logits at the first divergent position ``d`` a function of the
    agreed prefix only, so both paths saw (numerically nearly) these
    logits there — and compares the logit of each path's chosen token:

    * identical tokens -> ``{"divergence": "none"}``
    * ``logit[a_d]`` within ``tie_rtol * span + tie_atol`` of
      ``logit[b_d]`` -> ``"tie"`` (a near-tie argmax; rounding noise)
    * otherwise -> ``"real"`` — path B chose a token the model scores
      clearly lower, i.e. a genuine numerical/cache defect.

    Returns per-batch-row worst case: ``{"divergence", "agreement",
    "first_div_pos", "delta_logit", "tie_threshold"}`` plus a position
    profile (``first_div_positions`` per row, ``div_frac_by_quarter``)
    distinguishing late near-tie churn from an early cliff.
    """
    import numpy as np

    toks_a = np.asarray(tokens_a)
    toks_b = np.asarray(tokens_b)
    assert toks_a.shape == toks_b.shape
    B, N = toks_a.shape
    agree = float((toks_a == toks_b).mean())
    if (toks_a == toks_b).all():
        return {"divergence": "none", "agreement": 1.0,
                "first_div_pos": -1, "delta_logit": 0.0,
                "tie_threshold": 0.0,
                "first_div_positions": [-1] * B,
                "div_frac_by_quarter": ([0.0] * 4 if N >= 4 else [])}
    # Position profile of the disagreements (r4 verdict #9): a raw 0.64
    # agreement cannot distinguish "near-tie churn spread over late
    # positions" (benign: once one near-tie flips, the contexts
    # legitimately differ from there on) from "a cliff at one early
    # position" (suspicious: a systematic defect fires immediately).
    # first_div_positions: per-row position of the first disagreement
    # (-1 = row identical); div_frac_by_quarter: fraction of differing
    # positions in each quarter of the generation, over all rows — churn
    # ramps up across quarters, a cliff saturates every quarter >= d.
    neq = toks_a != toks_b
    first_divs = [int(np.nonzero(neq[b])[0][0]) if neq[b].any() else -1
                  for b in range(B)]
    quarters = [round(float(neq[:, i * N // 4:(i + 1) * N // 4]
                            .mean()), 4)
                for i in range(4)] if N >= 4 else []
    full_a = jnp.concatenate(
        [jnp.asarray(prompt), jnp.asarray(toks_a)], axis=1)
    logits = _jitted_apply(model)(variables, full_a)
    logits = np.asarray(logits, np.float32)
    T = prompt.shape[1]
    worst = {"divergence": "none", "agreement": agree,
             "first_div_pos": -1, "delta_logit": 0.0,
             "tie_threshold": 0.0,
             "first_div_positions": first_divs,
             "div_frac_by_quarter": quarters}
    rank = {"none": 0, "tie": 1, "real": 2}
    for b in range(B):
        d = first_divs[b]
        if d < 0:
            continue
        # logits that produced generated token d live at sequence
        # position T + d - 1 (the previous token's output)
        row = logits[b, T + d - 1]
        la = float(row[toks_a[b, d]])
        lb = float(row[toks_b[b, d]])
        span = float(np.abs(row).max())
        thr = tie_rtol * span + tie_atol
        kind = "tie" if abs(la - lb) <= thr else "real"
        if rank[kind] > rank[worst["divergence"]] or (
                kind == worst["divergence"]
                and abs(la - lb) > abs(worst["delta_logit"])):
            worst = {"divergence": kind, "agreement": agree,
                     "first_div_pos": d,
                     "delta_logit": round(la - lb, 4),
                     "tie_threshold": round(thr, 4),
                     "first_div_positions": first_divs,
                     "div_frac_by_quarter": quarters}
    return worst


@functools.lru_cache(maxsize=8)
def _jitted_apply(model):
    """One jit wrapper per model: an inline ``jax.jit(model.apply)``
    would build a fresh wrapper (and recompile the full forward) on
    every ``classify_divergence`` call — the bench invokes it up to 3x
    per run."""
    return jax.jit(model.apply)


def quantize_params(params, in_axes_of=None):
    """Int8 weight-only quantization of a Transformer parameter tree for
    bandwidth-bound decode.

    Every ``QuantDense`` kernel is replaced by a symmetric per-output-
    channel int8 kernel plus an fp32 ``scale`` leaf (absmax over the
    contraction dims / 127); embeddings and norms are left untouched
    (embeddings are gathered, not streamed, and norms are tiny).  The
    resulting tree feeds straight into ``model.apply`` / ``generate`` —
    ``QuantDense`` dequantizes inside the matmul read, so HBM streams
    half the bytes (see docs/performance.md).

    ``in_axes_of`` maps a module name to its contraction-dim count for
    non-default layouts; the Transformer only needs ``{"o": 2}`` (the
    output projection contracts [H, D]), which is the default.
    """
    import flax.linen as nn

    in_axes_of = {"o": 2} if in_axes_of is None else in_axes_of

    def walk(node, name):
        if isinstance(node, dict):
            kern = node.get("kernel")
            # tp-sharded trees carry nn.Partitioned metadata boxes —
            # unbox for the math, re-box so the sharding survives
            boxed = isinstance(kern, nn.meta.AxisMetadata)
            w_raw = kern.unbox() if boxed else kern
            if w_raw is not None and jnp.issubdtype(
                    jnp.asarray(w_raw).dtype, jnp.floating):
                w = jnp.asarray(w_raw, jnp.float32)
                n_in = in_axes_of.get(name, 1)
                axes = tuple(range(n_in))
                absmax = jnp.max(jnp.abs(w), axis=axes)
                scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
                q = jnp.clip(jnp.round(w / scale), -127, 127)
                out = dict(node)
                qk = q.astype(jnp.int8)
                sc = scale.astype(jnp.float32)
                if boxed:
                    out["kernel"] = kern.replace_boxed(qk)
                    # the scale spans the kernel's output dims; carry the
                    # matching tail of the partition names
                    names = getattr(kern, "names", None)
                    if names is not None and any(names[n_in:]):
                        sc = nn.Partitioned(sc, names=tuple(names[n_in:]))
                    out["scale"] = sc
                else:
                    out["kernel"] = qk
                    out["scale"] = sc
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params, "")


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from ``logits [B, vocab]``.

    ``temperature == 0`` is greedy argmax.  ``top_k`` keeps the k highest
    logits; ``top_p`` keeps the smallest prefix of the sorted distribution
    with cumulative probability >= top_p (the highest-probability token is
    always kept).  Both filters compose (k first, then p), matching the
    usual HF ``generate`` semantics.

    Tie semantics: ``top_p`` masks by value threshold (smallest kept
    logit), so a token whose logit exactly equals the threshold survives
    even if it sat outside the nucleus in sorted order — with fp32
    logits exact ties are measure-zero, and keeping a tied-equal token
    is distribution-identical anyway (it has the same probability as the
    kept one).  HF instead scatters a positional mask back through the
    argsort; switch to that only if bit-exact HF parity ever matters.
    """
    if temperature == 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose *exclusive* cumulative mass is < top_p; the
        # argmax token has exclusive mass 0 and so always survives
        keep_sorted = (cum - probs) < top_p
        # threshold = smallest kept logit, mapped back to original order
        kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
        threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def make_generate_fn(model: Transformer, max_new_tokens: int, *,
                     temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     pad_id: int = 0,
                     kv_quant: bool = False,
                     cache_len: Optional[int] = None,
                     cache_layout: str = "auto"):
    """Build a jitted ``fn(variables, prompt [B, T], rng) -> dict`` that
    appends ``max_new_tokens`` sampled tokens to each prompt row.

    The prompt must be fully valid (no padding); rows that emit ``eos_id``
    are frozen to ``pad_id`` for the remaining steps.  Returns
    ``{"tokens": [B, max_new_tokens], "done": [B] bool}``.

    ``kv_quant=True`` decodes against an int8 KV cache (per-position,
    per-head scales — see ``models.transformer.init_cache``): half the
    cache HBM stream per token, at a small quantization cost to the
    attention weights.  Pair with ``quantize_params`` for the full int8
    decode mode.

    ``cache_len`` over-allocates the KV cache beyond the default
    ``T + max_new_tokens`` (decode attends over the whole buffer, so a
    longer cache costs bandwidth — use it to hold geometry constant
    across program variants, e.g. for benchmarking, or to reuse one
    compiled program across prompt lengths).

    ``cache_layout`` forwards to ``init_cache``: "auto" (flat
    decode-kernel layout on TPU, grouped elsewhere), "flat", or
    "grouped".
    """
    cfg = model.cfg

    def run(variables, prompt, rng):
        B, T = prompt.shape
        need = T + max_new_tokens
        if cache_len is not None and cache_len < need:
            # dynamic_update_slice would silently clamp out-of-range
            # writes onto the last slot, corrupting generation
            raise ValueError(
                f"cache_len={cache_len} < prompt + max_new_tokens "
                f"({need})")
        caches = init_cache(cfg, B, cache_len or need,
                            quantized=kv_quant, layout=cache_layout)
        # prefill: one batched forward writes the prompt's K/V into the
        # cache; last_only keeps the LM head off the T-1 positions whose
        # [B, T, vocab] fp32 logits nobody reads
        logits, caches = model.apply(
            variables, prompt, caches, 0, True, method=Transformer.decode)
        rng, sub = jax.random.split(rng)
        tok = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        done = (tok == eos_id) if eos_id is not None else jnp.zeros(B, bool)
        greedy = temperature == 0

        def step(carry, i):
            caches, tok, done, rng = carry
            logits, caches = model.apply(
                variables, tok[:, None], caches, T + i,
                method=Transformer.decode)
            if greedy:
                # no per-step rng: the carried key would force a threefry
                # split every step that DCE cannot remove (the key is
                # loop state), a pure tax on the decode critical path
                nxt = sample_logits(logits[:, -1], rng, 0.0)
            else:
                rng, sub = jax.random.split(rng)
                nxt = sample_logits(
                    logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, pad_id, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (caches, nxt, done, rng), tok

        (caches, tok, done, rng), toks = jax.lax.scan(
            step, (caches, tok, done, rng),
            jnp.arange(max_new_tokens - 1))
        del caches
        tokens = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), tok[:, None]], axis=1)
        return {"tokens": tokens, "done": done}

    return _layout_aware_jit(run)


class _AutoLayoutCache:
    """LRU bookkeeping for AUTO-layout compiled executables and their
    placed parameter trees (the machinery behind ``_layout_aware_jit``).

    Two nested LRUs: a long-lived serving process cycling prompt shapes
    (or alternating distinct same-shape int8 trees) must not pin
    compiled executables and full placed parameter copies forever (r4
    advisor).

      * ``max_compiled`` compiled executables, keyed on tree structure +
        every leaf's (shape, dtype) + the prompt shape;
      * per executable, ``max_placed`` placed (device_put into the
        compiler-chosen layout) copies of the full parameter tree, keyed
        on EVERY leaf's identity — a tree sharing just its first leaf
        with a previously placed one must not reuse it, and the leaves
        are held in the entry so no id can be recycled.

    ``compile_fn(variables, prompt, rng) -> (compiled, input_formats)``
    and ``place_fn(tree_or_args, format)`` are injectable so the LRU
    semantics are unit-testable on CPU (tests/test_inference_jit_cache.
    py) — the real compile path is only reachable on TPU.
    """

    def __init__(self, compile_fn, place_fn, max_compiled: int = 8,
                 max_placed: int = 2):
        from collections import OrderedDict

        self._odict = OrderedDict
        self.cache: "OrderedDict" = OrderedDict()
        self.max_compiled = max_compiled
        self.max_placed = max_placed
        self.compile_fn = compile_fn
        self.place_fn = place_fn

    @staticmethod
    def key_of(variables, prompt, rng, leaves=None):
        if leaves is None:
            leaves = jax.tree_util.tree_leaves(variables)
        return (jax.tree_util.tree_structure((variables, prompt, rng)),
                tuple((x.shape, str(x.dtype)) for x in leaves),
                prompt.shape, str(prompt.dtype))

    def __call__(self, variables, prompt, rng, leaves=None):
        # one tree walk per call: the caller's leaves list (computed for
        # its int8 gate) feeds the compile key and the placed-copy
        # identity key alike
        if leaves is None:
            leaves = jax.tree_util.tree_leaves(variables)
        key = self.key_of(variables, prompt, rng, leaves)
        ent = self.cache.get(key)
        if ent is None:
            compiled, formats = self.compile_fn(variables, prompt, rng)
            self.cache[key] = ent = (compiled, formats, self._odict())
            if len(self.cache) > self.max_compiled:
                self.cache.popitem(last=False)
        else:
            self.cache.move_to_end(key)
        compiled, formats, placed = ent
        # re-lay the params once per distinct tree (identity-keyed); a
        # couple of placed copies may be alive at once (alternating
        # trees, e.g. an A/B) without re-device_putting per call
        pkey = tuple(id(x) for x in leaves)
        hit = placed.get(pkey)
        if hit is None:
            # evict BEFORE placing so at most max_placed full device
            # copies of the params are ever alive (placing first would
            # transiently hold one extra — an OOM hazard for trees near
            # half of HBM; holding 2 is the explicit trade for not
            # re-device_putting per call when two trees alternate)
            while len(placed) >= self.max_placed:
                placed.popitem(last=False)
            placed[pkey] = hit = (
                list(leaves), self.place_fn(variables, formats[0]))
        else:
            placed.move_to_end(pkey)
        pvars = hit[1]
        p, r = self.place_fn((prompt, rng), (formats[1], formats[2]))
        return compiled(pvars, p, r)


def _layout_aware_jit(run):
    """jit ``run(variables, prompt, rng)``; int8 trees on TPU compile
    with AUTO input layouts.

    XLA's default entry layout for s8 parameters streams at roughly half
    the chip's HBM rate through the decode loop's mixed s8 dots; letting
    the compiler choose the layout (``Format(Layout.AUTO)``) recovers
    full rate — measured r4 on v5e: 0.49 -> 0.37 ms/token.  The params
    are ``device_put`` into the chosen layout on first use (a no-op copy
    on subsequent calls, since the placed tree is returned to the cache).
    Float trees see no effect from AUTO and take the plain jit path.
    LRU bookkeeping lives in ``_AutoLayoutCache`` (exposed as
    ``call._cache`` for introspection).
    """
    plain = jax.jit(run)
    try:
        from jax.experimental.layout import Format, Layout
        auto_jit = jax.jit(run, in_shardings=Format(Layout.AUTO))
    except Exception:  # pragma: no cover - older jax
        return plain

    def compile_fn(variables, prompt, rng):
        compiled = auto_jit.lower(variables, prompt, rng).compile()
        return compiled, compiled.input_formats[0]

    cache = _AutoLayoutCache(compile_fn, jax.device_put)

    def call(variables, prompt, rng):
        leaves = jax.tree_util.tree_leaves(variables)
        has_int8 = any(getattr(x, "dtype", None) == jnp.int8
                       for x in leaves)
        if not has_int8 or jax.default_backend() not in ("tpu", "axon"):
            return plain(variables, prompt, rng)
        return cache(variables, prompt, rng, leaves)

    call._cache = cache
    return call


@functools.lru_cache(maxsize=32)
def _cached_fn(model, max_new_tokens, temperature, top_k, top_p, eos_id,
               pad_id, kv_quant=False):
    return make_generate_fn(
        model, max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, eos_id=eos_id, pad_id=pad_id, kv_quant=kv_quant)


def generate(model: Transformer, variables, prompt, max_new_tokens: int, *,
             temperature: float = 1.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, eos_id: Optional[int] = None,
             pad_id: int = 0, rng=None, kv_quant: bool = False):
    """Convenience wrapper around :func:`make_generate_fn` (memoized on the
    static arguments, so repeated calls reuse the compiled program).

    Stochastic sampling (``temperature > 0``) requires an explicit ``rng``
    — a silent default key would make every call return the identical
    "sample".  Greedy decoding (``temperature=0``) needs no rng.
    """
    if rng is None:
        if temperature != 0:
            raise ValueError(
                "temperature > 0 samples stochastically: pass rng="
                "jax.random.PRNGKey(...) (each distinct key gives a "
                "distinct sample)")
        rng = jax.random.PRNGKey(0)
    fn = _cached_fn(model, max_new_tokens, temperature, top_k, top_p,
                    eos_id, pad_id, kv_quant)
    return fn(variables, prompt, rng)


def beam_search(model: Transformer, variables, prompt, max_new_tokens: int,
                num_beams: int, *, length_penalty: float = 1.0,
                eos_id: Optional[int] = None, pad_id: int = 0,
                cache_len: Optional[int] = None):
    """Beam-search decoding with the KV cache: returns the highest-scoring
    continuation per batch row.

    At each step every live beam expands over the full vocabulary, the
    top ``num_beams`` (by cumulative log-probability) survive per batch
    row, and their KV caches are gathered to follow the surviving
    parents — the cache reorder is a batched ``take`` on the cache
    pytree inside the scan, so the whole search is one compiled program
    (without ``eos_id`` this is exact beam search; the brute-force
    reference test pins it).  EOS semantics are the *frozen-slot*
    variant: a beam that emits ``eos_id`` keeps its slot, emitting
    ``pad_id`` at zero additional cost and a frozen length — unlike HF,
    which retires finished hypotheses to a pool and promotes the
    next-best live candidate into the freed slot, so with ``eos_id`` set
    the effective exploration width shrinks as beams finish.  Final
    ranking divides each beam's score by ``length**length_penalty``
    (>1 favors longer sequences).

    Returns ``{"tokens": [B, max_new_tokens], "scores": [B],
    "beam_tokens": [B, num_beams, max_new_tokens],
    "beam_scores": [B, num_beams]}`` — tokens/scores are the best beam's.
    """
    fn = _cached_beam_fn(model, max_new_tokens, num_beams,
                         length_penalty, eos_id, pad_id, cache_len)
    return fn(variables, prompt)


@functools.lru_cache(maxsize=32)
def _cached_beam_fn(model, max_new_tokens, num_beams, length_penalty,
                    eos_id, pad_id, cache_len=None):
    cfg = model.cfg
    K = num_beams
    V = cfg.vocab_size
    N = max_new_tokens
    NEG = jnp.float32(-1e30)

    def run(variables, prompt):
        B, T = prompt.shape
        if cache_len is not None and cache_len < T + N:
            raise ValueError(
                f"cache_len={cache_len} < prompt + max_new_tokens "
                f"({T + N})")
        caches = init_cache(cfg, B, cache_len or (T + N))
        logits, caches = model.apply(
            variables, prompt, caches, 0, True, method=Transformer.decode)
        logprobs = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        # distinct first tokens seed the beams
        scores, tok0 = jax.lax.top_k(logprobs, K)        # [B, K]
        # caches tile to [B*K, ...] — beam-major within each batch row
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, K, axis=0), caches)
        flat_tok = tok0.reshape(B * K)
        done = ((flat_tok == eos_id) if eos_id is not None
                else jnp.zeros(B * K, bool))
        lengths = jnp.ones(B * K, jnp.int32)             # tokens emitted
        history = jnp.full((B * K, N), pad_id, jnp.int32)
        history = history.at[:, 0].set(flat_tok)
        scores = scores.reshape(B * K)

        def step(carry, i):
            caches, tok, scores, done, lengths, history = carry
            logits, caches = model.apply(
                variables, tok[:, None], caches, T + i,
                method=Transformer.decode)
            lp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32))       # [B*K, V]
            # finished beams: only pad continues, at zero cost
            pad_row = jnp.full((V,), NEG).at[pad_id].set(0.0)
            lp = jnp.where(done[:, None], pad_row[None, :], lp)
            cand = scores[:, None] + lp                  # [B*K, V]
            cand = cand.reshape(B, K * V)
            new_scores, idx = jax.lax.top_k(cand, K)     # [B, K]
            parent = idx // V                            # beam within row
            new_tok = idx % V                            # token id
            flat_parent = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
            # follow the surviving parents
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, flat_parent, axis=0), caches)
            done = jnp.take(done, flat_parent)
            lengths = jnp.take(lengths, flat_parent)
            history = jnp.take(history, flat_parent, axis=0)
            flat_tok = new_tok.reshape(B * K)
            flat_tok = jnp.where(done, pad_id, flat_tok)
            history = history.at[:, i + 1].set(flat_tok)
            lengths = jnp.where(done, lengths, lengths + 1)
            if eos_id is not None:
                done = done | (flat_tok == eos_id)
            return (caches, flat_tok, new_scores.reshape(B * K), done,
                    lengths, history), ()

        (caches, tok, scores, done, lengths, history), _ = jax.lax.scan(
            step, (caches, flat_tok, scores, done, lengths, history),
            jnp.arange(N - 1))
        del caches
        # rank by length-normalized score
        norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
        norm = norm.reshape(B, K)
        best = jnp.argmax(norm, axis=-1)                 # [B]
        history = history.reshape(B, K, N)
        best_tokens = jnp.take_along_axis(
            history, best[:, None, None], axis=1)[:, 0]
        best_scores = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
        return {"tokens": best_tokens, "scores": best_scores,
                "beam_tokens": history, "beam_scores": norm}

    return jax.jit(run)


def truncated_draft(cfg, variables, num_layers: int):
    """LayerSkip-style self-draft: the target's own first ``num_layers``
    blocks (plus its embeddings, final norm, and LM head) form the
    draft model — no trained draft checkpoint needed, and the layers
    are shared (zero extra HBM for weights beyond what the target
    already holds... the pytree leaves are the SAME arrays, so XLA
    deduplicates them).

    A 4-of-12-layer draft runs ~3x cheaper per token than the target
    while staying correlated with it (early layers carry most
    next-token signal on average); speculative acceptance then decides
    how much of that cheapness survives.  Returns ``(draft_model,
    draft_variables)`` for ``speculative_generate``.
    """
    import dataclasses

    if not 1 <= num_layers <= cfg.num_layers:
        raise ValueError(
            f"draft num_layers {num_layers} not in [1, {cfg.num_layers}]")
    dcfg = dataclasses.replace(cfg, num_layers=num_layers)
    params = variables["params"]
    keep = {k: v for k, v in params.items()
            if not k.startswith("block_")
            or int(k.split("_")[1]) < num_layers}
    return Transformer(dcfg), {"params": keep}


def speculative_generate(target: Transformer, target_vars,
                         draft: Transformer, draft_vars,
                         prompt, max_new_tokens: int, *, gamma: int = 4,
                         eos_id: Optional[int] = None, pad_id: int = 0,
                         cache_len: Optional[int] = None):
    """Greedy speculative decoding: a small draft model proposes ``gamma``
    tokens autoregressively, the target model verifies them in ONE
    ``gamma+1``-token decode, and the longest agreeing prefix is accepted
    plus the target's own next token — so each target forward emits
    between 1 and ``gamma+1`` tokens.  In exact arithmetic greedy
    acceptance makes the output identical to target-only greedy decoding
    (the draft only changes speed, never content); in floating point the
    correction token comes from a tq=gamma+1 forward whose reduction
    order differs from ``generate``'s tq=1 steps, so a near-tie argmax
    can occasionally flip.  The exactness tests pin equality on fixed
    seeds.

    The KV-cache design makes rejection rollback free: cache slots beyond
    ``pos`` are never read (the causal mask doubles as the validity mask),
    so rejected drafts' K/V are simply overwritten later and both models
    just track the accepted position.  Both models must share the
    vocabulary.  Returns ``{"tokens": [B, max_new_tokens],
    "acceptance": mean accepted-per-round fraction}``.
    """
    fn = _cached_spec_fn(target, draft, max_new_tokens, gamma, eos_id,
                         pad_id, cache_len)
    return fn(target_vars, draft_vars, prompt)


@functools.lru_cache(maxsize=16)
def _cached_spec_fn(target, draft, max_new_tokens, gamma, eos_id, pad_id,
                    cache_len=None):
    N, G = max_new_tokens, gamma
    tcfg, dcfg = target.cfg, draft.cfg

    def run(target_vars, draft_vars, prompt):
        B, T = prompt.shape
        need = T + N + G + 1
        if cache_len is not None and cache_len < need:
            raise ValueError(
                f"cache_len={cache_len} < prompt + max_new_tokens + "
                f"gamma + 1 ({need})")
        S = cache_len or need
        # target cache: every target call is a tq=gamma+1 verify (or
        # prefill) at a traced pos — the flat layout's tq>1 fallback
        # would pay a physical cache relayout per round, so the target
        # stays grouped; the draft's tq=1 steps get the flat kernel.
        t_caches = init_cache(tcfg, B, S, layout="grouped")
        d_caches = init_cache(dcfg, B, S)
        # prefill both models; the target's last-position logits give the
        # first pending token
        t_logits, t_caches = target.apply(
            target_vars, prompt, t_caches, 0, True,
            method=Transformer.decode)
        _, d_caches = draft.apply(
            draft_vars, prompt, d_caches, 0, True,
            method=Transformer.decode)
        last = jnp.argmax(t_logits[:, -1], axis=-1)      # pending token
        out = jnp.full((B, N + G + 1), pad_id, jnp.int32)
        done = ((last == eos_id) if eos_id is not None
                else jnp.zeros(B, bool))
        out = out.at[:, 0].set(last)

        # carry: emitted counts the tokens already WRITTEN to out;
        # pos = T + emitted - 1 is both caches' valid-prefix length
        # (the newest written token is pending, its K/V not yet stored)
        def cond(c):
            return c[0] < N

        def body(c):
            (emitted, last, out, done, t_caches, d_caches, rounds, acc,
             live_slots) = c
            pos = T + emitted - 1

            # draft G tokens with the small model
            def d_step(carry, _):
                d_caches, tok, p = carry
                lg, d_caches = draft.apply(
                    draft_vars, tok[:, None], d_caches, p,
                    method=Transformer.decode)
                nxt = jnp.argmax(lg[:, -1], axis=-1)
                return (d_caches, nxt, p + 1), nxt

            (d_caches, _, _), drafts = jax.lax.scan(
                d_step, (d_caches, last, pos), None, length=G)
            drafts = jnp.moveaxis(drafts, 0, 1)          # [B, G]

            # one target forward verifies all G drafts (+ bonus token)
            block = jnp.concatenate([last[:, None], drafts], axis=1)
            t_lg, t_caches = target.apply(
                target_vars, block, t_caches, pos,
                method=Transformer.decode)
            t_argmax = jnp.argmax(t_lg, axis=-1)         # [B, G+1]

            # longest agreeing prefix per row
            agree = (t_argmax[:, :G] == drafts)
            k = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1),
                        axis=1)                          # [B] in [0, G]
            # lockstep across the batch: accept the batch-min prefix so a
            # single scalar pos advance serves every row (per-row pos
            # would need per-row cache offsets); rows that could have
            # accepted more simply re-verify those tokens next round --
            # same output, slightly more rounds on divergent batches
            kmin = jnp.min(jnp.where(done, G, k))
            take = kmin + 1                              # tokens emitted
            # emitted block: kmin accepted drafts, then the target's own
            # argmax at position kmin (correction if kmin<G, bonus at G)
            corr = jnp.take_along_axis(
                t_argmax, jnp.full((B, 1), kmin), axis=1)[:, 0]
            cols = jnp.arange(G + 1)[None, :]
            toks = jnp.where(cols < kmin[None, None][0],
                             jnp.concatenate(
                                 [drafts, drafts[:, :1]], axis=1),
                             pad_id).astype(jnp.int32)
            toks = toks.at[:, kmin].set(corr)
            toks = jnp.where(cols >= take, pad_id, toks)
            if eos_id is not None:
                # freeze within the round: positions strictly after the
                # first eos become pad, matching generate()'s semantics
                is_eos = (toks == eos_id) & (cols < take)
                after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                         - is_eos.astype(jnp.int32)) > 0
                toks = jnp.where(after, pad_id, toks)
                done_new = done | jnp.any(is_eos, axis=1)
            else:
                done_new = done
            toks = jnp.where(done[:, None], pad_id, toks)
            out = jax.lax.dynamic_update_slice(out, toks, (0, emitted))
            new_last = jnp.where(done, last, corr)
            # acceptance accounting over LIVE rows only: finished rows
            # draft nothing real (kmin treats them as accepting G via the
            # batch-min), so counting their slots would inflate the rate
            # on eos-terminated batches
            n_live = jnp.sum(jnp.where(done, 0, 1))
            return (emitted + take, new_last, out, done_new, t_caches,
                    d_caches, rounds + 1, acc + kmin * n_live,
                    live_slots + G * n_live)

        emitted0 = jnp.int32(1)
        rounds0 = jnp.int32(0)
        acc0 = jnp.int32(0)
        (emitted, last, out, done, t_caches, d_caches, rounds, acc,
         live_slots) = (
            jax.lax.while_loop(
                cond, body,
                (emitted0, last, out, done, t_caches, d_caches, rounds0,
                 acc0, jnp.int32(0))))
        del t_caches, d_caches
        return {"tokens": out[:, :N],
                "acceptance": (acc.astype(jnp.float32)
                               / jnp.maximum(live_slots, 1)),
                "rounds": rounds,
                "tokens_per_target_forward": (
                    jnp.float32(N) / jnp.maximum(rounds, 1))}

    return jax.jit(run)
