"""Autoregressive generation with a KV cache.

The reference is a training-communication library and ships no inference
path; a complete framework needs one.  TPU-first design:

* the KV cache is an explicit functional pytree (``models.transformer.
  init_cache``) threaded through ``lax.scan`` — not mutable module state —
  so the whole generation loop is one compiled XLA program;
* prefill and per-token decode share one static-shape program shape
  ("tq tokens at offset pos"), so a full generate compiles exactly two
  programs (prefill tq=T, decode tq=1) regardless of sequence length;
* sampling (temperature / top-k / top-p) runs on device inside the scan;
  EOS handling is a carried ``done`` mask (static shapes — finished rows
  emit ``pad_id`` for the remaining steps).

Typical use::

    fn = make_generate_fn(model, max_new_tokens=64, temperature=0.8,
                          top_p=0.9, eos_id=2)
    out = fn(variables, prompt_tokens, jax.random.PRNGKey(0))
    # out["tokens"]: [B, max_new_tokens]
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .models.transformer import Transformer, init_cache

__all__ = ["make_generate_fn", "generate", "sample_logits",
           "quantize_params", "beam_search", "speculative_generate"]


def quantize_params(params, in_axes_of=None):
    """Int8 weight-only quantization of a Transformer parameter tree for
    bandwidth-bound decode.

    Every ``QuantDense`` kernel is replaced by a symmetric per-output-
    channel int8 kernel plus an fp32 ``scale`` leaf (absmax over the
    contraction dims / 127); embeddings and norms are left untouched
    (embeddings are gathered, not streamed, and norms are tiny).  The
    resulting tree feeds straight into ``model.apply`` / ``generate`` —
    ``QuantDense`` dequantizes inside the matmul read, so HBM streams
    half the bytes (see docs/performance.md).

    ``in_axes_of`` maps a module name to its contraction-dim count for
    non-default layouts; the Transformer only needs ``{"o": 2}`` (the
    output projection contracts [H, D]), which is the default.
    """
    import flax.linen as nn

    in_axes_of = {"o": 2} if in_axes_of is None else in_axes_of

    def walk(node, name):
        if isinstance(node, dict):
            kern = node.get("kernel")
            # tp-sharded trees carry nn.Partitioned metadata boxes —
            # unbox for the math, re-box so the sharding survives
            boxed = isinstance(kern, nn.meta.AxisMetadata)
            w_raw = kern.unbox() if boxed else kern
            if w_raw is not None and jnp.issubdtype(
                    jnp.asarray(w_raw).dtype, jnp.floating):
                w = jnp.asarray(w_raw, jnp.float32)
                n_in = in_axes_of.get(name, 1)
                axes = tuple(range(n_in))
                absmax = jnp.max(jnp.abs(w), axis=axes)
                scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
                q = jnp.clip(jnp.round(w / scale), -127, 127)
                out = dict(node)
                qk = q.astype(jnp.int8)
                sc = scale.astype(jnp.float32)
                if boxed:
                    out["kernel"] = kern.replace_boxed(qk)
                    # the scale spans the kernel's output dims; carry the
                    # matching tail of the partition names
                    names = getattr(kern, "names", None)
                    if names is not None and any(names[n_in:]):
                        sc = nn.Partitioned(sc, names=tuple(names[n_in:]))
                    out["scale"] = sc
                else:
                    out["kernel"] = qk
                    out["scale"] = sc
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params, "")


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from ``logits [B, vocab]``.

    ``temperature == 0`` is greedy argmax.  ``top_k`` keeps the k highest
    logits; ``top_p`` keeps the smallest prefix of the sorted distribution
    with cumulative probability >= top_p (the highest-probability token is
    always kept).  Both filters compose (k first, then p), matching the
    usual HF ``generate`` semantics.
    """
    if temperature == 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose *exclusive* cumulative mass is < top_p; the
        # argmax token has exclusive mass 0 and so always survives
        keep_sorted = (cum - probs) < top_p
        # threshold = smallest kept logit, mapped back to original order
        kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
        threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def make_generate_fn(model: Transformer, max_new_tokens: int, *,
                     temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     pad_id: int = 0):
    """Build a jitted ``fn(variables, prompt [B, T], rng) -> dict`` that
    appends ``max_new_tokens`` sampled tokens to each prompt row.

    The prompt must be fully valid (no padding); rows that emit ``eos_id``
    are frozen to ``pad_id`` for the remaining steps.  Returns
    ``{"tokens": [B, max_new_tokens], "done": [B] bool}``.
    """
    cfg = model.cfg

    def run(variables, prompt, rng):
        B, T = prompt.shape
        caches = init_cache(cfg, B, T + max_new_tokens)
        # prefill: one batched forward writes the prompt's K/V into the
        # cache; last_only keeps the LM head off the T-1 positions whose
        # [B, T, vocab] fp32 logits nobody reads
        logits, caches = model.apply(
            variables, prompt, caches, 0, True, method=Transformer.decode)
        rng, sub = jax.random.split(rng)
        tok = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        done = (tok == eos_id) if eos_id is not None else jnp.zeros(B, bool)

        def step(carry, i):
            caches, tok, done, rng = carry
            logits, caches = model.apply(
                variables, tok[:, None], caches, T + i,
                method=Transformer.decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(
                logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, pad_id, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (caches, nxt, done, rng), tok

        (caches, tok, done, rng), toks = jax.lax.scan(
            step, (caches, tok, done, rng),
            jnp.arange(max_new_tokens - 1))
        del caches
        tokens = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), tok[:, None]], axis=1)
        return {"tokens": tokens, "done": done}

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _cached_fn(model, max_new_tokens, temperature, top_k, top_p, eos_id,
               pad_id):
    return make_generate_fn(
        model, max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, eos_id=eos_id, pad_id=pad_id)


def generate(model: Transformer, variables, prompt, max_new_tokens: int, *,
             temperature: float = 1.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, eos_id: Optional[int] = None,
             pad_id: int = 0, rng=None):
    """Convenience wrapper around :func:`make_generate_fn` (memoized on the
    static arguments, so repeated calls reuse the compiled program).

    Stochastic sampling (``temperature > 0``) requires an explicit ``rng``
    — a silent default key would make every call return the identical
    "sample".  Greedy decoding (``temperature=0``) needs no rng.
    """
    if rng is None:
        if temperature != 0:
            raise ValueError(
                "temperature > 0 samples stochastically: pass rng="
                "jax.random.PRNGKey(...) (each distinct key gives a "
                "distinct sample)")
        rng = jax.random.PRNGKey(0)
    fn = _cached_fn(model, max_new_tokens, temperature, top_k, top_p,
                    eos_id, pad_id)
    return fn(variables, prompt, rng)


def beam_search(model: Transformer, variables, prompt, max_new_tokens: int,
                num_beams: int, *, length_penalty: float = 1.0,
                eos_id: Optional[int] = None, pad_id: int = 0):
    """Beam-search decoding with the KV cache: returns the highest-scoring
    continuation per batch row.

    At each step every live beam expands over the full vocabulary, the
    top ``num_beams`` (by cumulative log-probability) survive per batch
    row, and their KV caches are gathered to follow the surviving
    parents — the cache reorder is a batched ``take`` on the cache
    pytree inside the scan, so the whole search is one compiled program
    (without ``eos_id`` this is exact beam search; the brute-force
    reference test pins it).  EOS semantics are the *frozen-slot*
    variant: a beam that emits ``eos_id`` keeps its slot, emitting
    ``pad_id`` at zero additional cost and a frozen length — unlike HF,
    which retires finished hypotheses to a pool and promotes the
    next-best live candidate into the freed slot, so with ``eos_id`` set
    the effective exploration width shrinks as beams finish.  Final
    ranking divides each beam's score by ``length**length_penalty``
    (>1 favors longer sequences).

    Returns ``{"tokens": [B, max_new_tokens], "scores": [B],
    "beam_tokens": [B, num_beams, max_new_tokens],
    "beam_scores": [B, num_beams]}`` — tokens/scores are the best beam's.
    """
    fn = _cached_beam_fn(model, max_new_tokens, num_beams,
                         length_penalty, eos_id, pad_id)
    return fn(variables, prompt)


@functools.lru_cache(maxsize=32)
def _cached_beam_fn(model, max_new_tokens, num_beams, length_penalty,
                    eos_id, pad_id):
    cfg = model.cfg
    K = num_beams
    V = cfg.vocab_size
    N = max_new_tokens
    NEG = jnp.float32(-1e30)

    def run(variables, prompt):
        B, T = prompt.shape
        caches = init_cache(cfg, B, T + N)
        logits, caches = model.apply(
            variables, prompt, caches, 0, True, method=Transformer.decode)
        logprobs = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        # distinct first tokens seed the beams
        scores, tok0 = jax.lax.top_k(logprobs, K)        # [B, K]
        # caches tile to [B*K, ...] — beam-major within each batch row
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, K, axis=0), caches)
        flat_tok = tok0.reshape(B * K)
        done = ((flat_tok == eos_id) if eos_id is not None
                else jnp.zeros(B * K, bool))
        lengths = jnp.ones(B * K, jnp.int32)             # tokens emitted
        history = jnp.full((B * K, N), pad_id, jnp.int32)
        history = history.at[:, 0].set(flat_tok)
        scores = scores.reshape(B * K)

        def step(carry, i):
            caches, tok, scores, done, lengths, history = carry
            logits, caches = model.apply(
                variables, tok[:, None], caches, T + i,
                method=Transformer.decode)
            lp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32))       # [B*K, V]
            # finished beams: only pad continues, at zero cost
            pad_row = jnp.full((V,), NEG).at[pad_id].set(0.0)
            lp = jnp.where(done[:, None], pad_row[None, :], lp)
            cand = scores[:, None] + lp                  # [B*K, V]
            cand = cand.reshape(B, K * V)
            new_scores, idx = jax.lax.top_k(cand, K)     # [B, K]
            parent = idx // V                            # beam within row
            new_tok = idx % V                            # token id
            flat_parent = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
            # follow the surviving parents
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, flat_parent, axis=0), caches)
            done = jnp.take(done, flat_parent)
            lengths = jnp.take(lengths, flat_parent)
            history = jnp.take(history, flat_parent, axis=0)
            flat_tok = new_tok.reshape(B * K)
            flat_tok = jnp.where(done, pad_id, flat_tok)
            history = history.at[:, i + 1].set(flat_tok)
            lengths = jnp.where(done, lengths, lengths + 1)
            if eos_id is not None:
                done = done | (flat_tok == eos_id)
            return (caches, flat_tok, new_scores.reshape(B * K), done,
                    lengths, history), ()

        (caches, tok, scores, done, lengths, history), _ = jax.lax.scan(
            step, (caches, flat_tok, scores, done, lengths, history),
            jnp.arange(N - 1))
        del caches
        # rank by length-normalized score
        norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
        norm = norm.reshape(B, K)
        best = jnp.argmax(norm, axis=-1)                 # [B]
        history = history.reshape(B, K, N)
        best_tokens = jnp.take_along_axis(
            history, best[:, None, None], axis=1)[:, 0]
        best_scores = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
        return {"tokens": best_tokens, "scores": best_scores,
                "beam_tokens": history, "beam_scores": norm}

    return jax.jit(run)


def speculative_generate(target: Transformer, target_vars,
                         draft: Transformer, draft_vars,
                         prompt, max_new_tokens: int, *, gamma: int = 4,
                         eos_id: Optional[int] = None, pad_id: int = 0):
    """Greedy speculative decoding: a small draft model proposes ``gamma``
    tokens autoregressively, the target model verifies them in ONE
    ``gamma+1``-token decode, and the longest agreeing prefix is accepted
    plus the target's own next token — so each target forward emits
    between 1 and ``gamma+1`` tokens.  In exact arithmetic greedy
    acceptance makes the output identical to target-only greedy decoding
    (the draft only changes speed, never content); in floating point the
    correction token comes from a tq=gamma+1 forward whose reduction
    order differs from ``generate``'s tq=1 steps, so a near-tie argmax
    can occasionally flip.  The exactness tests pin equality on fixed
    seeds.

    The KV-cache design makes rejection rollback free: cache slots beyond
    ``pos`` are never read (the causal mask doubles as the validity mask),
    so rejected drafts' K/V are simply overwritten later and both models
    just track the accepted position.  Both models must share the
    vocabulary.  Returns ``{"tokens": [B, max_new_tokens],
    "acceptance": mean accepted-per-round fraction}``.
    """
    fn = _cached_spec_fn(target, draft, max_new_tokens, gamma, eos_id,
                         pad_id)
    return fn(target_vars, draft_vars, prompt)


@functools.lru_cache(maxsize=16)
def _cached_spec_fn(target, draft, max_new_tokens, gamma, eos_id, pad_id):
    N, G = max_new_tokens, gamma
    tcfg, dcfg = target.cfg, draft.cfg

    def run(target_vars, draft_vars, prompt):
        B, T = prompt.shape
        S = T + N + G + 1
        t_caches = init_cache(tcfg, B, S)
        d_caches = init_cache(dcfg, B, S)
        # prefill both models; the target's last-position logits give the
        # first pending token
        t_logits, t_caches = target.apply(
            target_vars, prompt, t_caches, 0, True,
            method=Transformer.decode)
        _, d_caches = draft.apply(
            draft_vars, prompt, d_caches, 0, True,
            method=Transformer.decode)
        last = jnp.argmax(t_logits[:, -1], axis=-1)      # pending token
        out = jnp.full((B, N + G + 1), pad_id, jnp.int32)
        done = ((last == eos_id) if eos_id is not None
                else jnp.zeros(B, bool))
        out = out.at[:, 0].set(last)

        # carry: emitted counts the tokens already WRITTEN to out;
        # pos = T + emitted - 1 is both caches' valid-prefix length
        # (the newest written token is pending, its K/V not yet stored)
        def cond(c):
            return c[0] < N

        def body(c):
            emitted, last, out, done, t_caches, d_caches, rounds, acc = c
            pos = T + emitted - 1

            # draft G tokens with the small model
            def d_step(carry, _):
                d_caches, tok, p = carry
                lg, d_caches = draft.apply(
                    draft_vars, tok[:, None], d_caches, p,
                    method=Transformer.decode)
                nxt = jnp.argmax(lg[:, -1], axis=-1)
                return (d_caches, nxt, p + 1), nxt

            (d_caches, _, _), drafts = jax.lax.scan(
                d_step, (d_caches, last, pos), None, length=G)
            drafts = jnp.moveaxis(drafts, 0, 1)          # [B, G]

            # one target forward verifies all G drafts (+ bonus token)
            block = jnp.concatenate([last[:, None], drafts], axis=1)
            t_lg, t_caches = target.apply(
                target_vars, block, t_caches, pos,
                method=Transformer.decode)
            t_argmax = jnp.argmax(t_lg, axis=-1)         # [B, G+1]

            # longest agreeing prefix per row
            agree = (t_argmax[:, :G] == drafts)
            k = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1),
                        axis=1)                          # [B] in [0, G]
            # lockstep across the batch: accept the batch-min prefix so a
            # single scalar pos advance serves every row (per-row pos
            # would need per-row cache offsets); rows that could have
            # accepted more simply re-verify those tokens next round --
            # same output, slightly more rounds on divergent batches
            kmin = jnp.min(jnp.where(done, G, k))
            take = kmin + 1                              # tokens emitted
            # emitted block: kmin accepted drafts, then the target's own
            # argmax at position kmin (correction if kmin<G, bonus at G)
            corr = jnp.take_along_axis(
                t_argmax, jnp.full((B, 1), kmin), axis=1)[:, 0]
            cols = jnp.arange(G + 1)[None, :]
            toks = jnp.where(cols < kmin[None, None][0],
                             jnp.concatenate(
                                 [drafts, drafts[:, :1]], axis=1),
                             pad_id).astype(jnp.int32)
            toks = toks.at[:, kmin].set(corr)
            toks = jnp.where(cols >= take, pad_id, toks)
            if eos_id is not None:
                # freeze within the round: positions strictly after the
                # first eos become pad, matching generate()'s semantics
                is_eos = (toks == eos_id) & (cols < take)
                after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                         - is_eos.astype(jnp.int32)) > 0
                toks = jnp.where(after, pad_id, toks)
                done_new = done | jnp.any(is_eos, axis=1)
            else:
                done_new = done
            toks = jnp.where(done[:, None], pad_id, toks)
            out = jax.lax.dynamic_update_slice(out, toks, (0, emitted))
            new_last = jnp.where(done, last, corr)
            return (emitted + take, new_last, out, done_new, t_caches,
                    d_caches, rounds + 1, acc + kmin)

        emitted0 = jnp.int32(1)
        rounds0 = jnp.int32(0)
        acc0 = jnp.int32(0)
        (emitted, last, out, done, t_caches, d_caches, rounds, acc) = (
            jax.lax.while_loop(
                cond, body,
                (emitted0, last, out, done, t_caches, d_caches, rounds0,
                 acc0)))
        del t_caches, d_caches
        return {"tokens": out[:, :N],
                "acceptance": (acc.astype(jnp.float32)
                               / jnp.maximum(rounds * G, 1)),
                "rounds": rounds,
                "tokens_per_target_forward": (
                    jnp.float32(N) / jnp.maximum(rounds, 1))}

    return jax.jit(run)
