"""Autoregressive generation with a KV cache.

The reference is a training-communication library and ships no inference
path; a complete framework needs one.  TPU-first design:

* the KV cache is an explicit functional pytree (``models.transformer.
  init_cache``) threaded through ``lax.scan`` — not mutable module state —
  so the whole generation loop is one compiled XLA program;
* prefill and per-token decode share one static-shape program shape
  ("tq tokens at offset pos"), so a full generate compiles exactly two
  programs (prefill tq=T, decode tq=1) regardless of sequence length;
* sampling (temperature / top-k / top-p) runs on device inside the scan;
  EOS handling is a carried ``done`` mask (static shapes — finished rows
  emit ``pad_id`` for the remaining steps).

Typical use::

    fn = make_generate_fn(model, max_new_tokens=64, temperature=0.8,
                          top_p=0.9, eos_id=2)
    out = fn(variables, prompt_tokens, jax.random.PRNGKey(0))
    # out["tokens"]: [B, max_new_tokens]
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .models.transformer import Transformer, init_cache

__all__ = ["make_generate_fn", "generate", "sample_logits",
           "quantize_params"]


def quantize_params(params, in_axes_of=None):
    """Int8 weight-only quantization of a Transformer parameter tree for
    bandwidth-bound decode.

    Every ``QuantDense`` kernel is replaced by a symmetric per-output-
    channel int8 kernel plus an fp32 ``scale`` leaf (absmax over the
    contraction dims / 127); embeddings and norms are left untouched
    (embeddings are gathered, not streamed, and norms are tiny).  The
    resulting tree feeds straight into ``model.apply`` / ``generate`` —
    ``QuantDense`` dequantizes inside the matmul read, so HBM streams
    half the bytes (see docs/performance.md).

    ``in_axes_of`` maps a module name to its contraction-dim count for
    non-default layouts; the Transformer only needs ``{"o": 2}`` (the
    output projection contracts [H, D]), which is the default.
    """
    import flax.linen as nn

    in_axes_of = {"o": 2} if in_axes_of is None else in_axes_of

    def walk(node, name):
        if isinstance(node, dict):
            kern = node.get("kernel")
            # tp-sharded trees carry nn.Partitioned metadata boxes —
            # unbox for the math, re-box so the sharding survives
            boxed = isinstance(kern, nn.meta.AxisMetadata)
            w_raw = kern.unbox() if boxed else kern
            if w_raw is not None and jnp.issubdtype(
                    jnp.asarray(w_raw).dtype, jnp.floating):
                w = jnp.asarray(w_raw, jnp.float32)
                n_in = in_axes_of.get(name, 1)
                axes = tuple(range(n_in))
                absmax = jnp.max(jnp.abs(w), axis=axes)
                scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
                q = jnp.clip(jnp.round(w / scale), -127, 127)
                out = dict(node)
                qk = q.astype(jnp.int8)
                sc = scale.astype(jnp.float32)
                if boxed:
                    out["kernel"] = kern.replace_boxed(qk)
                    # the scale spans the kernel's output dims; carry the
                    # matching tail of the partition names
                    names = getattr(kern, "names", None)
                    if names is not None and any(names[n_in:]):
                        sc = nn.Partitioned(sc, names=tuple(names[n_in:]))
                    out["scale"] = sc
                else:
                    out["kernel"] = qk
                    out["scale"] = sc
                return out
            return {k: walk(v, k) for k, v in node.items()}
        return node

    return walk(params, "")


def sample_logits(logits, rng, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from ``logits [B, vocab]``.

    ``temperature == 0`` is greedy argmax.  ``top_k`` keeps the k highest
    logits; ``top_p`` keeps the smallest prefix of the sorted distribution
    with cumulative probability >= top_p (the highest-probability token is
    always kept).  Both filters compose (k first, then p), matching the
    usual HF ``generate`` semantics.
    """
    if temperature == 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose *exclusive* cumulative mass is < top_p; the
        # argmax token has exclusive mass 0 and so always survives
        keep_sorted = (cum - probs) < top_p
        # threshold = smallest kept logit, mapped back to original order
        kept_logits = jnp.where(keep_sorted, sorted_logits, jnp.inf)
        threshold = jnp.min(kept_logits, axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def make_generate_fn(model: Transformer, max_new_tokens: int, *,
                     temperature: float = 1.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     eos_id: Optional[int] = None,
                     pad_id: int = 0):
    """Build a jitted ``fn(variables, prompt [B, T], rng) -> dict`` that
    appends ``max_new_tokens`` sampled tokens to each prompt row.

    The prompt must be fully valid (no padding); rows that emit ``eos_id``
    are frozen to ``pad_id`` for the remaining steps.  Returns
    ``{"tokens": [B, max_new_tokens], "done": [B] bool}``.
    """
    cfg = model.cfg

    def run(variables, prompt, rng):
        B, T = prompt.shape
        caches = init_cache(cfg, B, T + max_new_tokens)
        # prefill: one batched forward writes the prompt's K/V into the
        # cache; last_only keeps the LM head off the T-1 positions whose
        # [B, T, vocab] fp32 logits nobody reads
        logits, caches = model.apply(
            variables, prompt, caches, 0, True, method=Transformer.decode)
        rng, sub = jax.random.split(rng)
        tok = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        done = (tok == eos_id) if eos_id is not None else jnp.zeros(B, bool)

        def step(carry, i):
            caches, tok, done, rng = carry
            logits, caches = model.apply(
                variables, tok[:, None], caches, T + i,
                method=Transformer.decode)
            rng, sub = jax.random.split(rng)
            nxt = sample_logits(
                logits[:, -1], sub, temperature, top_k, top_p)
            nxt = jnp.where(done, pad_id, nxt)
            if eos_id is not None:
                done = done | (nxt == eos_id)
            return (caches, nxt, done, rng), tok

        (caches, tok, done, rng), toks = jax.lax.scan(
            step, (caches, tok, done, rng),
            jnp.arange(max_new_tokens - 1))
        del caches
        tokens = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), tok[:, None]], axis=1)
        return {"tokens": tokens, "done": done}

    return jax.jit(run)


@functools.lru_cache(maxsize=32)
def _cached_fn(model, max_new_tokens, temperature, top_k, top_p, eos_id,
               pad_id):
    return make_generate_fn(
        model, max_new_tokens, temperature=temperature, top_k=top_k,
        top_p=top_p, eos_id=eos_id, pad_id=pad_id)


def generate(model: Transformer, variables, prompt, max_new_tokens: int, *,
             temperature: float = 1.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, eos_id: Optional[int] = None,
             pad_id: int = 0, rng=None):
    """Convenience wrapper around :func:`make_generate_fn` (memoized on the
    static arguments, so repeated calls reuse the compiled program).

    Stochastic sampling (``temperature > 0``) requires an explicit ``rng``
    — a silent default key would make every call return the identical
    "sample".  Greedy decoding (``temperature=0``) needs no rng.
    """
    if rng is None:
        if temperature != 0:
            raise ValueError(
                "temperature > 0 samples stochastically: pass rng="
                "jax.random.PRNGKey(...) (each distinct key gives a "
                "distinct sample)")
        rng = jax.random.PRNGKey(0)
    fn = _cached_fn(model, max_new_tokens, temperature, top_k, top_p,
                    eos_id, pad_id)
    return fn(variables, prompt, rng)
