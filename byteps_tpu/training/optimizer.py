"""DistributedOptimizer — the optax rendering of the reference's
``byteps.torch.DistributedOptimizer`` (torch/__init__.py:98-231) and
``DistributedTrainer`` (mxnet/__init__.py:142-204).

The reference hooks the framework's autograd to push_pull each gradient as
it materializes, then ``synchronize()``s before the optimizer step.  In JAX
the whole step is one traced program, so the same behavior is expressed
compositionally: a gradient transformation that allreduces (bucketed, in
priority order) sits in front of the user's optimizer, and XLA overlaps the
resulting collective chain with the backward compute the same way BytePS's
background threads overlapped NCCL with autograd.

``backward_passes_per_step`` (reference torch/__init__.py:107-154) is
honored via optax.MultiSteps: gradients accumulate locally for k steps and
only the k-th triggers communication — the same wire traffic reduction.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Union

import jax
import optax

from ..common.config import get_config
from ..common.partition import BucketPlan
from ..ops.compression import Compression
from ..parallel.collectives import push_pull_tree


class PushPullState(NamedTuple):
    """No dynamic state; the bucket plan is trace-time static."""


def resolve_compression(compression):
    """Split a compression spec into ``(cast_compressor, ef_tx)``.

    Cast specs (Compressor classes, ``"none"``/``"bf16"``/``"fp16"``)
    ride the collective's ``wire_dtype`` hook unchanged.  Biased registry
    schemes (``"onebit"``/``"topk"``/``"randomk"``/``"int8"``) become an
    ``error_feedback_compress`` transformation chained BEFORE the
    communication — compress after local aggregation, before the wire —
    with the residual living in the optimizer state (donated,
    checkpointable; compression/error_feedback.py).
    """
    if compression is None:
        return Compression.none, None
    if isinstance(compression, str):
        from ..compression import error_feedback_compress, get_scheme

        scheme = get_scheme(compression)
        if scheme.name in ("none", "bf16", "fp16"):
            return getattr(Compression, scheme.name), None
        return Compression.none, error_feedback_compress(scheme)
    # a registry adapter class (ops.compression.Compression.resolve) carries
    # its Scheme: route biased ones to EF exactly like their string
    # spelling — the cast path would silently ignore them (wire_dtype=None)
    scheme = getattr(compression, "scheme", None)
    if scheme is not None and scheme.biased:
        from ..compression import error_feedback_compress

        return Compression.none, error_feedback_compress(scheme)
    return compression, None


def resolve_local_axis(axes: Sequence[str],
                       local_axis: Optional[str]) -> tuple:
    """Split the reduce axes into ``(scatter_axis, sum_axes)`` — the
    hierarchical structure of the 3-level reduction (docs/wire.md
    "Hierarchical reduction"): the *local* axis (ICI — the reference's
    NCCL reduce-scatter group) is scattered over, everything else (DCN /
    the PS tier) is summed on the scattered shard.  Default: the
    innermost (last) axis, the mesh convention.  ``local_axis`` pins it
    explicitly and is validated against the reduce axes — a wrong local
    axis would scatter over the slow tier and sum over the fast one,
    silently inverting the bandwidth argument."""
    axes = tuple(axes)
    if local_axis is None:
        return axes[-1], axes[:-1]
    if local_axis not in axes:
        raise ValueError(
            f"local_axis={local_axis!r} is not one of the reduce axes "
            f"{axes} — the local reduce-scatter must run over a mesh "
            "axis the gradients are reduced across")
    return local_axis, tuple(a for a in axes if a != local_axis)


def sgd_momentum_update(m, g, lr: float, momentum: float):
    """One heavy-ball SGD step on host numpy: ``m' = momentum*m + g``,
    ``delta = -lr*m'`` (the parameter increment).  Returns ``(m', delta)``.

    This is the SINGLE update rule both the replicated baseline and the
    ZeRO-sharded path (training/zero.py) call: it is elementwise, so the
    owner of a parameter span computing it over just that span produces
    bytes bitwise-identical to a replicated client computing the full
    tensor and slicing — the bit-equality contract tests/test_zero.py
    pins.  Keep it numpy (not jnp): the eager PS data path is host-side,
    and both legs must share one arithmetic, not two lowerings of it."""
    m = momentum * m + g
    return m, (-lr) * m


def push_pull_gradients(
    axis_name: Union[str, Sequence[str], None] = "dp",
    average: bool = True,
    compression: type = Compression.none,
    partition_bytes: Optional[int] = None,
    plan: Optional[BucketPlan] = None,
    local_axis: Optional[str] = None,
) -> optax.GradientTransformation:
    """An optax transformation that allreduces incoming gradients across the
    data axes via the bucketed reduce-scatter/all-gather path.

    Must run inside shard_map over a mesh containing ``axis_name`` (the
    innermost/ICI axis is the last element when a sequence is given; leading
    axes — e.g. ``"dcn"`` — are summed hierarchically on the scattered
    shard, reference SURVEY.md §2.4 3-level reduction).  ``local_axis``
    pins which axis hosts the local reduce-scatter stage explicitly
    (validated against the axes — see :func:`resolve_local_axis`).
    ``axis_name=None`` means single-worker: pass-through (the reference
    likewise short-circuits when size()==1).

    ``compression`` accepts cast specs only (class or ``"bf16"``/
    ``"fp16"``); a biased registry scheme needs error-feedback state,
    which this stateless transformation cannot hold — use
    ``DistributedOptimizer(compression="onebit")`` or chain
    ``compression.error_feedback_compress`` in front.
    """
    if isinstance(compression, str):
        cast, ef = resolve_compression(compression)
        if ef is not None:
            raise ValueError(
                f"compression={compression!r} is a biased scheme and needs "
                "error-feedback state; use DistributedOptimizer or chain "
                "byteps_tpu.compression.error_feedback_compress before "
                "push_pull_gradients")
        compression = cast
    cfg = get_config()
    pb = partition_bytes or cfg.effective_partition_bytes
    # compression class wins; else env BYTEPS_WIRE_DTYPE ("bf16"/"fp16")
    wire = getattr(compression, "wire_dtype", None)
    if wire is None:
        wire = cfg.wire_jnp_dtype

    def init_fn(params):
        del params
        return PushPullState()

    def update_fn(updates, state, params=None):
        del params
        if axis_name is None:
            return updates, state
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        scatter, sums = resolve_local_axis(axes, local_axis)
        # single-worker short-circuit (reference does the same when
        # size()==1): with |axes|==1 the collectives are no-ops but the
        # bucket gather/scatter copies are not — skip them entirely.
        world = 1
        for ax in axes:
            world *= jax.lax.psum(1, ax)
        if world == 1:
            return updates, state
        reduced = push_pull_tree(
            updates,
            plan=plan,
            scatter_axis=scatter,
            sum_axes=sums,
            average=average,
            wire_dtype=wire,
            partition_bytes=pb,
        )
        return reduced, state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters: Any = None,  # accepted for API parity; unused in JAX
    compression: Any = Compression.none,  # Compressor class or scheme name
    backward_passes_per_step: int = 1,
    axis_name: Union[str, Sequence[str], None] = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    plan: Optional[BucketPlan] = None,
    local_axis: Optional[str] = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its gradients are push_pulled across
    workers first (reference torch/__init__.py:383-402 factory).

    ``compression`` takes a Compressor class or a registry scheme name
    (docs/compression.md): ``"bf16"``/``"fp16"`` cast the collective
    payload, while ``"onebit"``/``"topk"``/``"randomk"``/``"int8"``
    chain an error-feedback compressor in front of the allreduce (one
    extra chain level in the opt_state, holding the fp32 residual
    pytree).

    ``local_axis`` names the mesh axis hosting the local (ICI)
    reduce-scatter stage of the hierarchical reduction — the
    ``NcclManager`` group of the reference (docs/wire.md "Hierarchical
    reduction").  Default: the innermost of ``axis_name``; an axis not
    in ``axis_name`` raises at build time.

    Usage inside a shard_mapped train step::

        opt = bps.DistributedOptimizer(optax.sgd(0.1), axis_name="dp",
                                       compression="onebit")
        updates, opt_state = opt.update(grads, opt_state, params)
    """
    del named_parameters
    cast, ef_tx = resolve_compression(compression)
    # validate eagerly: a bad local_axis must fail at build time, not
    # from inside the traced update
    if axis_name is not None:
        axes = ((axis_name,) if isinstance(axis_name, str)
                else tuple(axis_name))
        resolve_local_axis(axes, local_axis)
    links = [] if ef_tx is None else [ef_tx]
    links.append(
        push_pull_gradients(
            axis_name=axis_name,
            average=average,
            compression=cast,
            partition_bytes=partition_bytes,
            plan=plan,
            local_axis=local_axis,
        ))
    links.append(optimizer)
    tx = optax.chain(*links)
    if backward_passes_per_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx
