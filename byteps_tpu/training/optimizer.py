"""DistributedOptimizer — the optax rendering of the reference's
``byteps.torch.DistributedOptimizer`` (torch/__init__.py:98-231) and
``DistributedTrainer`` (mxnet/__init__.py:142-204).

The reference hooks the framework's autograd to push_pull each gradient as
it materializes, then ``synchronize()``s before the optimizer step.  In JAX
the whole step is one traced program, so the same behavior is expressed
compositionally: a gradient transformation that allreduces (bucketed, in
priority order) sits in front of the user's optimizer, and XLA overlaps the
resulting collective chain with the backward compute the same way BytePS's
background threads overlapped NCCL with autograd.

``backward_passes_per_step`` (reference torch/__init__.py:107-154) is
honored via optax.MultiSteps: gradients accumulate locally for k steps and
only the k-th triggers communication — the same wire traffic reduction.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Union

import jax
import optax

from ..common.config import get_config
from ..common.partition import BucketPlan
from ..ops.compression import Compression
from ..parallel.collectives import push_pull_tree


class PushPullState(NamedTuple):
    """No dynamic state; the bucket plan is trace-time static."""


def push_pull_gradients(
    axis_name: Union[str, Sequence[str], None] = "dp",
    average: bool = True,
    compression: type = Compression.none,
    partition_bytes: Optional[int] = None,
    plan: Optional[BucketPlan] = None,
) -> optax.GradientTransformation:
    """An optax transformation that allreduces incoming gradients across the
    data axes via the bucketed reduce-scatter/all-gather path.

    Must run inside shard_map over a mesh containing ``axis_name`` (the
    innermost/ICI axis is the last element when a sequence is given; leading
    axes — e.g. ``"dcn"`` — are summed hierarchically on the scattered
    shard, reference SURVEY.md §2.4 3-level reduction).
    ``axis_name=None`` means single-worker: pass-through (the reference
    likewise short-circuits when size()==1).
    """
    cfg = get_config()
    pb = partition_bytes or cfg.effective_partition_bytes
    # compression class wins; else env BYTEPS_WIRE_DTYPE ("bf16"/"fp16")
    wire = getattr(compression, "wire_dtype", None)
    if wire is None:
        wire = cfg.wire_jnp_dtype

    def init_fn(params):
        del params
        return PushPullState()

    def update_fn(updates, state, params=None):
        del params
        if axis_name is None:
            return updates, state
        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        # single-worker short-circuit (reference does the same when
        # size()==1): with |axes|==1 the collectives are no-ops but the
        # bucket gather/scatter copies are not — skip them entirely.
        world = 1
        for ax in axes:
            world *= jax.lax.psum(1, ax)
        if world == 1:
            return updates, state
        reduced = push_pull_tree(
            updates,
            plan=plan,
            scatter_axis=axes[-1],
            sum_axes=axes[:-1],
            average=average,
            wire_dtype=wire,
            partition_bytes=pb,
        )
        return reduced, state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(
    optimizer: optax.GradientTransformation,
    named_parameters: Any = None,  # accepted for API parity; unused in JAX
    compression: type = Compression.none,
    backward_passes_per_step: int = 1,
    axis_name: Union[str, Sequence[str], None] = "dp",
    average: bool = True,
    partition_bytes: Optional[int] = None,
    plan: Optional[BucketPlan] = None,
) -> optax.GradientTransformation:
    """Wrap an optax optimizer so its gradients are push_pulled across
    workers first (reference torch/__init__.py:383-402 factory).

    Usage inside a shard_mapped train step::

        opt = bps.DistributedOptimizer(optax.sgd(0.1), axis_name="dp")
        updates, opt_state = opt.update(grads, opt_state, params)
    """
    del named_parameters
    tx = optax.chain(
        push_pull_gradients(
            axis_name=axis_name,
            average=average,
            compression=compression,
            partition_bytes=partition_bytes,
            plan=plan,
        ),
        optimizer,
    )
    if backward_passes_per_step > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=backward_passes_per_step)
    return tx
