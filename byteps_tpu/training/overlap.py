"""Cross-iteration communication/compute overlap — the ByteScheduler analog.

The reference's ByteScheduler (bytescheduler/torch/optimizer.py) removes the
global barrier between iterations: per-layer forward **pre-hooks block each
layer only until *its own* parameters' push_pull + update finished**
(optimizer.py:180-214), a poller thread applies per-parameter updates as
handles complete (optimizer.py:151-178), so iteration N+1's forward runs
while iteration N's low-priority buckets are still reducing.

TPU rendering: threads and hooks cannot express this (one traced program
per step), but *program structure* can.  ``make_delayed_grad_step`` builds a
step whose gradient collectives consume the **previous** iteration's local
gradients, carried in the train state:

    g_N        = grad(loss)(params_N, batch_N)        # backward compute
    r_{N-1}    = push_pull(pending = g_{N-1})          # collectives: no data
                                                       #  dependency on batch_N!
    params_N+1 = params_N - lr * r_{N-1}               # 1-step-stale update
    pending'   = g_N

Because the collective chain's operands are program *inputs* (state), not
values produced by this step's compute, XLA's latency-hiding scheduler is
free to run the whole reduce concurrently with the forward+backward — the
same overlap ByteScheduler gets from its barrier removal, with the same
bounded staleness (each parameter update lags its gradient by exactly one
iteration; ByteScheduler's lag is sub-iteration but nonzero per layer).
``tests/test_overlap.py`` verifies both the exact staleness semantics and,
via jaxpr dependency analysis, that no collective depends on the batch.

Use ``flush()`` after the loop to apply the final pending gradients (the
analog of ByteScheduler's final-step synchronize, optimizer.py:75-97).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.config import get_config
from ..ops.compression import Compression
from ..parallel.collectives import _axis_size, push_pull_tree, shard_map
from .step import replicate_state


class OverlapState(NamedTuple):
    params: Any
    opt_state: Any
    model_state: Any
    step: jax.Array
    pending: Any  # previous iteration's local (un-reduced) gradients


def make_delayed_grad_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axes: Sequence[str] = ("dp",),
    compression: type = Compression.none,
    partition_bytes: Optional[int] = None,
    donate: bool = True,
):
    """Build the jitted delayed-gradient data-parallel step.

    Same calling convention as ``make_data_parallel_step``
    (``loss_fn(params, model_state, batch) -> (loss, new_model_state)``,
    batch sharded over ``axes``) but with cross-iteration overlap: the
    returned ``DelayedStep`` also exposes ``flush(state)`` to apply the last
    pending gradients after the loop.
    """
    axes = tuple(axes)
    cfg = get_config()
    pb = partition_bytes or cfg.effective_partition_bytes
    wire = getattr(compression, "wire_dtype", None) or cfg.wire_jnp_dtype

    def _reduce_and_update(params, opt_state, pending, world):
        reduced = push_pull_tree(
            pending,
            scatter_axis=axes[-1],
            sum_axes=axes[:-1],
            average=True,
            wire_dtype=wire,
            partition_bytes=pb,
        ) if world > 1 else pending
        updates, new_opt = optimizer.update(reduced, opt_state, params)
        return optax.apply_updates(params, updates), new_opt

    def local_step(state: OverlapState, batch):
        def lf(p):
            return loss_fn(p, state.model_state, batch)

        # this iteration's backward (compute)
        (loss, new_mstate), grads = jax.value_and_grad(lf, has_aux=True)(
            state.params
        )
        n = _axis_size(axes)
        # previous iteration's reduce + update (collectives, independent of
        # `batch` — the overlap invariant; see module docstring)
        new_params, new_opt = _reduce_and_update(
            state.params, state.opt_state, state.pending, n
        )
        loss = jax.lax.psum(loss, axes) / n
        new_mstate = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, axes) / n
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            new_mstate,
        )
        return (
            OverlapState(new_params, new_opt, new_mstate, state.step + 1, grads),
            {"loss": loss},
        )

    def local_flush(state: OverlapState):
        new_params, new_opt = _reduce_and_update(
            state.params, state.opt_state, state.pending, _axis_size(axes)
        )
        zero = jax.tree_util.tree_map(jnp.zeros_like, state.pending)
        return OverlapState(
            new_params, new_opt, state.model_state, state.step, zero
        )

    state_spec = P()
    batch_spec = P(axes)
    jitted = jax.jit(
        shard_map(local_step, mesh, in_specs=(state_spec, batch_spec),
                  out_specs=(state_spec, state_spec)),
        donate_argnums=(0,) if donate else (),
    )
    jitted_flush = jax.jit(
        shard_map(local_flush, mesh, in_specs=(state_spec,),
                  out_specs=state_spec),
        donate_argnums=(0,) if donate else (),
    )
    return DelayedStep(jitted, jitted_flush, optimizer, mesh, local_step)


class DelayedStep:
    """Callable delayed-gradient step; ``flush`` applies the final pending
    gradients (ByteScheduler's end-of-training synchronize)."""

    def __init__(self, fn, flush_fn, tx, mesh, local_fn):
        self._fn = fn
        self._flush = flush_fn
        self.tx = tx
        self.mesh = mesh
        self._local_fn = local_fn  # exposed for jaxpr-level tests

    def __call__(self, state: OverlapState, batch):
        return self._fn(state, batch)

    def flush(self, state: OverlapState) -> OverlapState:
        return self._flush(state)

    def init_state(self, params, model_state=None) -> OverlapState:
        state = OverlapState(
            params=params,
            opt_state=self.tx.init(params),
            model_state=model_state if model_state is not None else {},
            step=jnp.zeros((), jnp.int32),
            pending=jax.tree_util.tree_map(jnp.zeros_like, params),
        )
        return replicate_state(state, self.mesh)

    def lower(self, state, batch):
        return self._fn.lower(state, batch)
