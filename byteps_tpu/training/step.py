"""Train-step factories: the framework's hot path.

The reference's hot path is loss.backward() firing per-gradient hooks that
enqueue push_pull tasks drained by C++ threads (SURVEY.md §3.2).  The TPU
rendering is one traced SPMD program per step: ``shard_map`` over the mesh,
local backward, bucketed priority-ordered push_pull (collectives.py), optax
update — XLA's latency-hiding scheduler overlaps the collective chain with
the backward compute, which is precisely the role of the reference's
10-thread pipeline (core_loops.cc).

``make_data_parallel_step`` is the Horovod-benchmark-equivalent step used by
bench.py and the examples; model-parallel (tp/sp) steps compose GSPMD jit
with these same pieces (see models/transformer.py and __graft_entry__.py).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.config import get_config
from ..ops.compression import Compression
from .optimizer import DistributedOptimizer
from ..parallel.collectives import shard_map


class TrainState(NamedTuple):
    """Functional train state (params + optimizer state + mutable model
    collections such as BatchNorm running stats + step counter)."""

    params: Any
    opt_state: Any
    model_state: Any
    step: jax.Array


def create_train_state(
    params, tx: optax.GradientTransformation, model_state=None
) -> TrainState:
    return TrainState(
        params=params,
        opt_state=tx.init(params),
        model_state=model_state if model_state is not None else {},
        step=jnp.zeros((), jnp.int32),
    )


def _world1_compression_tx(compression) -> Optional[optax.GradientTransformation]:
    """The single-process rendering of a compression spec: a local optax
    transformation reproducing what the scheme does to each worker's
    contribution on a multi-worker wire, so ``world == 1`` sees the same
    gradient numerics as a multi-process run (the world==1 limit of
    "compress, reduce over one worker, decompress").

    Returns None when nothing needs doing (no/none compression) or — with
    a warning — when the spec is genuinely inapplicable: an object that
    is neither a registry scheme name nor a ``compress``/``decompress``
    Compressor, whose wire behavior we cannot reproduce locally.
    """
    from ..ops.compression import Compression as C

    if compression is None or compression is C.none or compression == "none":
        return None
    if isinstance(compression, str):
        from ..compression import (compression_roundtrip,
                                   error_feedback_compress, get_scheme)

        scheme = get_scheme(compression)  # unknown names fail like multi
        if scheme.biased:
            return error_feedback_compress(scheme)
        return compression_roundtrip(scheme)
    if hasattr(compression, "compress") and hasattr(compression,
                                                    "decompress"):
        def update_fn(updates, state, params=None):
            del params

            def one(g):
                c, ctx = compression.compress(g)
                return compression.decompress(c, ctx)

            return jax.tree_util.tree_map(one, updates), state

        return optax.GradientTransformation(
            lambda params: optax.EmptyState(), update_fn)
    from ..common.logging import get_logger

    get_logger().warning(
        "make_data_parallel_step: world size is 1 and compression=%r is "
        "neither a registry scheme name nor a Compressor — it cannot be "
        "applied locally and is dropped; multi-device meshes will reject "
        "it too", compression)
    return None


def make_data_parallel_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axes: Sequence[str] = ("dp",),
    compression: Any = Compression.none,  # Compressor class or scheme name
    partition_bytes: Optional[int] = None,
    backward_passes_per_step: int = 1,
    donate: bool = True,
    local_axis: Optional[str] = None,
):
    """Build a jitted data-parallel train step.

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)`` runs
    on the *local* batch shard.  The returned step function has signature
    ``step(state: TrainState, batch) -> (TrainState, metrics)`` where
    ``batch`` is a pytree whose leaves have the global batch on dim 0
    (sharded over ``axes``), and metrics = {"loss": mean loss}.

    Semantics match the reference benchmark
    (example/pytorch/benchmark_byteps.py): gradients are *averaged* across
    all workers via the bucketed scheduled push_pull; BatchNorm normalizes
    per-replica (torchvision semantics) while running stats are averaged
    across replicas so the state stays replicated.

    ``local_axis`` pins which of ``axes`` hosts the local (ICI)
    reduce-scatter stage of the hierarchical reduction (docs/wire.md
    "Hierarchical reduction"); default: the innermost axis.

    .. note:: At ``world == 1`` (with ``backward_passes_per_step == 1``)
       the DistributedOptimizer wrapper is dropped — matching the
       reference's ``size()==1`` short-circuit — but any ``compression``
       passed is still honored through an equivalent local
       transformation (cast roundtrip, or error-feedback compression for
       biased registry schemes), so single- and multi-process runs see
       the same gradient numerics.  The ``opt_state`` pytree nesting
       still differs from the multi-worker chain, so **checkpoints do
       not transfer between world sizes**.
    """
    axes = tuple(axes)
    world = 1
    for ax in axes:
        world *= mesh.shape[ax]
    if world == 1 and backward_passes_per_step == 1:
        # Single-worker fast path (the reference likewise short-circuits
        # when size()==1): the push_pull wrapper is already a traced no-op
        # at world==1, but its chain nesting in opt_state costs measurable
        # per-call dispatch on small models (~80 us/step through the
        # tunneled runtime) — drop the wrapper, keep the compression
        # numerics (a compressed multi-worker run and its single-worker
        # debug rerun must not silently diverge).
        comp_tx = _world1_compression_tx(compression)
        tx = optimizer if comp_tx is None else optax.chain(comp_tx,
                                                           optimizer)
    else:
        tx = DistributedOptimizer(
            optimizer,
            compression=compression,
            axis_name=axes,
            average=True,
            partition_bytes=partition_bytes or get_config().partition_bytes,
            backward_passes_per_step=backward_passes_per_step,
            local_axis=local_axis,
        )

    def local_step(state: TrainState, batch):
        def lf(p):
            return loss_fn(p, state.model_state, batch)

        (loss, new_mstate), grads = jax.value_and_grad(lf, has_aux=True)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        n = jax.lax.psum(1, axes)
        loss = jax.lax.psum(loss, axes) / n
        # keep mutable model state (BN stats) replicated: average across dp
        new_mstate = jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, axes) / n
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            new_mstate,
        )
        return (
            TrainState(new_params, new_opt, new_mstate, state.step + 1),
            {"loss": loss},
        )

    state_spec = P()  # params/opt state replicated across data axes
    batch_spec = P(axes)
    mapped = shard_map(
        local_step,
        mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, state_spec),
    )
    jitted = jax.jit(mapped, donate_argnums=(0,) if donate else ())
    return TrainStep(jitted, tx, mesh)


class TrainStep:
    """Callable train step bundling the jitted SPMD program with the
    *wrapped* optimizer (DistributedOptimizer chain) whose state layout the
    program expects — use ``init_state`` to build a matching TrainState."""

    def __init__(self, fn, tx: optax.GradientTransformation, mesh: Mesh):
        self._fn = fn
        self.tx = tx
        self.mesh = mesh

    def __call__(self, state, batch):
        return self._fn(state, batch)

    def init_state(self, params, model_state=None) -> TrainState:
        state = create_train_state(params, self.tx, model_state=model_state)
        return replicate_state(state, self.mesh)

    def lower(self, state, batch):
        return self._fn.lower(state, batch)


def make_zero_step(loss_fn, zero, model_state=None, reduce_grads=None):
    """Eager ZeRO-1 train step over the PS tier (training/zero.py).

    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)``
    with ``params`` a flat ``{name: array}`` dict (the replica ``zero``
    holds); the backward pass is jitted, the optimizer/wire half runs
    on the host through ``zero.step`` (push owned span deltas, pull the
    rest — docs/parallel.md).  Returns ``step(batch) -> loss``.

    ``reduce_grads`` maps this worker's raw gradients to the
    group-reduced gradients ``zero.step`` requires (e.g. stacking over
    colocated workers through ``collectives.reduce_scatter_spans``, or
    an allreduce); None means the gradients are already reduced — the
    single-worker / pre-reduced harness case.  Mutable model state is
    not threaded (this is the eager PS path, not
    ``make_data_parallel_step``); pass BN-free losses."""
    import numpy as np

    ms = {} if model_state is None else model_state

    grad_fn = jax.jit(jax.value_and_grad(
        lambda p, b: loss_fn(p, ms, b)[0]))

    def step(batch):
        loss, grads = grad_fn(zero.params, batch)
        g = {n: np.asarray(v) for n, v in grads.items()}
        if reduce_grads is not None:
            g = reduce_grads(g)
        zero.step(g)
        return float(loss)

    return step


def shard_batch(batch, mesh: Mesh, axes: Sequence[str] = ("dp",)):
    """Place a host batch on the mesh, dim 0 sharded over ``axes``."""
    sharding = NamedSharding(mesh, P(tuple(axes)))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch
    )


def replicate_state(state, mesh: Mesh):
    sharding = NamedSharding(mesh, P())
    # Copy committed jax.Arrays before placing: device_put may alias their
    # buffers into the replicated output, and TrainState is donated into the
    # jitted step — without the copy, donation would delete the caller's
    # arrays too.  Host (numpy/scalar) leaves are always copied by
    # device_put itself, so no extra materialization for them.
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            jnp.array(x) if isinstance(x, jax.Array) else x, sharding
        ),
        state,
    )


def lm_loss_fn(model, fused_head: bool = False,
               block_n: Optional[int] = None, block_v: Optional[int] = None,
               early_exit: Optional[tuple] = None):
    """Next-token cross-entropy loss closure for a causal LM whose batch
    is ``{"tokens": [B, T]}``; fits ``make_data_parallel_step``.

    ``early_exit=(layers, weight)`` adds the LayerSkip auxiliary loss:
    ``weight * CE(first-`layers` exit)`` where the exit is the model's
    own ``ln_f`` + head applied to the truncated depth — exactly the
    truncation ``inference.truncated_draft`` builds, so a model trained
    with this term accepts its own truncated self-draft under
    speculative decoding.  Without it the early-exit readout is
    untrained and the draft is useless no matter how well the full
    model converges (measured: acceptance ~0.002 on a converged
    vanilla-trained 12L model vs 0.70-0.88 with the term — see
    bench.py's trained-speculative row).  Requires a
    ``models.transformer.Transformer`` (the truncation slices its
    ``block_i`` param subtree).

    ``fused_head=True`` routes through the Pallas fused LM-head kernel
    (ops/fused_cross_entropy.py): the model's ``hidden`` method supplies
    pre-head states and the ``lm_head`` kernel multiplies inside the
    fused op — the [B, T, vocab] logits never materialize.  The full
    B*T rows go to the kernel (keeping N block-divisible for typical
    sequence lengths); the shift-off last position rides the kernel's
    ignore-index semantics (out-of-range target → loss 0, no grad).
    Requires a model exposing ``hidden`` plus either an ``lm_head``
    Dense or tied embeddings (models/transformer.Transformer, either
    way; for tied models the head weight is the embedding transpose).  ``block_n``/``block_v`` pass
    through to the kernel for vocab/batch sizes its auto-fit cannot
    divide (e.g. GPT-2's 50257).

    Padded streams: pass ``batch["labels"]`` with ``-100`` on ignored
    positions (the HF convention; ``tokens`` keep an embeddable pad id).
    The mean is over *valid* targets — ignored positions contribute
    neither loss nor denominator, in both the fused and plain branches.
    """

    def _head_weight(params, h):
        if "lm_head" in params:
            return params["lm_head"]["kernel"].astype(h.dtype)
        # tied-embedding models (tie_embeddings=True) have no
        # lm_head; the head weight is the embedding transposed.
        # tp-partitioned trees box the leaf in nn.Partitioned.
        import flax.linen as nn

        emb = params["embed"]["embedding"]
        if isinstance(emb, nn.meta.AxisMetadata):
            emb = emb.unbox()
        return emb.T.astype(h.dtype)

    def _fused_ce(params, m, tokens, targets):
        from ..ops.fused_cross_entropy import fused_linear_cross_entropy

        h = m.apply({"params": params}, tokens, method=m.hidden)
        w = _head_weight(params, h)
        B, T, d = h.shape
        V = w.shape[-1]
        flat_t = targets.reshape(-1)
        per_row = fused_linear_cross_entropy(
            h.reshape(-1, d), w, flat_t, block_n, block_v,
        )
        # mean over *valid* targets only: with padded token streams
        # (HF -100 convention) a fixed B*(T-1) denominator deflates
        # the loss; the kernel already zeroes ignored rows
        valid = jnp.sum((flat_t >= 0) & (flat_t < V))
        return per_row.sum() / jnp.maximum(valid, 1).astype(per_row.dtype)

    def _plain_ce(params, m, tokens, targets):
        logits = m.apply({"params": params}, tokens)
        t = targets[:, :-1]
        valid = (t >= 0) & (t < logits.shape[-1])
        # optax's integer-label CE has no ignore-index: out-of-range
        # labels produce garbage — clamp them and zero their loss
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], jnp.where(valid, t, 0)
        )
        per_tok = jnp.where(valid, per_tok, 0.0)
        return per_tok.sum() / jnp.maximum(valid.sum(), 1).astype(
            per_tok.dtype)

    ce = _fused_ce if fused_head else _plain_ce

    def loss_fn(params, model_state, batch):
        tokens = batch["tokens"]
        if "labels" in batch:
            # HF convention: explicit labels with -100 on padded/ignored
            # positions (tokens themselves must stay embeddable pad ids)
            targets = jnp.roll(batch["labels"], -1, axis=1)
        else:
            targets = jnp.roll(tokens, -1, axis=1)
        targets = targets.at[:, -1].set(-100)  # ignore the wrap position
        loss = ce(params, model, tokens, targets)
        if early_exit is not None:
            from ..inference import truncated_draft

            e_layers, e_weight = early_exit
            # truncated_draft only filters the pytree, so it traces
            # cleanly under jit/grad — and it is the SAME truncation
            # speculative_generate runs at decode time, keeping the
            # trained exit and the runtime draft in lockstep
            dmodel, dvars = truncated_draft(
                model.cfg, {"params": params}, e_layers)
            loss = loss + e_weight * ce(
                dvars["params"], dmodel, tokens, targets)
        return loss, model_state

    return loss_fn


def classification_loss_fn(model, train: bool = True, rngs_fn=None):
    """Standard softmax-CE loss closure for a flax vision model with
    (optional) BatchNorm state; fits ``make_data_parallel_step``."""

    def loss_fn(params, model_state, batch):
        images, labels = batch["image"], batch["label"]
        variables = {"params": params, **model_state}
        mutable = list(model_state.keys())
        kwargs = {}
        if rngs_fn is not None:
            kwargs["rngs"] = rngs_fn()
        if mutable:
            logits, new_state = model.apply(
                variables, images, train=train, mutable=mutable, **kwargs
            )
        else:
            logits = model.apply(variables, images, train=train, **kwargs)
            new_state = {}
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        return loss, new_state

    return loss_fn
