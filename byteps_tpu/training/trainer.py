"""High-level training driver — the analog of the reference's Gluon
``DistributedTrainer`` (mxnet/__init__.py:142-204) and the Keras callback
stack: owns the step function, broadcast-at-start, metric averaging,
checkpointing, and the train loop, so user code is just model + data.

Example::

    trainer = Trainer(
        loss_fn=classification_loss_fn(model),
        optimizer=optax.sgd(warmup_schedule(0.1, bps.size(), 500), momentum=0.9),
        checkpoint_dir="/tmp/ckpts",
    )
    trainer.fit(params, model_state, data_iter, steps=1000)
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import optax

import byteps_tpu as bps

from ..common import logging as bps_log
from ..ops.compression import Compression
from .callbacks import average_metrics
from .checkpoint import CheckpointManager
from .step import (
    TrainState,
    make_data_parallel_step,
    replicate_state,
    shard_batch,
)


# Bounded dispatch pipelining depth shared by fit/evaluate: unbounded async
# dispatch of data-dependent steps can starve XLA's collective rendezvous
# (the virtual-CPU harness SIGABRTs); blocking on results from this many
# iterations back keeps the pipeline full while bounding it.
_INFLIGHT_WINDOW = 4


class Trainer:
    def __init__(
        self,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        mesh=None,
        axes: Sequence[str] = ("dp",),
        compression: type = Compression.none,
        backward_passes_per_step: int = 1,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 1000,
        checkpoint_keep: int = 3,
        log_every: int = 100,
        callbacks: Sequence[Callable] = (),
        async_mode: Optional[bool] = None,
        async_store=None,
        async_interval: int = 1,
        worker_id: Optional[int] = None,
        overlap: bool = False,
    ):
        bps.init()
        from ..common.config import get_config

        self.mesh = mesh if mesh is not None else bps.mesh()
        # --- ByteScheduler mode (reference bytescheduler/torch/optimizer.py):
        # cross-iteration comm/compute overlap via the delayed-gradient step
        # (training/overlap.py).  The reference opts in by wrapping the
        # optimizer; here it is a Trainer flag.  fit() flushes the final
        # pending gradients (the analog of ByteScheduler's last-step
        # synchronize, optimizer.py:75-97).
        self.overlap = bool(overlap)
        if self.overlap:
            if async_mode:
                raise ValueError("overlap=True is a synchronous schedule; "
                                 "it cannot combine with async_mode")
            if backward_passes_per_step != 1:
                raise ValueError("overlap=True does not compose with "
                                 "backward_passes_per_step > 1")
            from .overlap import make_delayed_grad_step

            self.step_fn = make_delayed_grad_step(
                loss_fn, optimizer, self.mesh, axes=tuple(axes),
                compression=compression,
            )
        else:
            self.step_fn = make_data_parallel_step(
                loss_fn, optimizer, self.mesh, axes=tuple(axes),
                compression=compression,
                backward_passes_per_step=backward_passes_per_step,
            )
        self.ckpt = (
            CheckpointManager(checkpoint_dir, checkpoint_every, checkpoint_keep)
            if checkpoint_dir else None
        )
        self.log_every = log_every
        self.callbacks = list(callbacks)
        self.state: Optional[TrainState] = None
        # --- async-PS mode (reference BYTEPS_ENABLE_ASYNC,
        # torch/__init__.py:174-189): intra-mesh reduction stays synchronous
        # (the reference's intra-machine NCCL stage does too); *between*
        # workers sharing a store, weight deltas are pushed and global state
        # pulled with no barrier.  Flag precedence: explicit arg > env.
        self.async_mode = (
            get_config().enable_async if async_mode is None else async_mode
        )
        self.async_interval = max(1, async_interval)
        self.worker_id = worker_id if worker_id is not None else bps.rank()
        self._async_worker = None
        if self.async_mode:
            from ..engine.async_ps import get_async_store

            self.async_store = (
                async_store if async_store is not None else get_async_store()
            )
        else:
            self.async_store = None

    # ------------------------------------------------------------------ api

    def init_state(self, params, model_state=None, root_rank: int = 0,
                   resume: bool = True) -> TrainState:
        """Broadcast-consistent init (reference BroadcastGlobalVariables
        semantics), optionally resuming from the latest checkpoint."""
        state = None
        if self.ckpt is not None and resume:
            state = self.step_fn.init_state(params, model_state=model_state)
            restored, step = self.ckpt.restore_latest(template=tuple(state))
            if restored is not None:
                bps_log.info("resuming from checkpoint step %d", step)
                # reconstruct whatever state type the step uses (TrainState,
                # or OverlapState in overlap mode)
                state = type(state)(*restored)
            else:
                state = None
        if state is None:
            params = bps.broadcast_parameters(params, root_rank=root_rank)
            if model_state:
                model_state = bps.broadcast_parameters(model_state, root_rank)
            state = self.step_fn.init_state(params, model_state=model_state)
        if self.async_mode and self._async_worker is None:
            from ..engine.async_ps import AsyncWorker

            # registers + does the first-push-wins initial push (reference
            # InitTensor's blocking initial push, operations.cc:262-284)
            self._async_worker = AsyncWorker(
                self.async_store, jax.device_get(state.params),
                worker_id=self.worker_id,
            )
        return state

    def fit(
        self,
        params,
        model_state,
        batches: Iterable,
        steps: Optional[int] = None,
    ) -> TrainState:
        state = self.state or self.init_state(params, model_state)
        t0 = time.time()
        seen = 0
        # Track the step number on host: reading int(state.step) every
        # iteration would force a device sync per step and serialize the
        # async dispatch pipeline whose overlap is the performance story.
        start_step = int(state.step)
        window = _INFLIGHT_WINDOW
        inflight: list = []
        for i, batch in enumerate(batches):
            if steps is not None and i >= steps:
                break
            batch = shard_batch(batch, self.mesh)
            state, metrics = self.step_fn(state, batch)
            # metrics (not state) goes in the window: state buffers are
            # donated into the next step and blocking on a donated array
            # would raise; metrics data-depends on the full step.
            inflight.append(metrics)
            if len(inflight) > window:
                jax.block_until_ready(inflight.pop(0))
            seen += 1
            step_no = start_step + seen
            if self.ckpt is not None:
                self.ckpt.maybe_save(tuple(state), step_no)
            for cb in self.callbacks:
                maybe = cb(state)
                if maybe is not None:
                    state = maybe
                    # A callback may have replaced state (e.g. rollback) —
                    # resync the host-side counter with the device counter
                    # so checkpoint step numbers stay consistent.
                    start_step = int(state.step) - seen
            if self._async_worker is not None and seen % self.async_interval == 0:
                # Pipelined async-PS exchange (reference torch/__init__.py:
                # 174-189, kept off the critical path): adopt the PREVIOUS
                # interval's pulled global state with the catch-up rule
                # params += pulled - submitted (local progress made while
                # the exchange flew is preserved; see AsyncWorker), then
                # submit this interval's exchange on a non-donated device
                # copy.  The train thread never blocks on device_get.
                state = self._adopt_exchange(state)
                # non-donated copy: the step donates state buffers, so the
                # background thread must not read state.params directly
                self._async_worker.begin_push_pull(
                    jax.tree_util.tree_map(jnp.copy, state.params))
            if self.log_every and seen % self.log_every == 0:
                avg = average_metrics(
                    {k: v for k, v in metrics.items()}
                )
                rate = seen / max(time.time() - t0, 1e-9)
                bps_log.info(
                    "step %d %s (%.2f steps/s)", step_no,
                    {k: round(v, 4) for k, v in avg.items()}, rate,
                )
        if self._async_worker is not None:
            # drain the last in-flight exchange so the returned state
            # reflects the global store
            state = self._adopt_exchange(state)
        if self.overlap:
            # apply the final pending (1-step-stale) gradients
            state = self.step_fn.flush(state)
        self.state = state
        return state

    def close(self) -> None:
        """Release background resources (the async-PS exchange thread,
        which pins a host param snapshot until stopped).  Idempotent."""
        if self._async_worker is not None:
            self._async_worker.close()
            self._async_worker = None

    def _adopt_exchange(self, state):
        """Fold a completed background exchange into the current params:
        ``params += pulled - submitted`` (catch-up rule — see
        AsyncWorker.take_result).  No-op when nothing is in flight."""
        if not self._async_worker.exchange_in_flight():
            return state
        pulled, submitted = self._async_worker.take_result()
        new_params = jax.tree_util.tree_map(
            lambda x, p, s: x + replicate_state(
                jnp.asarray(p - s), self.mesh).astype(x.dtype),
            state.params, pulled, submitted)
        return state._replace(params=new_params)

    def evaluate(self, eval_fn: Callable, batches: Iterable) -> Dict[str, float]:
        """Average ``eval_fn(state, batch) -> {metric: scalar}`` over
        batches and across workers (reference MetricAverageCallback).

        Host reads ride the same bounded in-flight window as ``fit``:
        ``float(v)`` on the newest batch would sync the device per batch
        and serialize dispatch, so summation happens on values from a few
        batches back while newer eval steps are already in flight.
        """
        sums: Dict[str, float] = {}
        n = 0
        window = _INFLIGHT_WINDOW
        inflight: list = []

        def drain(out):
            nonlocal n
            for k, v in out.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            n += 1

        for batch in batches:
            batch = shard_batch(batch, self.mesh)
            inflight.append(eval_fn(self.state, batch))
            if len(inflight) > window:
                drain(inflight.pop(0))
        for out in inflight:
            drain(out)
        means = {k: v / max(n, 1) for k, v in sums.items()}
        return average_metrics(means)
