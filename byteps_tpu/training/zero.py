"""ZeRO-1-style optimizer-state sharding over the PS tier.

The replicated eager PS loop (scripts/chaos_smoke.py, docs/wire.md)
keeps FULL optimizer state on every worker and pushes a FULL gradient
mutation per worker per step.  This module shards both by parameter
*span*: worker ``r`` of a ``world``-sized ownership group

  * holds momentum ONLY for the spans it owns (client optimizer-state
    bytes drop ``world``-fold);
  * computes the optimizer update for those spans client-side and
    pushes just the resulting parameter *delta* as its own
    ``name@z{r}`` wire key (per-step mutation wire bytes drop
    ``world``-fold — pulls are reads, not mutations);
  * pulls the other ranks' updated ``name@z{q}`` spans (one windowed
    ``pull_many`` fan-out) to rebuild its full parameter replica.

The PS tier needs NOTHING new: ``name@z{r}`` is an ordinary wire key,
so partitioning (``#p{i}``), wire compression + error feedback (the
EF residual is keyed per wire name — ``WireCompressor.residual_bytes``
shows it sharding alongside the momentum), version-guard retry dedup,
and failover re-seeding all apply per span for free.  Better: span
ownership RESTORES the single-writer-per-key condition the version
guard needs (docs/resilience.md "Exactly-once retried mutations") even
in multi-worker runs, because exactly one rank ever mutates a given
span key.  The hierarchical layer never re-slices span keys
(``hierarchical.is_sliced_name`` knows ``@z``).

Bit-equality contract: the update rule is
:func:`~byteps_tpu.training.optimizer.sgd_momentum_update` — shared
with the replicated baseline and elementwise — so given identical
reduced gradients, the sharded group's final parameters are
bitwise-identical to a replicated single-worker loop
(tests/test_zero.py).  Gradient reduction itself is out of scope here:
feed grads already summed across data-parallel workers (on-mesh via
``collectives.reduce_scatter_spans``, whose span layout matches
:func:`zero_spans` exactly, or a plain allreduce).

Honest CPU-host caveats: this is the *eager* PS data path — host numpy
math, one wire round trip batch per phase — built to measure and pin
the byte/state accounting (bench_comm.py --zero), not to win
wall-clock on a single host.  See docs/parallel.md.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.config import get_config
from .optimizer import sgd_momentum_update

ZERO_SEP = "@z"


def zero_key(name: str, rank: int) -> str:
    """Wire key of ``name``'s span ``rank`` — an ordinary PS tensor."""
    return f"{name}{ZERO_SEP}{rank}"


def zero_spans(n: int, world: int) -> List[Tuple[int, int]]:
    """``[(start, stop)]`` flat spans of the ``world`` ownership chunks
    of an ``n``-element tensor: equal ``ceil(n/world)`` chunks, ragged
    (possibly empty) tail — the same layout ``lax.psum_scatter`` /
    ``collectives.reduce_scatter_spans`` yield, so an on-mesh gradient
    reduce-scatter drops each rank's summed gradient span exactly on
    its owner.  Unlike ``hierarchical.slice_spans`` empty tail spans
    are allowed: an empty span simply has no wire key (every rank
    derives the same span table, so nobody ever asks for one)."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    c = -(-n // world) if n else 0
    return [(min(r * c, n), min((r + 1) * c, n)) for r in range(world)]


def make_optimizer_state(store, params: Dict[str, np.ndarray], **kw):
    """Config-driven factory: ``BYTEPS_ZERO=1`` (``Config.zero``) picks
    :class:`ShardedOptimizerState`, otherwise the replicated baseline —
    so a training loop opts into ZeRO with an env knob, no code change
    (docs/parallel.md)."""
    if get_config().zero:
        return ShardedOptimizerState(store, params, **kw)
    kw.pop("world", None)
    kw.pop("rank", None)
    return ReplicatedOptimizerState(store, params, **kw)


class ShardedOptimizerState:
    """Client half of the ZeRO-1 sharding: one instance per worker.

    ``params`` is a ``{name: array}`` dict (the full replica every
    worker keeps for the forward/backward pass — ZeRO-1 shards
    optimizer state, not parameters).  ``store`` is any RemoteStore-
    shaped client (``init_tensor``/``push_delta``/``pull``, optionally
    ``pull_many``).

    Step protocol (split-phase, so a caller can overlap compute):

      1. ``push_updates(grads)`` — for every owned non-empty span:
         momentum update via the shared ``sgd_momentum_update``, push
         the parameter delta to the span's wire key, fold it into the
         local replica.
      2. ``pull_params()`` — one fan-out pull of every NON-owned span
         key, folded into the local replica; returns the params dict.

    ``step(grads)`` does both.  ``state_bytes()`` is the client
    optimizer-state footprint the tests/bench pin (momentum only —
    the params replica is identical in both legs by design).
    """

    def __init__(self, store, params: Dict[str, np.ndarray], *,
                 world: int = 0, rank: Optional[int] = None,
                 lr: float = 0.01, momentum: float = 0.9,
                 init: bool = True):
        cfg = get_config()
        self.store = store
        # world=0 defers to the BYTEPS_ZERO_WORLD knob, then the DMLC
        # worker count — the launcher-injected group size
        self.world = (int(world) or int(getattr(cfg, "zero_world", 0))
                      or max(1, cfg.num_worker))
        self.rank = int(cfg.worker_id if rank is None else rank)
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"rank {self.rank} outside the ownership group "
                f"[0, {self.world})")
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.params: Dict[str, np.ndarray] = {}
        self._spans: Dict[str, List[Tuple[int, int]]] = {}
        self._m: Dict[str, np.ndarray] = {}  # momentum, OWNED spans only
        for name, value in params.items():
            if ZERO_SEP in name:
                raise ValueError(
                    f"parameter name {name!r} contains the reserved "
                    f"ZeRO span marker {ZERO_SEP!r}")
            arr = np.ascontiguousarray(np.asarray(value))
            self.params[name] = arr
            self._spans[name] = zero_spans(arr.size, self.world)
            a, b = self._spans[name][self.rank]
            if b > a:
                self._m[name] = np.zeros(b - a, arr.dtype)
        if init:
            self._init_store()

    def _init_store(self) -> None:
        """INIT every non-empty span key with the initial parameter
        bytes.  First-push-wins on the server, so every rank seeding
        all keys with identical values is idempotent — and each INIT
        reply primes the client's failover seed (``_last_global``), so
        a mid-run shard death can re-home any span from any worker."""
        for name, arr in self.params.items():
            flat = arr.reshape(-1)
            for r, (a, b) in enumerate(self._spans[name]):
                if b > a:
                    self.store.init_tensor(zero_key(name, r), flat[a:b])

    # ------------------------------------------------------------- step

    def push_updates(self, grads: Dict[str, np.ndarray]) -> None:
        """Phase 1: momentum-update the OWNED span of every gradient,
        push the resulting parameter delta as this rank's span key, and
        fold it into the local replica.  ``grads`` must be the
        already-reduced (summed over data-parallel workers) gradients;
        extra names raise — a silently ignored gradient would freeze
        its parameter while the loss keeps moving."""
        for name, g in grads.items():
            if name not in self.params:
                raise KeyError(f"unknown parameter {name!r}")
            a, b = self._spans[name][self.rank]
            if b <= a:
                continue  # tensor smaller than the group: no owned span
            arr = self.params[name]
            gspan = np.ascontiguousarray(
                np.asarray(g, arr.dtype).reshape(-1)[a:b])
            self._m[name], delta = sgd_momentum_update(
                self._m[name], gspan, self.lr, self.momentum)
            self.store.push_delta(zero_key(name, self.rank), delta)
            arr.reshape(-1)[a:b] += delta

    def pull_params(self) -> Dict[str, np.ndarray]:
        """Phase 2: pull every NON-owned span key (one windowed fan-out
        when the store supports ``pull_many``) and fold the owners'
        updated bytes into the local replica."""
        keys = []
        for name in self.params:
            keys.extend(
                (name, q, a, b)
                for q, (a, b) in enumerate(self._spans[name])
                if q != self.rank and b > a)
        wire = [zero_key(name, q) for name, q, _, _ in keys]
        pull_many = getattr(self.store, "pull_many", None)
        if pull_many is not None:
            pulled = pull_many(wire)
        else:  # duck-typed store: serial pulls
            pulled = {k: self.store.pull(k) for k in wire}
        for (name, q, a, b), k in zip(keys, wire):
            arr = self.params[name]
            span = np.asarray(pulled[k], arr.dtype).reshape(-1)
            if span.size != b - a:
                raise ValueError(
                    f"span {k!r} came back with {span.size} elements, "
                    f"expected {b - a} — ownership tables disagree "
                    f"across the group (mismatched world sizes?)")
            arr.reshape(-1)[a:b] = span
        return self.params

    def step(self, grads: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """``push_updates`` then ``pull_params`` — one training step.

        Bit-equality at ``world > 1`` requires every rank's
        ``push_updates`` for step N to land before any rank's
        ``pull_params`` for step N reads its spans.  In a real
        deployment the per-step gradient collective provides that
        ordering; when simulating several ranks in one process, drive
        the two phases explicitly (push all ranks, then pull all
        ranks) instead of calling ``step`` rank-by-rank."""
        self.push_updates(grads)
        return self.pull_params()

    # ------------------------------------------------------ accounting

    def state_bytes(self) -> int:
        """Client optimizer-state bytes held (momentum spans): the
        number that must drop ``~world``-fold vs a replicated client
        (ISSUE 20 acceptance: >= 1.8x at world=2)."""
        return sum(int(m.nbytes) for m in self._m.values())

    def owned_spans(self) -> Dict[str, Tuple[int, int]]:
        """``{name: (start, stop)}`` of this rank's non-empty spans."""
        out = {}
        for name, spans in self._spans.items():
            a, b = spans[self.rank]
            if b > a:
                out[name] = (a, b)
        return out


class ReplicatedOptimizerState:
    """The A/B baseline: FULL momentum client-side, FULL parameter-
    delta mutation per step, one ordinary wire key per tensor — the
    pre-ZeRO eager PS loop, behind the same split-phase API so the
    bench/tests drive both legs with one harness.  Uses the same
    ``sgd_momentum_update`` rule, so a ``world=1`` sharded group and
    this baseline are bitwise-identical by construction."""

    def __init__(self, store, params: Dict[str, np.ndarray], *,
                 lr: float = 0.01, momentum: float = 0.9,
                 init: bool = True):
        self.store = store
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.params = {n: np.ascontiguousarray(np.asarray(v))
                       for n, v in params.items()}
        self._m = {n: np.zeros(v.size, v.dtype)
                   for n, v in self.params.items()}
        if init:
            for name, arr in self.params.items():
                store.init_tensor(name, arr.reshape(-1))

    def push_updates(self, grads: Dict[str, np.ndarray]) -> None:
        for name, g in grads.items():
            arr = self.params[name]
            gflat = np.ascontiguousarray(
                np.asarray(g, arr.dtype).reshape(-1))
            self._m[name], delta = sgd_momentum_update(
                self._m[name], gflat, self.lr, self.momentum)
            self.store.push_delta(name, delta)
            arr.reshape(-1)[:] += delta

    def pull_params(self) -> Dict[str, np.ndarray]:
        return self.params

    def step(self, grads):
        self.push_updates(grads)
        return self.pull_params()

    def state_bytes(self) -> int:
        return sum(int(m.nbytes) for m in self._m.values())
