"""byteps_tpu.training — DistributedOptimizer, trainer, async-PS mode,
callbacks."""

from .optimizer import DistributedOptimizer, push_pull_gradients
from .overlap import OverlapState, make_delayed_grad_step
from .trainer import Trainer
from .step import (
    TrainState,
    classification_loss_fn,
    lm_loss_fn,
    create_train_state,
    make_data_parallel_step,
    make_zero_step,
    replicate_state,
    shard_batch,
)
from .zero import (ReplicatedOptimizerState, ShardedOptimizerState,
                   make_optimizer_state)

__all__ = [
    "DistributedOptimizer", "push_pull_gradients",
    "TrainState", "create_train_state", "make_data_parallel_step",
    "shard_batch", "replicate_state", "classification_loss_fn", "lm_loss_fn",
    "OverlapState", "make_delayed_grad_step", "Trainer",
    "make_zero_step", "ShardedOptimizerState", "ReplicatedOptimizerState",
    "make_optimizer_state",
]
