"""byteps_tpu.training — DistributedOptimizer, trainer, async-PS mode,
callbacks."""

from .optimizer import DistributedOptimizer, push_pull_gradients

__all__ = ["DistributedOptimizer", "push_pull_gradients"]
