"""Checkpoint / resume.

The reference delegates checkpointing to the frameworks and only supplies
the *consistency* half: ``broadcast_parameters`` / ``broadcast_optimizer_state``
so every worker resumes from the root's state (SURVEY.md §5
"Checkpoint / resume"; torch/__init__.py:234-381, keras/callbacks.py:28-31).

The TPU rebuild owns the whole story: orbax-backed save/restore of the
functional TrainState plus the same broadcast-on-resume contract —
``restore_checkpoint(..., broadcast=True)`` replicates every leaf across the
mesh exactly like the reference's zero-non-root + push_pull trick did.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np

from ..common import logging as bps_log


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(path: str, state: Any, force: bool = True) -> str:
    """Save a pytree (TrainState or any params tree) to ``path``.

    Multi-host: only process 0 writes (the reference's root-centric model);
    call on every process — non-roots no-op.
    """
    path = os.path.abspath(path)
    if jax.process_index() != 0:
        return path
    # orbax wants fully-addressable host arrays
    host_state = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if hasattr(x, "dtype") else x, state
    )
    _checkpointer().save(path, host_state, force=force)
    bps_log.info("checkpoint saved to %s", path)
    return path


def restore_checkpoint(
    path: str,
    template: Any = None,
    broadcast: bool = True,
    root_rank: int = 0,
) -> Any:
    """Restore a pytree from ``path``.

    ``template`` (same structure, for dtype/shape guidance) is optional.
    With ``broadcast=True`` the restored tree is pushed through
    ``broadcast_parameters`` so every worker/device holds the root's bytes —
    the reference's resume-consistency contract.
    """
    path = os.path.abspath(path)

    def _load():
        if template is not None:
            return _checkpointer().restore(path, item=template)
        return _checkpointer().restore(path)

    if jax.process_count() > 1:
        # save_checkpoint writes only on process 0: process 0 is therefore
        # always the loader, and the broadcast sources from it regardless
        # of root_rank (the reference's root-loads-then-broadcast pattern)
        root_rank = 0
        if jax.process_index() == root_rank:
            restored = _load()
        else:
            try:
                restored = _load()
            except Exception:
                if template is None:
                    raise FileNotFoundError(
                        f"checkpoint {path} not readable on process "
                        f"{jax.process_index()} and no template given; "
                        "multi-host restore without a shared filesystem "
                        "requires template="
                    )
                if not broadcast:
                    # without the broadcast the template (fresh init) would
                    # silently diverge from the root's restored state
                    raise RuntimeError(
                        f"checkpoint {path} not readable on process "
                        f"{jax.process_index()} and broadcast=False: "
                        "cannot fall back to the template without diverging "
                        "from the root — pass broadcast=True or make the "
                        "checkpoint readable on every host"
                    )
                restored = template
        if not broadcast:
            return restored
        import byteps_tpu as bps

        return bps.broadcast_parameters(restored, root_rank=root_rank)

    restored = _load()
    if broadcast:
        import byteps_tpu as bps

        restored = bps.broadcast_parameters(restored, root_rank=root_rank)
    return restored


class CheckpointManager:
    """Rolling checkpoint manager (keep last k, save every n steps)."""

    def __init__(self, directory: str, save_every: int = 1000, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.save_every = max(1, save_every)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def steps(self):
        out = []
        if not os.path.isdir(self.directory):
            return out
        for d in os.listdir(self.directory):
            if d.startswith("step_"):
                try:
                    out.append(int(d[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def maybe_save(self, state: Any, step: int) -> Optional[str]:
        if step % self.save_every != 0:
            return None
        path = save_checkpoint(self._step_dir(step), state)
        if jax.process_index() == 0:
            for old in self.steps()[: -self.keep] if self.keep > 0 else []:
                import shutil

                shutil.rmtree(self._step_dir(old), ignore_errors=True)
        return path

    def restore_latest(self, template: Any = None, broadcast: bool = True):
        steps = self.steps()
        if not steps:
            return None, -1
        step = steps[-1]
        return (
            restore_checkpoint(self._step_dir(step), template, broadcast),
            step,
        )
