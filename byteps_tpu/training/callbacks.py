"""Training callbacks / schedules — the Keras-callback surface of the
reference (_keras/callbacks.py:21-171, keras/callbacks.py) re-expressed for
a JAX training loop:

  * ``BroadcastGlobalVariablesCallback``  -> ``broadcast_parameters`` at
    step 0 (consistent init / checkpoint resume);
  * ``MetricAverageCallback``             -> ``average_metrics`` (push_pull
    of metric values across workers at epoch end);
  * ``LearningRateScheduleCallback`` and ``LearningRateWarmupCallback`` ->
    optax schedules via ``warmup_schedule`` / ``scaled_lr`` with the same
    momentum-correction option the reference applies when the LR changes
    mid-run (_keras/callbacks.py:116-171).

The linear-scaling + warmup recipe (Goyal et al.) is what the reference's
warmup callback implements: lr ramps from ``initial_lr`` to
``initial_lr * size()`` over ``warmup_epochs``.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax


def scaled_lr(base_lr: float, world_size: int) -> float:
    """Linear LR scaling with worker count (reference docstring advice in
    _keras/callbacks.py:84-96)."""
    return base_lr * world_size


def warmup_schedule(
    base_lr: float,
    world_size: int,
    warmup_steps: int,
    after: Optional[optax.Schedule] = None,
) -> optax.Schedule:
    """LR warmup from ``base_lr`` to ``base_lr * world_size`` over
    ``warmup_steps`` (reference LearningRateWarmupCallback semantics:
    gradual ramp to the scaled rate), then hand off to ``after`` (default:
    constant scaled rate)."""
    peak = scaled_lr(base_lr, world_size)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        frac = jnp.clip(step / jnp.maximum(warmup_steps, 1), 0.0, 1.0)
        warm = base_lr + (peak - base_lr) * frac
        if after is None:
            return warm
        return jnp.where(step < warmup_steps, warm, after(step - warmup_steps))

    return schedule


def multiplier_schedule(
    base_lr: float, multipliers: Dict[int, float]
) -> optax.Schedule:
    """Staircase schedule from {start_epoch_step: multiplier} — the
    reference's ``LearningRateScheduleCallback`` with ``staircase=True``
    (_keras/callbacks.py:98-140)."""
    boundaries = sorted(multipliers)

    def schedule(step):
        step = jnp.asarray(step, jnp.int32)
        mult = jnp.asarray(1.0, jnp.float32)
        for b in boundaries:
            mult = jnp.where(step >= b, jnp.asarray(multipliers[b], jnp.float32), mult)
        return base_lr * mult

    return schedule


def momentum_corrected_sgd(
    schedule: optax.Schedule, momentum: float = 0.9
) -> optax.GradientTransformation:
    """SGD whose momentum buffer is rescaled when the LR changes — the
    reference's ``momentum_correction`` (_keras/callbacks.py:143-171):
    on an LR change from lr0 to lr1 the velocity is multiplied by lr1/lr0 so
    the effective update magnitude tracks the new rate immediately."""

    def init_fn(params):
        return {
            "trace": jax.tree_util.tree_map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
            "prev_lr": jnp.asarray(schedule(0), jnp.float32),
        }

    def update_fn(updates, state, params=None):
        del params
        lr = jnp.asarray(schedule(state["step"]), jnp.float32)
        correction = lr / jnp.maximum(state["prev_lr"], 1e-30)
        new_trace = jax.tree_util.tree_map(
            lambda t, g: t * momentum * correction + g, state["trace"], updates
        )
        out = jax.tree_util.tree_map(lambda t: -lr * t, new_trace)
        return out, {
            "trace": new_trace,
            "step": state["step"] + 1,
            "prev_lr": lr,
        }

    return optax.GradientTransformation(init_fn, update_fn)


def average_metrics(metrics: Dict[str, Union[float, jax.Array]]) -> Dict[str, float]:
    """Average scalar metrics across workers at epoch end — the reference's
    ``MetricAverageCallback`` (_keras/callbacks.py:36-70, push_pull of
    metric variables).

    Multi-process runs average the *process-local* scalars across processes
    (each host computed its metric from its own data shard); single-process
    metrics are already global (the step program psums over the mesh), so
    this is the identity there.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        keys = sorted(metrics)
        local = np.asarray(
            [float(metrics[k]) for k in keys], dtype=np.float32
        )
        summed = multihost_utils.process_allgather(local).sum(axis=0)
        return {
            k: float(summed[i]) / jax.process_count()
            for i, k in enumerate(keys)
        }
    return {k: float(jnp.asarray(v, jnp.float32)) for k, v in metrics.items()}


class BroadcastGlobalVariablesCallback:
    """Callable hook: at the first step, broadcast params/opt state from the
    root so every worker starts identically (reference
    keras/callbacks.py:28-31 — also the checkpoint-resume path)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self._done = False

    def __call__(self, state):
        if self._done:
            return state
        import byteps_tpu as bps

        self._done = True
        return bps.broadcast_parameters(state, root_rank=self.root_rank)
