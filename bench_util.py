"""Shared helpers for the repo-root ``bench_*`` scripts.

Deliberately free of jax/numpy imports: the bench scripts set platform
env vars BEFORE importing jax, so anything they import first must not
touch a backend.
"""

from __future__ import annotations

import json


def archive_rows(rows, path, legacy_keys=()):
    """Merge ``rows`` into the JSON archive at ``path``, keyed by each
    row's ``metric`` name: a rerun replaces its own metrics' rows and
    leaves every other archived row untouched.  ``legacy_keys`` are
    pre-archive-era whole-file keys to drop — they were overwritten per
    run (never merged), so anything left is one stale snapshot that
    would sit beside the authoritative rows forever."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    for legacy in legacy_keys:
        doc.pop(legacy, None)
    new_metrics = {r["metric"] for r in rows}
    doc["rows"] = [r for r in doc.get("rows", [])
                   if r.get("metric") not in new_metrics] + rows
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"archived {len(rows)} rows -> {path}", flush=True)
