"""Paged KV cache (serving/blocks.py + the engine's paged mode).

THE parity anchor: a paged engine — block-granular slot memory, lazy
block grants, zero-copy prefix sharing, preemption under pressure —
must emit token-identical streams to sequential ``generate()`` (and so
to the dense engine, which pins the same baselines in
tests/test_serving.py), greedy AND seeded, including prefix-share and
chunked-prefill interleavings and across a preempt/resume cycle.  The
gather moves bytes and computes nothing, so parity is by construction;
these tests pin it bit-for-bit.

Zero-copy acceptance: on a paged engine prefix hits bump refcounts —
the ``prefix_copy``/``prefix_extract`` compile counters must stay 0
(no copy program even exists to run).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate
from byteps_tpu.models.transformer import Transformer, TransformerConfig
from byteps_tpu.serving import (
    PagedSlotPool,
    ServeMetrics,
    ServingEngine,
)
from byteps_tpu.serving import metrics as sm

M = 8  # tokens per request, shared so generate() compiles once per mode


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), toks)
    return cfg, model, variables


@pytest.fixture(scope="module")
def prompts():
    return [np.asarray(jax.random.randint(
        jax.random.PRNGKey(10 + i), (5 + i,), 0, 61), np.int32)
        for i in range(4)]


@pytest.fixture(scope="module")
def greedy_base(tiny, prompts):
    _, model, variables = tiny
    return [np.asarray(generate(model, variables, p[None], M,
                                temperature=0.0)["tokens"])[0]
            for p in prompts]


@pytest.fixture(scope="module")
def paged_eng(tiny):
    _, model, variables = tiny
    return ServingEngine(model, variables, n_slots=4, max_seq=64,
                         temperature=0.0, paged=True, block=8,
                         metrics=ServeMetrics())


# ------------------------------------------------------------- pool wiring


def test_paged_pool_validation_and_sizing(tiny):
    cfg, _, _ = tiny
    # max_seq must be block-aligned (gathered row == dense row shape)
    with pytest.raises(ValueError, match="multiple of"):
        PagedSlotPool(cfg, 2, 60, block=8)
    # the pool must fit one max-length request + the null block
    with pytest.raises(ValueError, match="too small"):
        PagedSlotPool(cfg, 2, 64, block=8, n_blocks=8)
    # kv_quant has no paged path (traced-position int8 reads)
    with pytest.raises(ValueError, match="dense"):
        PagedSlotPool(cfg, 2, 64, block=8, kv_quant=True)
    # byte budget -> block count, dense-equivalent default
    pool = PagedSlotPool(cfg, 2, 64, block=8)
    assert pool.max_blocks == 8
    assert pool.alloc.n_blocks == 2 * 8 + 1  # dense-equivalent + null
    assert pool.caches[0]["k"].shape == (17, 8, cfg.kv_heads, cfg.d_head)
    budget = PagedSlotPool(cfg, 2, 64, block=8,
                           kv_bytes=12 * pool.block_bytes)
    assert budget.alloc.n_blocks == 12
    assert budget.null_block == 0 and budget.alloc.refs(0) == 1
    st = budget.block_stats()
    assert st["free"] == 11 and st["used"] == 1 and st["shared"] == 0


# ------------------------------------------------------------------ parity


def test_paged_greedy_parity_and_lazy_block_growth(tiny, prompts,
                                                   greedy_base, paged_eng):
    """4 concurrent requests on the paged engine are bit-identical to
    sequential generate(), and blocks are granted lazily: the pool's
    usage peaks at actual usage, never n_slots * max_blocks."""
    eng = paged_eng
    reqs = [eng.submit(p, M) for p in prompts]
    peak = 0
    for _ in range(64):
        eng.step()
        peak = max(peak, eng.pool.alloc.used_count)
        if all(r.done for r in reqs):
            break
    for r, b in zip(reqs, greedy_base):
        np.testing.assert_array_equal(r.result(), b)
    # lazy grants: prompts are 5-8 tokens + M=8 new -> 2-3 blocks each
    # of 8 logical (a dense-equivalent pool would hold 32 + null)
    assert peak <= 1 + 4 * 3, peak
    assert eng.pool.alloc.used_count == 1  # everything reclaimed (null)


def test_paged_staggered_arrivals_and_compile_stability(tiny, prompts,
                                                        greedy_base,
                                                        paged_eng):
    eng = paged_eng
    r0 = eng.submit(prompts[0], M)
    eng.step()
    r1 = eng.submit(prompts[1], M)
    eng.step()
    r2 = eng.submit(prompts[2], M)
    eng.drain(timeout=120)
    for r, b in zip([r0, r1, r2], greedy_base):
        np.testing.assert_array_equal(r.result(), b)
    counts = eng.compile_counts()
    # the pos-capped gather compiles one decode program per block
    # high-water bucket touched (never more than O(log max_blocks));
    # these prompts grow through buckets {1, 2} of the 8-block table
    assert counts["decode"] == counts["decode_buckets"], counts
    assert 1 <= counts["decode_buckets"] <= 2, counts
    assert counts["prefix_copy"] == 0 and counts["prefix_extract"] == 0
    # steady state: a second wave over the same depths compiles NOTHING
    # new — decode, chunk, or gather-width buckets
    r3 = eng.submit(prompts[0], M)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(r3.result(), greedy_base[0])
    assert eng.compile_counts() == counts


def test_paged_seeded_parity(tiny, prompts):
    """Seeded sampling through the paged engine replays generate()'s
    exact key chain — the same anchor the dense engine pins."""
    _, model, variables = tiny
    p = prompts[0]
    base = np.asarray(generate(
        model, variables, p[None], M, temperature=0.8, top_k=20,
        rng=jax.random.PRNGKey(100))["tokens"])[0]
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        temperature=0.8, top_k=20, paged=True, block=8,
                        metrics=ServeMetrics())
    req = eng.submit(p, M, seed=100)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(req.result(), base)


# ------------------------------------------------- zero-copy prefix share


def test_prefix_hit_shares_blocks_zero_copy(tiny):
    """A prefix hit on the paged engine is refcount bumps: the admitted
    slot's table adopts the store's blocks, no device-side K/V copy
    happens for whole shared blocks (prefix_copy/prefix_extract compile
    counters pinned at 0), and the token streams stay bit-identical to
    generate() — chunked prefill resuming at the shared boundary."""
    _, model, variables = tiny
    shared = np.asarray(jax.random.randint(
        jax.random.PRNGKey(7), (16,), 0, 61), np.int32)
    pA = np.concatenate([shared, np.asarray([3, 9, 4], np.int32)])
    pB = np.concatenate([shared, np.asarray([11, 2], np.int32)])
    base = [np.asarray(generate(model, variables, p[None], M,
                                temperature=0.0)["tokens"])[0]
            for p in (pA, pB)]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, paged=True, block=8, chunk=8,
                        prefix_cache=True, metrics=ServeMetrics())
    rA = eng.submit(pA, M)
    eng.drain(timeout=120)
    # A's own blocks are now store-referenced (insert = refcount bumps)
    assert eng.prefix.entry_count == 1
    assert eng.metrics.get(sm.PREFIX_INSERTIONS) == 1
    rB = eng.submit(pB, M)
    eng.step()  # admission: B's table adopts the shared blocks
    assert eng.pool.alloc.shared_count() >= 2  # 16 tokens / 8 block
    eng.drain(timeout=120)
    np.testing.assert_array_equal(rA.result(), base[0])
    np.testing.assert_array_equal(rB.result(), base[1])
    counts = eng.compile_counts()
    assert counts["prefix_copy"] == 0, counts      # zero-copy: no copy
    assert counts["prefix_extract"] == 0, counts   # program ever ran
    assert counts["block_cow"] == 0, counts        # aligned: no forks
    assert eng.metrics.get(sm.PREFIX_HITS) == 1
    assert eng.metrics.get(sm.PREFIX_HIT_TOKENS) == 16
    # a paged engine refuses a foreign store (block ids are pool-local)
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(model, variables, n_slots=1, max_seq=64,
                      paged=True, block=8, prefix_cache=eng.prefix,
                      metrics=ServeMetrics())
    # ...and a DENSE engine refuses a paged store (its entries are
    # block ids, not row buffers — it would die on first insert/hit)
    with pytest.raises(ValueError, match="dense"):
        ServingEngine(model, variables, n_slots=1, max_seq=64,
                      prefix_cache=eng.prefix, metrics=ServeMetrics())


# ------------------------------------------------------------- preemption


def _preempt_prompts():
    pA = np.asarray(jax.random.randint(
        jax.random.PRNGKey(2), (19,), 0, 61), np.int32)
    pB = np.asarray(jax.random.randint(
        jax.random.PRNGKey(3), (18,), 0, 61), np.int32)
    return pA, pB


def test_preemption_under_block_pressure_greedy(tiny):
    """Two requests whose combined K/V exceeds the block pool: the
    newest is preempted back to QUEUED (never deadlocked), waits out
    the pressure, resumes by re-prefill, and BOTH streams stay
    bit-identical to generate().  Tokens emitted before the preemption
    are kept — consumers see a stall, never a replay."""
    _, model, variables = tiny
    pA, pB = _preempt_prompts()
    m = 30  # each needs ~7 of the pool's 8 usable blocks
    base = [np.asarray(generate(model, variables, p[None], m,
                                temperature=0.0)["tokens"])[0]
            for p in (pA, pB)]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, paged=True, block=8,
                        kv_blocks=9, metrics=ServeMetrics())
    r0 = eng.submit(pA, m)
    r1 = eng.submit(pB, m)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(r0.result(), base[0])
    np.testing.assert_array_equal(r1.result(), base[1])
    # preempted exactly once: the re-admission watermark keeps the
    # victim QUEUED until its need fits (no preempt/re-prefill thrash)
    assert eng.metrics.get(sm.PREEMPTIONS) == 1
    assert eng.pool.alloc.used_count == 1  # all blocks reclaimed


@pytest.mark.slow
def test_preemption_under_block_pressure_seeded(tiny):
    """Slow sibling of the greedy preemption test above (sampling-path
    compile; tier-1 duration budget).
    The preempt/resume cycle preserves the per-request sampling key
    chain: the resume prefill's sampled token and key split are
    discarded, the parked token + carried key continue the stream —
    seeded output identical to an unpreempted generate()."""
    _, model, variables = tiny
    pA, pB = _preempt_prompts()
    m = 30
    base = [np.asarray(generate(
        model, variables, p[None], m, temperature=0.8, top_k=20,
        rng=jax.random.PRNGKey(40 + i))["tokens"])[0]
        for i, p in enumerate((pA, pB))]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.8, top_k=20, paged=True, block=8,
                        kv_blocks=9, metrics=ServeMetrics())
    r0 = eng.submit(pA, m, seed=40)
    r1 = eng.submit(pB, m, seed=41)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(r0.result(), base[0])
    np.testing.assert_array_equal(r1.result(), base[1])
    assert eng.metrics.get(sm.PREEMPTIONS) >= 1


def test_pressure_evicts_prefix_store_before_preempting(tiny):
    """Cached-but-unreferenced prefixes are the cheapest memory under
    block pressure: a request whose need exceeds the free pool evicts
    the store's LRU entries (bumping serve.block_evictions) and
    completes — preemption and failure are later resorts.  (A lone
    max-length request can ALWAYS complete: the pool floor at
    construction guarantees max_blocks + null, and the store is
    evictable; the typed-failure branch is defense-in-depth.)"""
    _, model, variables = tiny
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        temperature=0.0, paged=True, block=8,
                        kv_blocks=9, prefix_cache=True,
                        metrics=ServeMetrics())
    # fill the store so its entries pin blocks, then retire the slot:
    # the pressure path must evict the store BEFORE failing anything
    warm = eng.submit(np.arange(16, dtype=np.int32) % 61, 2)
    eng.drain(timeout=60)
    assert len(warm.result()) == 2
    assert eng.prefix.entry_count == 1
    # 20 + 44 = 64 positions = all 8 usable blocks: fits only after
    # the store's 2 blocks are pressure-evicted (a DISJOINT prompt —
    # sharing the warm prefix would sidestep the pressure)
    big = eng.submit((np.arange(20, dtype=np.int32) + 23) % 61, 44)
    eng.drain(timeout=120)
    assert len(big.result()) == 44
    assert eng.metrics.get(sm.BLOCK_EVICTIONS) >= 1
    # the warm chain was pressure-evicted NODE BY NODE (the radix store
    # drains a cold chain leaf-first: 2 blocks = 2 node evictions); the
    # one remaining entry (= chain leaf) is big's OWN post-prefill
    # insertion (refcount bumps on its blocks)
    assert eng.prefix.evictions == 2 and eng.prefix.entry_count == 1
    assert eng.prefix.blocks_released == 2


def test_held_request_is_not_overtaken_by_newer_arrivals(tiny):
    """FCFS under pressure: while a preempted request waits on its
    re-admission watermark, requests submitted after it must NOT slip
    past and consume each tick's freed blocks (sustained arrivals
    would starve it forever)."""
    _, model, variables = tiny
    pA, pB = _preempt_prompts()
    eng = ServingEngine(model, variables, n_slots=3, max_seq=64,
                        temperature=0.0, paged=True, block=8,
                        kv_blocks=9, metrics=ServeMetrics())
    a = eng.submit(pA, 30)   # oldest, ~7 blocks
    b = eng.submit(pB, 30)   # collides with a -> preempted, held
    for _ in range(30):
        eng.step()
        if eng.metrics.get(sm.PREEMPTIONS):
            break
    assert eng.metrics.get(sm.PREEMPTIONS) == 1
    assert b.state.value == "queued"
    c = eng.submit(pB[:8], 2)  # newer short request: blocks would fit
    stats = eng.step()
    # ...but it must wait behind the held request b
    assert stats["admitted"] == 0, stats
    assert c.state.value == "queued"
    eng.drain(timeout=120)
    # b resumed first; c completed after — both fully served
    assert b.state.value == "done" and len(b.result()) == 30
    assert len(c.result()) == 2
    assert b.t_first < c.t_first


def test_padded_bucket_tail_holds_no_ghost_blocks(tiny, prompts):
    """Block grants cover the chunk's REAL tokens only: the padded
    bucket tail writes route to the null block instead of pinning
    pad-only blocks for the slot's whole lifetime."""
    _, model, variables = tiny
    pA, _ = _preempt_prompts()  # 19 tokens
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        temperature=0.0, paged=True, block=8,
                        metrics=ServeMetrics())
    r = eng.submit(pA, 4)
    eng.step()  # whole-prompt chunk pads 19 -> bucket 32
    # 19 real tokens -> 3 blocks of 8; blocks for positions [24, 32)
    # of the padded bucket must NOT be held
    assert len(eng.pool.tables[r.slot]) == 3
    eng.drain(timeout=60)
    assert len(r.result()) == 4


# --------------------------------------------- eager cancel + observability


def test_cancel_reclaims_blocks_same_tick(tiny):
    """Satellite: cancel() of an in-flight request returns its
    non-shared blocks at cancel time (eager, engine-lock serialized),
    and a full pool admits a queued request on the very next tick."""
    _, model, variables = tiny
    pA, pB = _preempt_prompts()
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, paged=True, block=8,
                        kv_blocks=9, metrics=ServeMetrics())
    a = eng.submit(pA, 30)
    b = eng.submit(pB, 30)
    eng.step()
    eng.step()  # both in flight, pool saturating
    c = eng.submit(pB[:8], 4)  # no free slot -> queued
    assert eng.scheduler.depth == 1
    free_before = eng.pool.alloc.free_count
    eng.cancel(a)  # eager: slot AND blocks return NOW, no tick needed
    assert a.done and a.state.value == "cancelled"
    assert eng.pool.alloc.free_count > free_before
    stats = eng.step()  # the very next tick admits c
    assert stats["admitted"] == 1, stats
    eng.cancel(b)
    eng.drain(timeout=120)
    assert len(c.result()) == 4
    assert eng.pool.alloc.used_count == 1  # only the null block


def test_block_gauges_metrics_and_tcp_stats(tiny, prompts, paged_eng):
    """Block-pool observability: kv_blocks_{free,used,shared} gauges on
    the registry after a tick, and the TCP STATS reply carries the pool
    accounting next to prefix_cache."""
    from byteps_tpu.serving.frontend import RemoteServeClient, serve

    eng = paged_eng
    req = eng.submit(prompts[0], M)
    eng.step()
    gauges = eng.metrics.registry.snapshot()["gauges"]
    assert {sm.KV_BLOCKS_FREE, sm.KV_BLOCKS_USED,
            sm.KV_BLOCKS_SHARED} <= set(gauges), gauges
    assert gauges[sm.KV_BLOCKS_USED] >= 2  # null + the first block
    eng.drain(timeout=120)
    assert len(req.result()) == M
    srv, _ = serve(eng, port=0, host="127.0.0.1", in_thread=True)
    try:
        c = RemoteServeClient("127.0.0.1:%d" % srv.server_address[1])
        stats = c.stats()
        kv = stats["kv_blocks"]
        assert kv["block"] == 8 and kv["n_blocks"] == 33
        assert kv["free"] + kv["used"] == kv["n_blocks"]
        c.close()
    finally:
        srv.shutdown()
        srv.server_close()
