"""Real >=2-process multi-host path test (VERDICT item 8).

Launches two actual worker processes through ``byteps_tpu.launcher`` with
the DMLC env contract on localhost; each bootstraps ``jax.distributed``
(the replacement for the reference's ps::StartAsync + scheduler barrier,
global.cc:197-212), builds the global mesh, and runs a cross-process
push_pull — asserting the reference sum contract across process
boundaries, not just the env translation.
"""

import os
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import numpy as np
    import jax

    # this image's sitecustomize registers the TPU plugin and overrides
    # JAX_PLATFORMS via jax.config, so select CPU the same way (must happen
    # before any backend-initializing call)
    jax.config.update("jax_platforms", "cpu")

    import byteps_tpu as bps

    bps.init()  # BYTEPS_DISTRIBUTED_INIT=1 -> jax.distributed.initialize
    assert jax.process_count() == 2, jax.process_count()
    r = bps.rank()
    n = bps.size()
    assert n == 2, n

    # cross-process sum: worker r contributes full((4,), r+1) => sum = 3
    out = bps.push_pull(np.full((4,), float(r + 1), np.float32),
                        average=False, name="xproc")
    np.testing.assert_allclose(np.asarray(out), 3.0)

    # average mode
    out = bps.push_pull(np.full((4,), float(r + 1), np.float32),
                        average=True, name="xproc_avg")
    np.testing.assert_allclose(np.asarray(out), 1.5)

    # broadcast_parameters: every process ends with the root's values
    params = {"w": np.full((3,), float(r), np.float32)}
    params = bps.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(np.asarray(params["w"]), 0.0)

    # row-sparse push_pull across processes: worker r contributes rows
    # [r, 2] with value r+1 => row0=1, row1=2, row2=3 (both touch row 2)
    idx = np.array([r, 2], np.int32)
    val = np.full((2, 4), float(r + 1), np.float32)
    dense = np.asarray(bps.push_pull_sparse(idx, val, num_rows=6))
    np.testing.assert_allclose(dense[0], 1.0)
    np.testing.assert_allclose(dense[1], 2.0)
    np.testing.assert_allclose(dense[2], 3.0)
    np.testing.assert_allclose(dense[3:], 0.0)

    print(f"WORKER_{r}_OK")
    bps.shutdown()
    """
)


_TORCH_WORKER = textwrap.dedent(
    """
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")

    import torch
    import byteps_tpu.torch as bps

    bps.init()
    r = bps.rank()
    assert bps.size() == 2, bps.size()

    # cross-process sum of torch tensors: r+1 each => 3
    out = bps.push_pull(torch.full((4,), float(r + 1)), average=False,
                        name="tsum")
    assert isinstance(out, torch.Tensor), type(out)
    np.testing.assert_allclose(out.numpy(), 3.0)

    # averaged, in place
    t = torch.full((4,), float(r + 1))
    bps.push_pull_inplace(t, average=True, name="tavg")
    np.testing.assert_allclose(t.numpy(), 1.5)

    # broadcast_parameters: non-root model adopts root's weights
    m = torch.nn.Linear(2, 2, bias=False)
    with torch.no_grad():
        m.weight.fill_(float(r))
    bps.broadcast_parameters(m.state_dict(), root_rank=0)
    np.testing.assert_allclose(m.weight.detach().numpy(), 0.0)

    print(f"TORCH_WORKER_{r}_OK")
    bps.shutdown()
    """
)


_TF_WORKER = textwrap.dedent(
    """
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")

    import tensorflow as tf
    import keras
    import byteps_tpu.tensorflow as bps

    bps.init()
    r = bps.rank()
    assert bps.size() == 2, bps.size()

    # cross-process sum of tf tensors: r+1 each => 3
    out = bps.push_pull(tf.fill([4], float(r + 1)), average=False,
                        name="tfsum")
    assert isinstance(out, tf.Tensor), type(out)
    np.testing.assert_allclose(out.numpy(), 3.0)

    # DistributedGradientTape: per-worker grads 2*r+2 average to 3
    w = tf.Variable([1.0, 1.0])
    with bps.DistributedGradientTape(tf.GradientTape()) as tape:
        loss = tf.reduce_sum(w * float(r + 1)) * 2.0
    (g,) = tape.gradient(loss, [w])
    np.testing.assert_allclose(np.asarray(g), 3.0)

    # broadcast_variables: non-root adopts root's values
    v = tf.Variable([float(r), float(r)])
    bps.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), 0.0)

    # keras optimizer: averaged grad applied identically on both workers
    opt = bps.DistributedOptimizer(keras.optimizers.SGD(0.5))
    var = tf.Variable([2.0, 2.0])
    opt.apply_gradients([(tf.fill([2], float(r + 1)), var)])  # avg grad 1.5
    np.testing.assert_allclose(var.numpy(), 1.25)

    print(f"TF_WORKER_{r}_OK")
    bps.shutdown()
    """
)


from byteps_tpu.engine.transport import free_port as _free_port


def _run_two_workers(tmp_path, source, ok_marker):
    script = tmp_path / "worker.py"
    script.write_text(source)
    port = _free_port()
    procs = []
    for wid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # children get 1 real CPU device each
        # the worker script lives in tmp_path, so its sys.path does not
        # include the repo; make byteps_tpu importable explicitly
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        prev = env.get("PYTHONPATH")
        env["PYTHONPATH"] = repo_root + (os.pathsep + prev if prev else "")
        env.update(
            JAX_PLATFORMS="cpu",
            DMLC_ROLE="worker",
            DMLC_NUM_WORKER="2",
            DMLC_WORKER_ID=str(wid),
            DMLC_PS_ROOT_URI="127.0.0.1",
            DMLC_PS_ROOT_PORT=str(port),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "byteps_tpu.launcher",
                 sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for wid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {wid} timed out")
        outs.append(out)
    for wid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {wid} failed:\n{out}"
        assert ok_marker.format(wid=wid) in out, out


# The three two-process tests spawn REAL worker subprocesses, each
# paying a full jax + frontend import and distributed init: 30-70s
# apiece, ~150s of tier-1 wall combined.  They run in the slow bucket
# (pytest -m slow) — the single-process collective/sharding coverage
# stays in tier-1.


@pytest.mark.slow
def test_two_process_push_pull(tmp_path):
    _run_two_workers(tmp_path, _WORKER, "WORKER_{wid}_OK")


@pytest.mark.slow
def test_two_process_torch_frontend(tmp_path):
    """byteps_tpu.torch across 2 real processes: worker==process semantics
    for push_pull (sum/avg/in-place) and broadcast_parameters."""
    pytest.importorskip("torch")
    _run_two_workers(tmp_path, _TORCH_WORKER, "TORCH_WORKER_{wid}_OK")


@pytest.mark.slow
def test_two_process_tf_frontend(tmp_path):
    """byteps_tpu.tensorflow across 2 real processes: push_pull on tf
    tensors, DistributedGradientTape averaging, broadcast_variables, and
    a keras DistributedOptimizer applying the worker-averaged gradient."""
    pytest.importorskip("tensorflow")
    pytest.importorskip("keras")
    _run_two_workers(tmp_path, _TF_WORKER, "TF_WORKER_{wid}_OK")
