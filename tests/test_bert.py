"""BERT encoder model tests."""

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.models import BertClassifier, BertMLM, bert_config


def _tiny_cfg():
    return bert_config(
        vocab_size=128, num_layers=2, num_heads=4, d_model=32, d_ff=64,
        max_seq_len=16, dtype=jnp.float32,
    )


def test_bert_classifier_shapes():
    model = BertClassifier(_tiny_cfg(), num_classes=3)
    tokens = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32


def test_bert_is_bidirectional():
    """Changing a LATE token must change an EARLY position's hidden state
    (unlike the causal decoder)."""
    model = BertMLM(_tiny_cfg())
    t1 = jnp.arange(16, dtype=jnp.int32)[None, :] % 128
    t2 = t1.at[0, 15].set(99)
    variables = model.init(jax.random.PRNGKey(0), t1)
    l1 = model.apply(variables, t1)
    l2 = model.apply(variables, t2)
    assert not np.allclose(np.asarray(l1[0, 0]), np.asarray(l2[0, 0]))


def test_bert_mlm_shapes_and_training_signal():
    cfg = _tiny_cfg()
    model = BertMLM(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 128)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(variables, tokens)
    assert logits.shape == (2, 16, 128)

    import optax

    def loss_fn(params):
        lg = model.apply({"params": params}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            lg, tokens).mean()

    g = jax.grad(loss_fn)(variables["params"])
    norms = [float(jnp.linalg.norm(x)) for x in jax.tree_util.tree_leaves(g)]
    assert all(np.isfinite(n) for n in norms) and any(n > 0 for n in norms)


def test_bert_attention_mask_zeroes_padding():
    model = BertClassifier(_tiny_cfg(), num_classes=2)
    tokens = jnp.ones((1, 16), jnp.int32)
    mask = jnp.array([[1] * 8 + [0] * 8])
    variables = model.init(jax.random.PRNGKey(0), tokens)
    # encoder output is zeroed at padded positions
    from byteps_tpu.models import BertEncoder

    enc = BertEncoder(_tiny_cfg())
    ev = enc.init(jax.random.PRNGKey(0), tokens)
    h = enc.apply(ev, tokens, mask)
    assert np.allclose(np.asarray(h[0, 8:]), 0.0)
    assert not np.allclose(np.asarray(h[0, :8]), 0.0)
