"""Trainer loop and int8/error-feedback quantization tests."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import byteps_tpu as bps
from byteps_tpu.ops.quantization import (
    dequantize,
    error_feedback_quantize_gradients,
    quantize,
)
from byteps_tpu.training.trainer import Trainer


def test_quantize_roundtrip_accuracy():
    x = jax.random.normal(jax.random.PRNGKey(0), (1000,))
    q, scale = quantize(x)
    assert q.dtype == jnp.int8
    err = np.abs(np.asarray(dequantize(q, scale) - x))
    assert err.max() <= float(scale) / 2 + 1e-7


def test_quantize_zero_tensor():
    q, scale = quantize(jnp.zeros(16))
    np.testing.assert_allclose(np.asarray(dequantize(q, scale)), 0.0)


def test_error_feedback_compensates():
    """With EF, the accumulated applied update converges to the accumulated
    true gradient (residual stays bounded)."""
    tx = error_feedback_quantize_gradients()
    g = jnp.full((8,), 0.001)  # tiny constant gradient, heavily quantized
    state = tx.init(g)
    applied = jnp.zeros_like(g)
    for i in range(100):
        upd, state = tx.update(g, state)
        applied = applied + upd
    # total applied ~= 100 * g (error feedback recovers dropped mass)
    np.testing.assert_allclose(np.asarray(applied), 0.1, rtol=0.05)


def test_ef_quant_composes_with_push_pull_training():
    from byteps_tpu.training import make_data_parallel_step, shard_batch
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    inner = optax.chain(error_feedback_quantize_gradients(), optax.sgd(0.05))
    step = make_data_parallel_step(loss_fn, inner, mesh)
    params = {"w": jnp.zeros((4,))}
    state = step.init_state(params)
    w_true = jnp.array([1.0, -2.0, 0.5, 3.0])
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
    batch = shard_batch({"x": x, "y": x @ w_true}, mesh)
    for _ in range(150):
        state, metrics = step(state, batch)
        # Block each step: unbounded async dispatch of data-dependent jitted
        # steps can starve XLA's in-process CPU collective rendezvous on the
        # virtual 8-device harness (observed SIGABRT after ~40s).
        jax.block_until_ready(state)
    assert float(metrics["loss"]) < 1e-2
    np.testing.assert_allclose(np.asarray(state.params["w"]), np.asarray(w_true),
                               atol=0.05)


def test_trainer_fit_and_resume(tmp_path):
    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    w_true = jnp.array([2.0, -1.0])
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 2))
    data = [{"x": x, "y": x @ w_true}] * 60

    trainer = Trainer(
        loss_fn, optax.sgd(0.1),
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=20,
        log_every=0,
    )
    params = {"w": jnp.zeros((2,))}
    state = trainer.fit(params, {}, iter(data), steps=60)
    assert int(state.step) == 60
    assert trainer.ckpt.steps()  # checkpoints written

    # new trainer resumes from latest checkpoint, not from scratch
    trainer2 = Trainer(
        loss_fn, optax.sgd(0.1),
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=20,
        log_every=0,
    )
    s2 = trainer2.init_state(params, {})
    assert int(s2.step) == max(trainer.ckpt.steps())
    np.testing.assert_allclose(np.asarray(s2.params["w"]),
                               np.asarray(state.params["w"]), atol=1e-4)


def test_trainer_evaluate_pipelines_host_reads():
    """evaluate() must not sync the host per batch (VERDICT r2 weak #6):
    >= 2 eval batches are issued before the first result is read back.
    Verified by interposing eval_fn (device work issued) and float()
    conversion order via a spy scalar type."""

    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    trainer = Trainer(loss_fn, optax.sgd(0.1), log_every=0)
    params = {"w": jnp.zeros((2,))}
    trainer.state = trainer.init_state(params, {})

    issued = [0]          # batches handed to eval_fn so far
    reads = []            # (batch index read, issued count at read time)

    class _Spy:
        def __init__(self, i, v):
            self.i, self.v = i, v

        def __float__(self):
            reads.append((self.i, issued[0]))
            return float(self.v)

    def eval_fn(state, batch):
        i = issued[0]
        issued[0] += 1
        loss, _ = loss_fn(state.params, {}, batch)
        return {"loss": _Spy(i, loss)}

    x = jnp.ones((8, 2))
    data = [{"x": x, "y": jnp.ones((8,))}] * 8
    out = trainer.evaluate(eval_fn, iter(data))
    assert "loss" in out and np.isfinite(out["loss"])
    # first host read consumed batch 0 only after >= 2 further batches
    # had already been issued (bounded in-flight window, not lockstep)
    first_batch, issued_at_read = reads[0]
    assert first_batch == 0
    assert issued_at_read - first_batch >= 2
    assert issued[0] == 8 and len(reads) == 8
