"""CI wiring for scripts/chaos_smoke.py: a 2-shard PS cluster under
seeded random faults must reach bit-for-bit the no-fault parameters.

Marked ``slow`` so tier-1 (-m 'not slow') stays fast; run explicitly
with ``pytest -m slow tests/test_chaos_smoke.py``.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


@pytest.mark.slow
def test_chaos_smoke_bitwise_convergence():
    import chaos_smoke

    stats = chaos_smoke.run(steps=40, seed=0, rate=0.15, verbose=False)
    assert stats["faults"] > 0
    # the deduplication path (applied + reply lost) must have fired at
    # least once across 160 mutating requests at a 5% drop_after rate —
    # if not, the seed changed the mix; bump steps rather than ignore
    assert stats.get("resilience.retry", 0) > 0


@pytest.mark.slow
@pytest.mark.parametrize("scheme", ["randomk", "onebit"])
def test_chaos_smoke_compressed_exactly_once(scheme):
    """Acceptance criterion (docs/compression.md): at a >=25% injected
    fault rate, a retried compressed PUSH must never double-apply the
    error-feedback residual — chaos.run raises on any clean/chaos
    divergence, and with EF compression a single double-fold (or a
    re-drawn random-k mask) diverges immediately.  Run twice with the
    same seed to pin run-reproducibility."""
    import chaos_smoke

    stats1 = chaos_smoke.run(steps=40, seed=1, rate=0.27, verbose=False,
                             compression=scheme)
    assert stats1["faults"] > 0
    assert stats1["faults"] / stats1["requests"] >= 0.05
    assert stats1.get("resilience.retry", 0) > 0
    stats2 = chaos_smoke.run(steps=40, seed=1, rate=0.27, verbose=False,
                             compression=scheme)
    # seeded faults + seeded compression => identical fault/retry mix
    assert stats2["faults"] == stats1["faults"]


@pytest.mark.slow
def test_chaos_smoke_uds_transport_exactly_once_with_failover():
    """PR 7 acceptance (docs/wire.md "Transports"): the full chaos bar
    on the AF_UNIX fast path — pipelined window, partitioned tensors,
    compression + EF, faults injected on every UDS connection, AND a
    deterministic mid-run shard kill so failover provably fires.  The
    clean run never sees the kill, so bit-for-bit parity additionally
    proves the failover re-seed loses nothing on this transport."""
    import chaos_smoke

    stats = chaos_smoke.run(steps=40, seed=1, rate=0.27, verbose=False,
                            compression="randomk", window=8,
                            partition_bytes=24, dim=64,
                            transport="unix", kill_shard_at=30)
    assert stats["faults"] > 0
    assert stats.get("resilience.window_abort", 0) > 0
    assert stats.get("resilience.retry_dedup", 0) > 0
    assert stats.get("resilience.failover", 0) >= 1


@pytest.mark.slow
def test_chaos_smoke_hierarchical_sliced_exactly_once_with_failover():
    """ISSUE 8 acceptance (docs/wire.md "Hierarchical reduction"): the
    full chaos bar with hierarchical slicing on — every tensor travels
    as 4 ``name@s{r}`` sub-tensors (each further partitioned), under a
    27% fault rate with the pipelined window AND a deterministic mid-run
    shard kill.  Bit-for-bit clean-vs-chaos proves the per-slice version
    guards, per-slice EF commits and per-slice failover re-seeds are
    exactly-once in any completion order."""
    import chaos_smoke

    stats = chaos_smoke.run(steps=40, seed=1, rate=0.27, verbose=False,
                            compression="randomk", window=8,
                            partition_bytes=24, dim=64,
                            hierarchical=True, kill_shard_at=30)
    assert stats["faults"] > 0
    assert stats["faults"] / stats["requests"] >= 0.05
    assert stats.get("resilience.window_abort", 0) > 0
    assert stats.get("resilience.retry_dedup", 0) > 0
    assert stats.get("resilience.failover", 0) >= 1


@pytest.mark.slow
def test_chaos_smoke_full_bar_under_lockcheck():
    """ISSUE 15 acceptance: the full chaos bar — compression + EF,
    pipelined window, partitioned tensors, a deterministic mid-run
    shard kill — passes bit-for-bit under ``BYTEPS_LOCKCHECK=1`` with
    zero lock-order cycles reported: the faulted schedule (retries,
    window aborts, failover re-seed) is deadlock-free, not just
    exactly-once (docs/analysis.md "Runtime lock-order detector")."""
    import chaos_smoke
    from byteps_tpu.analysis import runtime as lockrt

    try:
        stats = chaos_smoke.run(steps=40, seed=1, rate=0.27,
                                verbose=False, compression="randomk",
                                window=8, partition_bytes=24, dim=64,
                                kill_shard_at=30, lockcheck=True)
    finally:
        lockrt.uninstall()
        lockrt.reset()
    assert stats["faults"] > 0
    assert stats.get("resilience.failover", 0) >= 1
    assert stats["lockcheck.cycles"] == 0
    assert stats["lockcheck.locks"] > 0


@pytest.mark.slow
def test_chaos_smoke_pipelined_partitioned_exactly_once():
    """PR 4 acceptance (docs/wire.md): the pipelined wire client —
    in-flight window, partitioned tensors fanned out across shards,
    compression + error feedback on — survives the PR 3 fault rate
    (27%) bit-for-bit.  Partitioning multiplies the mutating requests
    per step, so this run drives window aborts, version-guard dedup AND
    failover/failback churn (the mix that exposed the failover-seed
    fold bug); chaos_smoke.run raises on any clean/chaos divergence."""
    import chaos_smoke

    stats = chaos_smoke.run(steps=40, seed=1, rate=0.27, verbose=False,
                            compression="randomk", window=8,
                            partition_bytes=24, dim=64)
    assert stats["faults"] > 0
    assert stats.get("resilience.window_abort", 0) > 0
    assert stats.get("resilience.retry_dedup", 0) > 0
