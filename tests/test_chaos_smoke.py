"""CI wiring for scripts/chaos_smoke.py: a 2-shard PS cluster under
seeded random faults must reach bit-for-bit the no-fault parameters.

Marked ``slow`` so tier-1 (-m 'not slow') stays fast; run explicitly
with ``pytest -m slow tests/test_chaos_smoke.py``.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))


@pytest.mark.slow
def test_chaos_smoke_bitwise_convergence():
    import chaos_smoke

    stats = chaos_smoke.run(steps=40, seed=0, rate=0.15, verbose=False)
    assert stats["faults"] > 0
    # the deduplication path (applied + reply lost) must have fired at
    # least once across 160 mutating requests at a 5% drop_after rate —
    # if not, the seed changed the mix; bump steps rather than ignore
    assert stats.get("resilience.retry", 0) > 0
