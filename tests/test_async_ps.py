"""Async parameter-server mode tests.

Staleness contract (reference BYTEPS_ENABLE_ASYNC semantics,
torch/__init__.py:174-189): global state == initial + sum of all pushed
deltas; read-your-writes per worker; no barrier — interleaving order doesn't
change the final state (summation is commutative).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.engine.async_ps import AsyncParameterServer, AsyncWorker


def test_push_pull_accumulates_deltas():
    server = AsyncParameterServer(use_native=False)
    p0 = {"w": np.zeros(4, np.float32)}
    w1 = AsyncWorker(server, p0, worker_id=0)
    w2 = AsyncWorker(server, p0, worker_id=1)

    w1.push_pull({"w": np.ones(4, np.float32)})  # delta +1
    got = w2.push_pull({"w": np.full(4, 2.0, np.float32)})  # delta +2
    np.testing.assert_allclose(got["w"], np.full(4, 3.0))  # 0 + 1 + 2


def test_read_your_writes():
    server = AsyncParameterServer(use_native=False)
    w = AsyncWorker(server, {"w": np.zeros(2, np.float32)})
    out = w.push_pull({"w": np.array([1.0, -1.0], np.float32)})
    np.testing.assert_allclose(out["w"], [1.0, -1.0])
    # second push is a delta vs the pulled snapshot, not vs initial
    out = w.push_pull({"w": np.array([2.0, 0.0], np.float32)})
    np.testing.assert_allclose(out["w"], [2.0, 0.0])


def test_interleaving_order_is_commutative():
    def run(order):
        server = AsyncParameterServer(use_native=False)
        p0 = {"w": np.zeros(1, np.float32)}
        workers = [AsyncWorker(server, p0, worker_id=i) for i in range(3)]
        deltas = [1.0, 10.0, 100.0]
        for i in order:
            snap = workers[i]._snapshot[0]
            workers[i].push_pull({"w": snap + deltas[i]})
        return server.pull("param_0")

    a = run([0, 1, 2])
    b = run([2, 0, 1])
    np.testing.assert_allclose(a, b)
    np.testing.assert_allclose(a, [111.0])


def test_concurrent_workers_no_lost_updates():
    server = AsyncParameterServer(use_native=False)
    p0 = {"w": np.zeros(8, np.float32)}
    nworkers, nsteps = 4, 25
    workers = [AsyncWorker(server, p0, worker_id=i) for i in range(nworkers)]

    def work(w):
        for _ in range(nsteps):
            w.push_pull({"w": w._snapshot[0] + 1.0})

    threads = [threading.Thread(target=work, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    np.testing.assert_allclose(server.pull("param_0"),
                               np.full(8, nworkers * nsteps, np.float32))


def test_async_training_converges():
    """Two async workers minimizing the same quadratic reach the optimum
    despite stale pulls (the reference's convergence claim for async mode)."""
    server = AsyncParameterServer(use_native=False)
    target = np.array([3.0, -2.0], np.float32)
    p0 = {"w": np.zeros(2, np.float32)}
    workers = [AsyncWorker(server, p0, worker_id=i) for i in range(2)]
    lr = 0.2

    for _ in range(60):
        for w in workers:
            cur = w.params["w"]
            grad = cur - target  # d/dw 0.5*(w-t)^2
            w.push_pull({"w": cur - lr * grad})
    for w in workers:
        np.testing.assert_allclose(w.params["w"], target, atol=1e-2)


def test_native_reducer_matches_numpy():
    from byteps_tpu.native import reducer

    if not reducer.available():
        pytest.skip("native lib unavailable")
    import ml_dtypes

    rng = np.random.default_rng(0)
    for dtype, atol in [(np.float32, 1e-6), (np.float16, 2e-3),
                        (ml_dtypes.bfloat16, 2e-2),
                        (np.int32, 0), (np.int64, 0), (np.float64, 1e-12)]:
        if dtype in (np.int32, np.int64):
            a = rng.integers(-1000, 1000, 1027).astype(dtype)
            b = rng.integers(-1000, 1000, 1027).astype(dtype)
        else:
            a = rng.standard_normal(1027).astype(dtype)
            b = rng.standard_normal(1027).astype(dtype)
        expect = (a.astype(np.float64) + b.astype(np.float64)) if atol else a + b
        got = a.copy()
        reducer.sum_into(got, b)
        if atol:
            np.testing.assert_allclose(got.astype(np.float64), expect,
                                       atol=atol, rtol=1e-2)
        else:
            np.testing.assert_array_equal(got, expect)


def test_native_key_to_shard_matches_reference_formula():
    from byteps_tpu.native import reducer

    for key in [0, 1, 65535, 65536, 2**31, 123456789]:
        for n in [1, 3, 7, 32]:
            expect = (((key >> 16) + (key % 65536)) * 9973) % n
            assert reducer.key_to_shard(key, n) == expect


def test_server_with_native_reducer():
    server = AsyncParameterServer(use_native=True)
    w = AsyncWorker(server, {"w": np.zeros(1000, np.float32)})
    out = w.push_pull({"w": np.ones(1000, np.float32)})
    np.testing.assert_allclose(out["w"], 1.0)
