"""Async parameter-server mode tests.

Staleness contract (reference BYTEPS_ENABLE_ASYNC semantics,
torch/__init__.py:174-189): global state == initial + sum of all pushed
deltas; read-your-writes per worker; no barrier — interleaving order doesn't
change the final state (summation is commutative).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.engine.async_ps import AsyncParameterServer, AsyncWorker


def test_push_pull_accumulates_deltas():
    server = AsyncParameterServer(use_native=False)
    p0 = {"w": np.zeros(4, np.float32)}
    w1 = AsyncWorker(server, p0, worker_id=0)
    w2 = AsyncWorker(server, p0, worker_id=1)

    w1.push_pull({"w": np.ones(4, np.float32)})  # delta +1
    got = w2.push_pull({"w": np.full(4, 2.0, np.float32)})  # delta +2
    np.testing.assert_allclose(got["w"], np.full(4, 3.0))  # 0 + 1 + 2


def test_read_your_writes():
    server = AsyncParameterServer(use_native=False)
    w = AsyncWorker(server, {"w": np.zeros(2, np.float32)})
    out = w.push_pull({"w": np.array([1.0, -1.0], np.float32)})
    np.testing.assert_allclose(out["w"], [1.0, -1.0])
    # second push is a delta vs the pulled snapshot, not vs initial
    out = w.push_pull({"w": np.array([2.0, 0.0], np.float32)})
    np.testing.assert_allclose(out["w"], [2.0, 0.0])


def test_interleaving_order_is_commutative():
    def run(order):
        server = AsyncParameterServer(use_native=False)
        p0 = {"w": np.zeros(1, np.float32)}
        workers = [AsyncWorker(server, p0, worker_id=i) for i in range(3)]
        deltas = [1.0, 10.0, 100.0]
        for i in order:
            snap = workers[i]._snapshot[0]
            workers[i].push_pull({"w": snap + deltas[i]})
        return server.pull("param_0")

    a = run([0, 1, 2])
    b = run([2, 0, 1])
    np.testing.assert_allclose(a, b)
    np.testing.assert_allclose(a, [111.0])


def test_concurrent_workers_no_lost_updates():
    server = AsyncParameterServer(use_native=False)
    p0 = {"w": np.zeros(8, np.float32)}
    nworkers, nsteps = 4, 25
    workers = [AsyncWorker(server, p0, worker_id=i) for i in range(nworkers)]

    def work(w):
        for _ in range(nsteps):
            w.push_pull({"w": w._snapshot[0] + 1.0})

    threads = [threading.Thread(target=work, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    np.testing.assert_allclose(server.pull("param_0"),
                               np.full(8, nworkers * nsteps, np.float32))


def test_async_training_converges():
    """Two async workers minimizing the same quadratic reach the optimum
    despite stale pulls (the reference's convergence claim for async mode)."""
    server = AsyncParameterServer(use_native=False)
    target = np.array([3.0, -2.0], np.float32)
    p0 = {"w": np.zeros(2, np.float32)}
    workers = [AsyncWorker(server, p0, worker_id=i) for i in range(2)]
    lr = 0.2

    for _ in range(60):
        for w in workers:
            cur = w.params["w"]
            grad = cur - target  # d/dw 0.5*(w-t)^2
            w.push_pull({"w": cur - lr * grad})
    for w in workers:
        np.testing.assert_allclose(w.params["w"], target, atol=1e-2)


def test_sharded_store_placement_and_ops():
    from byteps_tpu.engine.async_ps import ShardedParameterStore

    store = ShardedParameterStore(num_shards=4, use_native=False)
    names = [f"t{i}" for i in range(12)]
    for i, n in enumerate(names):
        store.init_tensor(n, np.zeros(4, np.float32))
    # placement: reference formula over the order-independent name key, so
    # two workers declaring in different orders agree on shards
    from byteps_tpu.common.context import name_key

    for n in names:
        expect = (((name_key(n) >> 16) + name_key(n) % 65536) * 9973) % 4
        assert store.shard_of(n) == expect
    s2 = ShardedParameterStore(num_shards=4, use_native=False)
    for n in reversed(names):  # different declaration order, same placement
        assert s2.shard_of(n) == store.shard_of(n)
    out = store.push_pull("t3", np.ones(4, np.float32))
    np.testing.assert_allclose(out, 1.0)
    store.push_delta("t3", np.ones(4, np.float32))
    np.testing.assert_allclose(store.pull("t3"), 2.0)
    assert store.version("t3") == 2
    assert set(store.names()) == set(names)
    assert sum(store.load()) > 0  # byte accounting active


def test_four_async_workers_converge_concurrently():
    """VERDICT item 3: 4 workers train async on the (sharded) store and
    converge — local SGD steps, delta push, stale pulls, no barrier."""
    from byteps_tpu.engine.async_ps import ShardedParameterStore

    store = ShardedParameterStore(num_shards=2, use_native=False)
    target = np.arange(4, dtype=np.float32)
    p0 = {"w": np.zeros(4, np.float32)}
    workers = [AsyncWorker(store, p0, worker_id=i) for i in range(4)]
    lr = 0.05

    def work(w):
        for _ in range(80):
            cur = w.params["w"]
            w.push_pull({"w": cur - lr * (cur - target)})

    threads = [threading.Thread(target=work, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for w in workers:
        w.push_pull(w.params)  # final pull (delta 0) to see global state
        np.testing.assert_allclose(w.params["w"], target, atol=5e-2)


def test_ps_server_end_to_end():
    """TCP server tier: two shard servers, two clients, reference push_pull
    semantics over the wire."""
    from byteps_tpu.engine import ps_server

    srv1, t1 = ps_server.serve(0, host="127.0.0.1", use_native=False,
                               in_thread=True)
    srv2, t2 = ps_server.serve(0, host="127.0.0.1", use_native=False,
                               in_thread=True)
    addrs = [f"127.0.0.1:{srv1.server_address[1]}",
             f"127.0.0.1:{srv2.server_address[1]}"]
    try:
        c1 = ps_server.RemoteStore(addrs)
        c2 = ps_server.RemoteStore(addrs)
        assert c1.ping()
        p0 = {"w": np.zeros(8, np.float32), "b": np.zeros(3, np.float32)}
        w1 = AsyncWorker(c1, p0, worker_id=0)
        w2 = AsyncWorker(c2, p0, worker_id=1)
        w1.push_pull({"w": np.ones(8, np.float32),
                      "b": np.full(3, 5.0, np.float32)})
        got = w2.push_pull({"w": np.full(8, 2.0, np.float32),
                            "b": np.full(3, -1.0, np.float32)})
        np.testing.assert_allclose(got["w"], 3.0)
        np.testing.assert_allclose(got["b"], 4.0)
        assert c1.version("param_0") == 2
        assert set(c1.names()) == {"param_0", "param_1"}
        # bf16 round-trips by dtype *name* (.str is raw-void for ml_dtypes)
        import ml_dtypes

        bf = np.zeros(16, ml_dtypes.bfloat16)
        c1.init_tensor("bf", bf)
        out = c1.push_pull("bf", np.ones(16, ml_dtypes.bfloat16))
        assert out.dtype == np.dtype(ml_dtypes.bfloat16)
        np.testing.assert_allclose(out.astype(np.float32), 1.0)
        # store-level error -> status-1 reply, connection survives
        with pytest.raises(RuntimeError, match="ps_server error"):
            c1.pull("never_declared")
        assert c1.ping()
        c1.close(); c2.close()
    finally:
        srv1.shutdown(); srv2.shutdown()
        srv1.server_close(); srv2.server_close()


def test_trainer_async_flag_changes_behavior(monkeypatch):
    """BYTEPS_ENABLE_ASYNC / Trainer(async_mode=) demonstrably routes
    training through the delta-push store (VERDICT item 3)."""
    import optax

    from byteps_tpu.common.config import reset_config
    from byteps_tpu.engine.async_ps import ShardedParameterStore
    from byteps_tpu.training.trainer import Trainer

    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    w_true = jnp.array([1.0, -1.0])
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 2))
    data = [{"x": x, "y": x @ w_true}] * 40

    # OFF: store untouched
    store = ShardedParameterStore(num_shards=2, use_native=False)
    t_off = Trainer(loss_fn, optax.sgd(0.1), log_every=0,
                    async_mode=False, async_store=store)
    t_off.fit({"w": jnp.zeros((2,))}, {}, iter(data), steps=5)
    assert store.names() == []

    # ON via env: flag read from config, store exercised, training converges
    monkeypatch.setenv("BYTEPS_ENABLE_ASYNC", "1")
    reset_config()
    from byteps_tpu.engine.async_ps import set_async_store

    set_async_store(store)
    try:
        t_on = Trainer(loss_fn, optax.sgd(0.2), log_every=0)
        assert t_on.async_mode
        state = t_on.fit({"w": jnp.zeros((2,))}, {}, iter(data), steps=40)
        assert store.names()  # tensors registered on the store
        assert store.version("param_0") >= 40  # one delta push per step
        np.testing.assert_allclose(np.asarray(state.params["w"]),
                                   np.asarray(w_true), atol=0.05)
    finally:
        set_async_store(None)
        monkeypatch.delenv("BYTEPS_ENABLE_ASYNC")
        reset_config()


def test_native_reducer_matches_numpy():
    from byteps_tpu.native import reducer

    if not reducer.available():
        pytest.skip("native lib unavailable")
    import ml_dtypes

    rng = np.random.default_rng(0)
    for dtype, atol in [(np.float32, 1e-6), (np.float16, 2e-3),
                        (ml_dtypes.bfloat16, 2e-2),
                        (np.int32, 0), (np.int64, 0), (np.float64, 1e-12)]:
        if dtype in (np.int32, np.int64):
            a = rng.integers(-1000, 1000, 1027).astype(dtype)
            b = rng.integers(-1000, 1000, 1027).astype(dtype)
        else:
            a = rng.standard_normal(1027).astype(dtype)
            b = rng.standard_normal(1027).astype(dtype)
        expect = (a.astype(np.float64) + b.astype(np.float64)) if atol else a + b
        got = a.copy()
        reducer.sum_into(got, b)
        if atol:
            np.testing.assert_allclose(got.astype(np.float64), expect,
                                       atol=atol, rtol=1e-2)
        else:
            np.testing.assert_array_equal(got, expect)


def test_native_key_to_shard_matches_reference_formula():
    from byteps_tpu.native import reducer

    for key in [0, 1, 65535, 65536, 2**31, 123456789]:
        for n in [1, 3, 7, 32]:
            expect = (((key >> 16) + (key % 65536)) * 9973) % n
            assert reducer.key_to_shard(key, n) == expect


def test_server_with_native_reducer():
    server = AsyncParameterServer(use_native=True)
    w = AsyncWorker(server, {"w": np.zeros(1000, np.float32)})
    out = w.push_pull({"w": np.ones(1000, np.float32)})
    np.testing.assert_allclose(out["w"], 1.0)


def test_pipelined_exchange_catch_up_rule():
    """begin_push_pull/take_result (VERDICT r3 #7): the background
    exchange returns (pulled, submitted); adopting with
    params += pulled - submitted preserves local progress made while the
    exchange was in flight."""
    server = AsyncParameterServer(use_native=False)
    w = AsyncWorker(server, {"p": np.zeros(4, np.float32)})
    other = AsyncWorker(server, {"p": np.zeros(4, np.float32)})

    w.begin_push_pull({"p": jnp.ones(4, jnp.float32)})       # delta +1
    pulled, submitted = w.take_result()
    np.testing.assert_allclose(pulled["p"], 1.0)
    np.testing.assert_allclose(submitted["p"], 1.0)

    # another worker contributes +2 BEFORE our second exchange is queued
    # (ordering fixed so the expected pulled value is deterministic)
    other.push_pull({"p": np.full(4, 2.0, np.float32)})         # delta +2
    w.begin_push_pull({"p": jnp.full((4,), 1.5, jnp.float32)})  # delta +0.5
    pulled, submitted = w.take_result()
    # local trained on to 1.7 while the exchange flew; catch-up keeps the
    # 0.2 of local progress on top of the pulled global state
    current = np.full(4, 1.7, np.float32)
    adopted = current + (pulled["p"] - submitted["p"])
    np.testing.assert_allclose(pulled["p"], 3.5)  # 0 +1 +2 +0.5
    np.testing.assert_allclose(adopted, 3.5 + 0.2, rtol=1e-6)

    # double-submit without take_result is an error; so is a synchronous
    # push_pull while an exchange is in flight
    w.begin_push_pull({"p": jnp.zeros(4)})
    with pytest.raises(RuntimeError):
        w.begin_push_pull({"p": jnp.zeros(4)})
    with pytest.raises(RuntimeError):
        w.push_pull({"p": np.zeros(4, np.float32)})
    w.take_result()
    w.close()
    other.close()


def test_four_workers_pipelined_converge():
    """4 workers with the PIPELINED exchange (train while the delta is in
    flight) still converge to the target — same contract as the
    synchronous-exchange test above."""
    from byteps_tpu.engine.async_ps import ShardedParameterStore

    store = ShardedParameterStore(num_shards=2, use_native=False)
    target = np.arange(4, dtype=np.float32)
    p0 = {"w": np.zeros(4, np.float32)}
    workers = [AsyncWorker(store, p0, worker_id=i) for i in range(4)]
    lr = 0.05

    def work(w):
        params = np.zeros(4, np.float32)
        for it in range(80):
            params = params - lr * (params - target)   # local step
            if w.exchange_in_flight():
                pulled, submitted = w.take_result()
                params = params + (pulled["w"] - submitted["w"])
            w.begin_push_pull({"w": jnp.asarray(params)})
        if w.exchange_in_flight():
            pulled, submitted = w.take_result()
            params = params + (pulled["w"] - submitted["w"])
        return params

    threads = [threading.Thread(target=work, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for w in workers:
        w.push_pull(w.params)  # settle + read the global state
        np.testing.assert_allclose(w.params["w"], target, atol=5e-2)


def test_trainer_pipelined_async_no_trainloop_device_get(monkeypatch):
    """The trainer's exchange path must not call jax.device_get on the
    train thread (the r2 stop-the-world stall): device_get happens only on
    the background exchange thread."""
    from byteps_tpu.engine.async_ps import (AsyncParameterServer,
                                            reset_async_store,
                                            set_async_store)
    from byteps_tpu.training.trainer import Trainer

    main_thread = threading.current_thread()
    calls = []
    orig = jax.device_get

    def spy(x):
        if threading.current_thread() is main_thread:
            calls.append(1)
        return orig(x)

    store = AsyncParameterServer(use_native=False)
    set_async_store(store)
    try:
        def loss_fn(params, mstate, batch):
            pred = batch["x"] @ params["w"]
            return jnp.mean((pred - batch["y"]) ** 2), mstate

        trainer = Trainer(loss_fn, optax.sgd(0.1), log_every=0,
                          async_mode=True, async_interval=2)
        w_true = jnp.array([1.0, -2.0])
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 2))
        data = [{"x": x, "y": x @ w_true}] * 20
        # init first: AsyncWorker registration does one legitimate
        # device_get outside the train loop
        trainer.state = trainer.init_state({"w": jnp.zeros(2)}, {})
        monkeypatch.setattr(jax, "device_get", spy)
        state = trainer.fit({"w": jnp.zeros(2)}, {}, iter(data), steps=20)
        monkeypatch.undo()
        assert not calls, "train thread called jax.device_get"
        # made real optimization progress and the store saw the pushes.
        # NOT a tight-tolerance check: the async exchange thread adopts
        # global state at its own cadence, so the final iterate depends
        # on thread timing — observed ||w - w*|| ranges ~0.005-0.1 over
        # 20 steps.  The timing-independent bound is contraction: 20 SGD
        # steps at lr 0.1 on this quadratic shrink the error by far more
        # than 2x even when every adopted exchange is maximally stale
        # (the flake history: atol=1e-2 failed at ~0.09 — a bound on the
        # lucky path, not the guaranteed one).
        err = np.linalg.norm(np.asarray(state.params["w"])
                             - np.asarray(w_true))
        err0 = np.linalg.norm(np.asarray(w_true))  # started from zeros
        assert err < 0.5 * err0, (
            f"async training made no progress: ||w-w*||={err:.3f} vs "
            f"initial {err0:.3f}")
        assert store.names()
        trainer.close()  # stops the exchange thread (frees the snapshot)
    finally:
        reset_async_store()


def test_ps_server_profile_timeline(tmp_path, monkeypatch):
    """BYTEPS_SERVER_ENABLE_PROFILE writes a chrome-trace of per-key
    push/pull B/E spans on the server tier (reference docs/timeline.md:
    the straggler-hunting tool the worker-side tracer cannot provide)."""
    import json

    from byteps_tpu.common import config as bps_config
    from byteps_tpu.common.context import name_key
    from byteps_tpu.engine import ps_server

    out = tmp_path / "server_profile.json"
    monkeypatch.setenv("BYTEPS_SERVER_ENABLE_PROFILE", "1")
    monkeypatch.setenv("BYTEPS_SERVER_PROFILE_OUTPUT_PATH", str(out))
    bps_config.reset_config()
    try:
        srv, thread = ps_server.serve(0, host="127.0.0.1",
                                      use_native=False, in_thread=True)
        addr = "127.0.0.1:%d" % srv.server_address[1]
        store = ps_server.RemoteStore([addr])
        store.init_tensor("w", np.zeros(4, np.float32))
        store.push_pull("w", np.ones(4, np.float32))
        store.pull("w")
        store.close()
        srv.shutdown()
        srv.server_close()  # flushes the profile
        thread.join(timeout=5)

        events = json.loads(out.read_text())
        names = {e["name"].split("-", 1)[0] for e in events}
        assert "push_pull" in names and "pull" in names
        # init is not a data-plane request: not profiled
        assert "init" not in names
        key = name_key("w")
        assert all(e["pid"] == key and e["tid"] == key for e in events)
        # every span is a B followed by an E with ts_E >= ts_B
        assert [e["ph"] for e in events] == ["B", "E"] * (len(events) // 2)
        for b, e in zip(events[::2], events[1::2]):
            assert e["ts"] >= b["ts"] and b["name"] == e["name"]
    finally:
        bps_config.reset_config()
