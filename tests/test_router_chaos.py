"""CI wiring for scripts/router_chaos.py and the router bench legs.

The chaos proof (ISSUE 11 acceptance): N in-process replicas behind
the router with a fault-injecting proxy on every replica leg, a
deterministic mid-stream replica kill, and a drain leg — every
in-flight request either completes token-identical to a single-engine
``generate()`` reference (greedy AND seeded) or fails with a typed
error within its deadline; zero hangs, zero silent drops; the drain
leg sees zero client-visible errors.

All ``slow``-marked; the fast deterministic single-failover sibling
lives in tier-1 (tests/test_serving_router.py).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts"))


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_router_chaos_kill_and_drain(temperature):
    """Mid-stream replica kill at a nonzero proxy fault rate: the
    victim's spliced stream is token-identical (failover +
    deterministic re-dispatch fired), background traffic completes or
    fails typed within its deadline, the drain leg retires a survivor
    with zero errors."""
    import router_chaos

    stats = router_chaos.run(requests=12, seed=0, temperature=temperature,
                             fault_rate=0.12, verbose=False)
    # run() already asserts the acceptance contract; pin the headline
    # numbers here so a silent weakening of run() cannot pass
    assert stats["mismatches"] == 0
    assert stats["untyped_failures"] == 0
    assert stats["hangs"] == 0
    assert stats["completed"] + stats["typed_failures"] == 12
    assert stats["killed_replica"] is not None
    assert stats["redispatches"] >= 1
    assert stats["drain_ok"] is True


@pytest.mark.slow
def test_bench_router_failover_completes_across_kill(tmp_path):
    """The failover bench row: the kill leg completes EVERY request
    token-identical (availability degrades to latency, never to
    correctness) and actually exercised re-dispatch."""
    import bench_serve

    row = bench_serve.router_failover(
        requests=10, tokens=16, slots=4,
        out_path=str(tmp_path / "BENCH_SERVE.json"))
    assert row["steady"]["completed"] == 10
    assert row["steady"]["mismatches"] == 0
    assert row["failover"]["completed"] == 10
    assert row["failover"]["mismatches"] == 0
    assert row["failover"]["failovers"] >= 1


@pytest.mark.slow
def test_bench_router_affinity_beats_round_robin(tmp_path):
    """The placement bench row: on skewed shared-prefix traffic the
    prefix-affinity router's aggregate cache hit rate must beat
    round-robin (and be high in absolute terms)."""
    import bench_serve

    row = bench_serve.router_affinity(
        out_path=str(tmp_path / "BENCH_SERVE.json"))
    assert row["hit_rate_affinity"] > row["hit_rate_rr"], row
    assert row["hit_rate_affinity"] >= 0.8, row
    assert (row["prefill_tokens_affinity"]
            < row["prefill_tokens_rr"]), row


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_router_chaos_kill_active_router(temperature):
    """The router-HA chaos leg (ISSUE 14 acceptance): 2 routers + 3
    replicas, a deterministic mid-stream kill of the ACTIVE router —
    every request token-identical to the single-router run (greedy AND
    seeded) or typed within deadline, zero hangs, and the dead epoch's
    late dispatch is refused by every replica (epoch fencing)."""
    import router_chaos

    stats = router_chaos.run_router_kill(
        requests=10, seed=0, temperature=temperature, kill_at=3,
        verbose=False)
    # run_router_kill() already asserts the contract; pin the headline
    # numbers so a silent weakening cannot pass
    assert stats["mismatches"] == 0
    assert stats["untyped_failures"] == 0
    assert stats["hangs"] == 0
    assert stats["completed"] + stats["typed_failures"] == 10
    assert stats["standby_active"] and stats["takeovers"] == 1
    assert stats["new_epoch"] > stats["old_epoch"]
    assert stats["fenced_replicas"] == 3


@pytest.mark.slow
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_router_chaos_kill_prefill_mid_ship(temperature):
    """The disaggregation chaos leg (ISSUE 17 acceptance): the
    prefill-role replica is hard-killed after EXACTLY N shipped KV
    blocks of the victim's prefill.  The decode replica must never
    attend the torn ship: the victim completes token-identically via
    the decode-side re-prefill fallback (greedy AND seeded), follow-up
    traffic keeps completing on the survivor, zero hangs."""
    import router_chaos

    stats = router_chaos.run_prefill_kill(
        requests=8, seed=0, temperature=temperature, kill_blocks=2,
        verbose=False)
    # run_prefill_kill() already asserts the contract; pin the
    # headline numbers so a silent weakening cannot pass
    assert stats["mismatches"] == 0
    assert stats["untyped_failures"] == 0
    assert stats["hangs"] == 0
    assert stats["completed"] == 8
    assert stats["shipped_before_kill"] == 2
    assert stats["disagg_fallbacks"] >= 1


@pytest.mark.slow
def test_bench_serve_disagg_mixed_no_mismatch(tmp_path):
    """The disaggregation bench row: the mixed long/short leg completes
    with ZERO mismatches in both modes, actually ships blocks, and the
    decode tier's short-request TPOT p99 grows no faster with prompt
    length than colocated serving (the point of the split)."""
    import bench_serve

    row = bench_serve.disagg_ab(
        out_path=str(tmp_path / "BENCH_SERVE.json"))
    assert row["disagg"]["mismatches"] == 0, row
    assert row["colocated"]["mismatches"] == 0, row
    assert row["disagg"]["shipped_blocks"] > 0, row
    assert row["disagg"]["fallbacks"] == 0, row
    assert (row["disagg"]["tpot_p99_growth"]
            <= row["colocated"]["tpot_p99_growth"]), row


@pytest.mark.slow
def test_router_chaos_load_spike():
    """The elastic-capacity chaos leg (ISSUE 18 acceptance): a 1x ->
    4x -> 1x load wave against a live autoscaling controller — the
    tier grows under the spike and drains back to one replica, every
    guaranteed request completes token-identical (never shed), every
    best-effort request completes or sheds typed, zero hangs.  The
    fast deterministic sibling (the same ScalePolicy on scripted
    traces, zero sleeps) lives in tests/test_autoscale.py."""
    import router_chaos

    stats = router_chaos.run_load_spike(seed=0, verbose=False)
    # run_load_spike() already asserts the contract; pin the headline
    # numbers here so a silent weakening cannot pass
    assert stats["mismatches"] == 0
    assert stats["untyped_failures"] == 0
    assert stats["hangs"] == 0
    assert stats["shed_guaranteed"] == 0
    assert stats["scale_ups"] >= 1 and stats["scale_downs"] >= 1
    assert stats["spike_replicas"] > 1
    assert stats["final_replicas"] == 1
    assert (stats["best_effort_ok"] + stats["best_effort_shed"]
            + stats["guaranteed_ok"] == stats["requests"])


@pytest.mark.slow
def test_bench_autoscale_spike(tmp_path):
    """The elasticity bench row: the elastic leg scales 1 -> >1 -> 1,
    sheds ZERO guaranteed requests, sheds strictly fewer best-effort
    requests than the fixed single-replica leg under the same
    sustained spike, and keeps the guaranteed spike p99 no worse than
    fixed — elasticity converts would-be sheds into completions
    without paying for it in the guaranteed tail."""
    import bench_serve

    row = bench_serve.autoscale_spike(
        out_path=str(tmp_path / "BENCH_SERVE.json"))
    el, fx = row["autoscale"], row["fixed"]
    assert el["untyped"] == 0 and fx["untyped"] == 0
    assert el["scale_ups"] >= 1 and el["scale_downs"] >= 1
    assert el["shed_guaranteed"] == 0
    assert el["peak_replicas"] > 1 and el["final_replicas"] == 1
    assert el["shed_best_effort"] < fx["shed_best_effort"], row
    assert el["spike_p99_s"] <= fx["spike_p99_s"] * 1.1, row


@pytest.mark.slow
def test_bench_router_ha_completes_across_router_kill(tmp_path):
    """The router-HA bench row: the router-kill leg completes EVERY
    request token-identical (availability degrades to takeover-window
    latency, never to correctness) and exactly one takeover fired."""
    import bench_serve

    row = bench_serve.router_ha(
        requests=10, tokens=16, slots=4,
        out_path=str(tmp_path / "BENCH_SERVE.json"))
    assert row["steady"]["completed"] == 10
    assert row["steady"]["mismatches"] == 0
    assert row["router_kill"]["completed"] == 10
    assert row["router_kill"]["mismatches"] == 0
    assert row["router_kill"]["takeovers"] == 1
    assert row["completion_rate"] == 1.0
