"""Int8 weight-only quantized inference (inference.quantize_params +
models.transformer.QuantDense).

Decode streams every non-embedding weight per generated token, so int8
kernels halve the bandwidth bill; these tests pin the numerics: the
quantized tree must compute exactly what its dequantized-fp equivalent
computes (the int8 path is a storage format, not a different algorithm).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate, quantize_params
from byteps_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    init_cache,
)


def _model():
    cfg = TransformerConfig(
        vocab_size=61, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 12), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    return cfg, model, tokens, variables


def test_quantize_params_structure():
    cfg, model, tokens, variables = _model()
    q = quantize_params(variables["params"])
    b0 = q["block_0"]
    H, D = cfg.num_heads, cfg.d_model // cfg.num_heads
    assert b0["attn"]["q"]["kernel"].dtype == jnp.int8
    assert b0["attn"]["q"]["scale"].shape == (H, D)
    # o-projection contracts [H, D]: per-output scale is [d_model]
    assert b0["attn"]["o"]["scale"].shape == (cfg.d_model,)
    assert b0["mlp"]["up"]["scale"].shape == (cfg.d_ff,)
    assert q["lm_head"]["kernel"].dtype == jnp.int8
    assert q["lm_head"]["scale"].shape == (cfg.vocab_size,)
    # embeddings and norms untouched
    assert q["embed"]["embedding"].dtype == variables["params"]["embed"][
        "embedding"].dtype
    assert "kernel" not in q["ln_f"]
    assert q["block_0"]["ln1"]["scale"].dtype == jnp.float32


def test_quant_apply_equals_dequantized_apply():
    """int8-kernel apply == apply of the host-dequantized fp tree (same
    math, different storage)."""
    cfg, model, tokens, variables = _model()
    qparams = quantize_params(variables["params"])

    def dequant(node):
        if isinstance(node, dict):
            if "kernel" in node and node["kernel"].dtype == jnp.int8:
                out = {k: v for k, v in node.items() if k != "scale"}
                out["kernel"] = (node["kernel"].astype(jnp.float32)
                                 * node["scale"])
                return out
            return {k: dequant(v) for k, v in node.items()}
        return node

    fp_equiv = dequant(qparams)
    got = model.apply({"params": qparams}, tokens)
    want = model.apply({"params": fp_equiv}, tokens)
    # QuantDense applies the per-output-channel scale AFTER the dot
    # ((x @ q) * s — so the MXU streams s8 from HBM); the dequantized
    # tree scales before (x @ (q * s)).  Same math, different float
    # rounding order, so equality holds to reordering tolerance only.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)
    # and the quantized logits track the original fp logits closely
    orig = model.apply(variables, tokens)
    corr = np.corrcoef(np.asarray(got).ravel(),
                       np.asarray(orig).ravel())[0, 1]
    assert corr > 0.99


def test_quantize_params_tp_partitioned():
    """Quantization must survive nn.Partitioned boxes (tp-sharded trees)
    and carry the sharding names onto kernel and scale (regression:
    jnp.asarray(Partitioned) raised TypeError)."""
    import flax.linen as nn
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("dp", "tp"))
    cfg = TransformerConfig(
        vocab_size=61, num_layers=1, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, mesh=mesh)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    boxed_kernel = variables["params"]["block_0"]["attn"]["q"]["kernel"]
    assert isinstance(boxed_kernel, nn.meta.AxisMetadata)

    q = quantize_params(variables["params"])
    qk = q["block_0"]["attn"]["q"]["kernel"]
    qs = q["block_0"]["attn"]["q"]["scale"]
    assert isinstance(qk, nn.Partitioned) and qk.unbox().dtype == jnp.int8
    assert qk.names == boxed_kernel.names
    assert isinstance(qs, nn.Partitioned)
    assert qs.names == tuple(boxed_kernel.names[1:])
    # unboxed quant tree still applies (the standard tp-apply flow
    # unboxes params first, as dryrun (b) does)
    raw = nn.meta.unbox({"params": q})
    logits = model.apply(raw, tokens)
    assert np.isfinite(np.asarray(logits)).all()


def test_quant_generate():
    cfg, model, tokens, variables = _model()
    qparams = quantize_params(variables["params"])
    out = generate(model, {"params": qparams}, tokens, 6, temperature=0)
    assert out["tokens"].shape == (2, 6)
    assert ((out["tokens"] >= 0) & (out["tokens"] < 61)).all()
    # training path is untouched by quantization: fp apply still works
    # with the same module tree (no scale leaves created at init)
    assert "scale" not in variables["params"]["block_0"]["attn"]["q"]


@pytest.mark.slow  # ~11s (tier-1 duration budget); int8_kv_cache_attention_close_to_fp + gqa/tp int8 parity stay fast
def test_int8_kv_cache_decode_matches_fp_cache():
    """Generation against the int8 KV cache (kv_quant=True) matches the
    fp-cache generation on a small model — the per-(position, head)
    scales keep quantization error below argmax-flip size here — and the
    cache pytree really holds s8 K/V plus scales."""
    from byteps_tpu.models.transformer import init_cache

    cfg, model, tokens, variables = _model()
    out_fp = generate(model, variables, tokens, 12, temperature=0)
    out_q8 = generate(model, variables, tokens, 12, temperature=0,
                      kv_quant=True)
    agree = float(jnp.mean(
        (out_fp["tokens"] == out_q8["tokens"]).astype(jnp.float32)))
    assert agree == 1.0, agree

    caches = init_cache(cfg, 2, 32, quantized=True)
    assert caches[0]["k"].dtype == jnp.int8
    assert caches[0]["v"].dtype == jnp.int8
    assert caches[0]["k_scale"].shape == (2, 32, cfg.num_heads)
    assert caches[0]["v_scale"].dtype == jnp.float32


def test_int8_kv_cache_attention_close_to_fp():
    """One decode step through the quantized cache stays within int8
    quantization tolerance of the fp-cache step (logits level)."""
    from byteps_tpu.models.transformer import init_cache

    cfg, model, tokens, variables = _model()
    c_fp = init_cache(cfg, 2, 32)
    c_q8 = init_cache(cfg, 2, 32, quantized=True)
    lg_fp, c_fp = model.apply(variables, tokens, c_fp, 0, True,
                              method=Transformer.decode)
    lg_q8, c_q8 = model.apply(variables, tokens, c_q8, 0, True,
                              method=Transformer.decode)
    # the dense prefill path reads the just-quantized cache (only the
    # flash prefill fast path sees exact K/V), so prefill logits carry
    # int8 quantization error too
    err0 = float(jnp.max(jnp.abs(lg_fp - lg_q8)))
    span0 = float(jnp.max(jnp.abs(lg_fp)))
    assert err0 < 0.05 * span0, (err0, span0)
    tok = jnp.argmax(lg_fp[:, -1], axis=-1)[:, None]
    lg2_fp, _ = model.apply(variables, tok, c_fp, tokens.shape[1],
                            method=Transformer.decode)
    lg2_q8, _ = model.apply(variables, tok, c_q8, tokens.shape[1],
                            method=Transformer.decode)
    # the decode step reads the s8 cache: error bounded by 8-bit quant
    err = float(jnp.max(jnp.abs(lg2_fp - lg2_q8)))
    span = float(jnp.max(jnp.abs(lg2_fp)))
    assert err < 0.05 * span, (err, span)


def test_generate_cache_len_overallocation():
    """cache_len > T + N must give identical tokens (the causal mask
    excludes unwritten tail slots)."""
    from byteps_tpu.inference import make_generate_fn

    cfg, model, tokens, variables = _model()
    out_a = make_generate_fn(model, 8, temperature=0)(
        variables, tokens, jax.random.PRNGKey(0))
    out_b = make_generate_fn(model, 8, temperature=0, cache_len=40)(
        variables, tokens, jax.random.PRNGKey(0))
    assert (out_a["tokens"] == out_b["tokens"]).all()


def test_quant_prefill_uses_exact_kv():
    """Prefill against an int8 cache must attend the exact
    pre-quantization prompt K/V regardless of prompt length (the flash
    gcd gate only covers some lengths); quantization error enters only
    through later cache READS, so prefill logits match the fp cache's
    prefill exactly."""
    import dataclasses

    cfg = TransformerConfig(vocab_size=97, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    # 13 is coprime with 1024: the awkward-length dense prefill path
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 13), 0, 97)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    c_fp = init_cache(cfg, 2, 32)
    c_q8 = init_cache(cfg, 2, 32, quantized=True)
    lg_fp, _ = model.apply(variables, tokens, c_fp, 0,
                           method=Transformer.decode)
    lg_q8, _ = model.apply(variables, tokens, c_q8, 0,
                           method=Transformer.decode)
    # not bitwise: the fp cache's prefill sums masked scores over the
    # full cache_len while the exact-k/v path sums over the prompt only
    # — pure f32 reduction-order noise (~1e-6), nothing like the
    # length-dependent quantization error this test guards against
    # (which measures ~1e-2 at this config)
    np.testing.assert_allclose(np.asarray(lg_q8), np.asarray(lg_fp),
                               rtol=1e-5, atol=1e-5)
