"""PR 6 observability layer (docs/observability.md): metrics registry,
live scrape surfaces (HTTP + OP_STATS), bounded tracer, wire-frame
trace ids, clock-offset estimation, and the merge/report tooling —
plus the env-knob documentation lint."""

import json
import os
import re
import struct
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from byteps_tpu.common.config import reset_config
from byteps_tpu.common.tracing import Tracer, get_tracer, reset_tracer
from byteps_tpu.engine import ps_server
from byteps_tpu.engine.wire import (_decode_frame, _encode_buffers,
                                    _recv_exact)
from byteps_tpu.observability import trace as obs_trace
from byteps_tpu.observability.export import (clock_offsets_from_events,
                                             load_trace_events,
                                             merge_traces, span_durations)
from byteps_tpu.observability.metrics import (Counter, Gauge, Histogram,
                                              MetricsRegistry, get_registry,
                                              reset_registry)
from byteps_tpu.observability.scrape import (start_metrics_server,
                                             stop_metrics_server)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_state():
    reset_config()
    reset_tracer()
    yield
    for k in ("BYTEPS_TRACE_PATH", "BYTEPS_TRACE_RPC",
              "BYTEPS_TRACE_BUFFER", "BYTEPS_METRICS_PORT",
              "BYTEPS_SERVER_ENABLE_PROFILE",
              "BYTEPS_SERVER_PROFILE_OUTPUT_PATH",
              "BYTEPS_PARTITION_BYTES"):
        os.environ.pop(k, None)
    stop_metrics_server()
    reset_config()
    reset_tracer()


# ------------------------------------------------------------------ registry


class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        c = reg.counter("a.count")
        assert c.inc() == 1 and c.inc(5) == 6
        g = reg.gauge("a.gauge")
        g.set(2.5)
        assert g.value == 2.5
        g.dec(0.5)
        assert g.value == 2.0
        h = reg.histogram("a.hist")
        for v in (0.002, 0.02, 0.2):
            h.observe(v)
        assert h.count == 3 and abs(h.sum - 0.222) < 1e-9

    def test_get_or_create_identity_and_type_guard(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        assert reg.counter("x") is reg.counter("x")
        # same name, different labels = different metric
        assert reg.counter("x", shard=0) is not reg.counter("x", shard=1)
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_concurrent_increments_exact(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        c = reg.counter("hot")
        n_threads, per = 8, 2000

        def worker():
            for _ in range(per):
                c.inc()

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == n_threads * per

    def test_histogram_percentiles(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        h = reg.histogram("lat")
        for i in range(1, 101):
            h.observe(i / 1000.0)  # 1..100 ms
        assert abs(h.percentile(50) - 0.050) <= 0.002
        assert abs(h.percentile(99) - 0.099) <= 0.002
        st = h.state()
        assert st["count"] == 100
        # cumulative buckets: everything <= 0.1 bucket
        assert st["buckets"]["0.1"] == 100

    def test_histogram_reservoir_bounded(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        h = reg.histogram("ring", max_samples=64)
        for i in range(10_000):
            h.observe(float(i))
        assert h.count == 10_000
        assert len(h._samples) == 64
        # reservoir holds the most recent samples -> p50 near the tail
        assert h.percentile(50) > 9_900

    def test_snapshot_isolation(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        c = reg.counter("c")
        c.inc(3)
        snap = reg.snapshot()
        c.inc(10)
        reg.gauge("late").set(1.0)
        assert snap["counters"]["c"] == 3
        assert "late" not in snap["gauges"]

    def test_prometheus_exposition(self):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        reg.counter("wire.bytes_sent", shard=1).inc(42)
        reg.gauge("wire.inflight").set(3)
        reg.histogram("ps.handle_s").observe(0.004)
        text = reg.to_prometheus()
        assert '# TYPE byteps_wire_bytes_sent_total counter' in text
        assert 'byteps_wire_bytes_sent_total{shard="1"} 42' in text
        assert "byteps_wire_inflight 3" in text
        assert 'byteps_ps_handle_s_bucket{le="+Inf"} 1' in text
        assert "byteps_ps_handle_s_count 1" in text

    def test_subsystem_resets_clear_global_registry(self):
        """reset_* must clear the registry-backed counts, not just the
        singleton: the global registry outlives it, so a rebuilt
        accessor would otherwise report pre-reset totals."""
        reset_registry()
        from byteps_tpu.compression.stats import (get_compression_stats,
                                                  reset_compression_stats)
        from byteps_tpu.resilience import counters as rc
        from byteps_tpu.serving import metrics as sm

        rc.get_counters().bump(rc.DEDUP)
        m = sm.get_serve_metrics()
        m.bump(sm.COMPLETED)
        m.observe_request(0.1, 0.2, 0.01, 4)
        get_compression_stats().observe("w", 100, 10)

        rc.reset_counters()
        sm.reset_serve_metrics()
        reset_compression_stats()

        assert rc.get_counters().get(rc.DEDUP) == 0
        assert sm.get_serve_metrics().get(sm.COMPLETED) == 0
        assert sm.get_serve_metrics().summary().get("ttft_n", 0) == 0
        assert get_registry().get("compression.wire_bytes_sent") is None
        # and a fresh bump counts from zero, not pre-reset totals
        assert rc.get_counters().bump(rc.DEDUP) == 1

    def test_counter_mirrors_tracer_series(self, tmp_path):
        t = Tracer(path=str(tmp_path / "t.json"))
        reg = MetricsRegistry(tracer=t)
        reg.counter("resilience.retry", track="resilience").inc(shard=2)
        evs = t.events()
        kinds = {e["ph"] for e in evs}
        assert kinds == {"i", "C"}  # instant + counter track, as before
        inst = [e for e in evs if e["ph"] == "i"][0]
        assert inst["tid"] == "resilience" and inst["args"]["shard"] == 2


# ----------------------------------------------------------------- tracer


class TestBoundedTracer:
    def test_rollover_incremental_flush_valid_json(self, tmp_path):
        path = str(tmp_path / "t.json")
        t = Tracer(path=path, max_events=10)
        for i in range(25):
            t.instant(f"e{i}", "s")
        # two rollovers happened; buffer holds the remainder
        assert len(t.events()) == 5
        # batches land via the background writer: poll for the mid-run
        # file (valid JSON BETWEEN flushes is the crash-safety contract)
        deadline = time.monotonic() + 10.0
        mid = {"traceEvents": []}
        while time.monotonic() < deadline:
            try:
                mid = json.load(open(path))
            except (OSError, ValueError):
                pass
            if len(mid["traceEvents"]) == 20:
                break
            time.sleep(0.01)
        assert len(mid["traceEvents"]) == 20
        t.flush()  # drains the writer first, then appends the tail
        evs = json.load(open(path))["traceEvents"]
        assert [e["name"] for e in evs] == [f"e{i}" for i in range(25)]
        assert t.dropped == 0

    def test_failed_write_drops_loudly(self, tmp_path):
        reset_registry()
        path = str(tmp_path / "missing_dir" / "t.json")
        t = Tracer(path=path, max_events=4)
        for i in range(9):
            t.instant(f"e{i}", "s")
        t._drain_writer()  # drops happen on the background writer
        assert t.dropped == 8  # two failed 4-event batches
        dropped = get_registry().get("trace.events_dropped")
        assert dropped is not None and dropped.value == 8

    def test_flush_empty_enabled_tracer_writes_valid_file(self, tmp_path):
        path = str(tmp_path / "empty.json")
        t = Tracer(path=path)
        assert t.flush() == path
        assert json.load(open(path)) == {"traceEvents": []}

    def test_complete_spans_use_wall_anchor(self, tmp_path):
        import time

        t = Tracer(path=str(tmp_path / "t.json"))
        t0 = time.perf_counter()
        t.complete("after_the_fact", "wire", t0, 0.001, trace_id="ab")
        ev = t.events()[0]
        # wall-anchored: microseconds since epoch, i.e. ~now * 1e6
        assert abs(ev["ts"] / 1e6 - time.time()) < 5.0
        assert ev["dur"] == pytest.approx(1000.0)
        assert ev["args"]["trace_id"] == "ab"


# ------------------------------------------------------------ wire trace ids


class _Pipe:
    """Minimal socket stand-in feeding _decode_frame from bytes."""

    def __init__(self, data: bytes):
        self._data = memoryview(bytearray(data))
        self._pos = 0

    def recv_into(self, buf, n):
        n = min(n, len(self._data) - self._pos)
        buf[:n] = self._data[self._pos:self._pos + n]
        self._pos += n
        return n


class TestWireExtension:
    def _roundtrip(self, bufs):
        import socket as s

        a, b = s.socketpair()
        try:
            a.sendall(b"".join(bytes(x) for x in bufs))
            return _decode_frame(b)
        finally:
            a.close()
            b.close()

    def test_trace_id_roundtrip(self):
        tid = bytes(range(8))
        arr = np.arange(6, dtype=np.float32)
        bufs = _encode_buffers(2, "grad/w", arr, trace_id=tid)
        op, name, out, _, got = self._roundtrip(bufs)
        assert (op, name, got) == (2, "grad/w", tid)
        np.testing.assert_array_equal(np.asarray(out).reshape(-1), arr)

    def test_unextended_frame_is_bit_identical_to_seed(self):
        arr = np.ones(3, np.float32)
        plain = b"".join(bytes(b) for b in _encode_buffers(1, "x", arr))
        # no extension flag byte anywhere in the head
        assert plain[0] == 1
        op, name, out, _, tid = self._roundtrip(_encode_buffers(1, "x", arr))
        assert tid == b"" and op == 1

    def test_bad_trace_id_length_raises(self):
        with pytest.raises(ValueError, match="8 bytes"):
            _encode_buffers(1, "x", None, trace_id=b"short")

    def test_unknown_extension_version_raises(self):
        import socket as s

        tid = b"\x01" * 8
        bufs = _encode_buffers(1, "x", None, trace_id=tid)
        head = bytearray(bytes(bufs[0]))
        head[5] = 99  # extension version byte
        a, b = s.socketpair()
        try:
            a.sendall(bytes(head) + b"".join(bytes(x) for x in bufs[1:]))
            with pytest.raises(ValueError, match="extension version 99"):
                _decode_frame(b)
        finally:
            a.close()
            b.close()


# ------------------------------------------------------- scrape round trips


def _spawn_server():
    srv, _ = ps_server.serve(0, host="127.0.0.1", use_native=False,
                             in_thread=True)
    return srv, f"127.0.0.1:{srv.server_address[1]}"


class TestScrape:
    def test_http_endpoint_roundtrip(self):
        reset_registry()
        get_registry().counter("test.scraped").inc(7)
        srv = start_metrics_server(0, host="127.0.0.1", role="tester",
                                   health_fn=lambda: {"detail": 1})
        try:
            base = f"http://127.0.0.1:{srv.port}"
            text = urllib.request.urlopen(base + "/metrics").read().decode()
            assert "byteps_test_scraped_total 7" in text
            snap = json.loads(
                urllib.request.urlopen(base + "/metrics.json").read())
            assert snap["counters"]["test.scraped"] == 7
            health = json.loads(
                urllib.request.urlopen(base + "/healthz").read())
            assert health["status"] == "ok"
            assert health["role"] == "tester" and health["detail"] == 1
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(base + "/nope")
        finally:
            srv.shutdown()
            srv.server_close()

    def test_health_fn_error_does_not_500(self):
        def broken():
            raise RuntimeError("probe died")

        srv = start_metrics_server(0, host="127.0.0.1", health_fn=broken)
        try:
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz").read())
            assert health["status"] == "ok"
            assert "probe died" in health["health_fn_error"]
        finally:
            srv.shutdown()
            srv.server_close()

    def test_op_stats_roundtrip(self):
        srv, addr = _spawn_server()
        store = ps_server.RemoteStore([addr])
        try:
            store.init_tensor("w", np.ones(8, np.float32))
            st = store.shard_stats(0)
            assert st["role"] == "ps_server" and st["tensors"] == 1
            assert st["uptime_s"] >= 0
            # the snapshot is built before the STATS request's own
            # increment, so only the preceding INIT is visible
            assert st["metrics"]["counters"]["ps.requests"] >= 1
        finally:
            store.close()
            srv.shutdown()

    def test_ping_reply_carries_server_clock(self):
        import socket as s
        import time

        srv, addr = _spawn_server()
        try:
            host, port = addr.rsplit(":", 1)
            with s.create_connection((host, int(port)), timeout=5) as sock:
                sock.sendall(ps_server._encode(ps_server.OP_PING, "", None))
                status, _, _, payload = ps_server._decode(sock)
            assert status == 0
            (t_server,) = struct.unpack_from("<d", payload)
            assert abs(t_server - time.time()) < 60
        finally:
            srv.shutdown()

    def test_clock_offset_estimation(self):
        srv, addr = _spawn_server()
        try:
            off = obs_trace.estimate_clock_offset(addr, n=3)
            # same host, same clock: the offset is bounded by the RTT
            assert abs(off.offset_s) < max(off.rtt_s, 0.5)
            assert off.samples == 3
        finally:
            srv.shutdown()


# -------------------------------------------- end-to-end trace correlation


class TestTraceCorrelation:
    def _run_traced_op(self, tmp_path, n_shards=2):
        trace_path = str(tmp_path / "client.json")
        prof_path = str(tmp_path / "server.json")
        os.environ["BYTEPS_TRACE_PATH"] = trace_path
        os.environ["BYTEPS_SERVER_ENABLE_PROFILE"] = "1"
        os.environ["BYTEPS_SERVER_PROFILE_OUTPUT_PATH"] = prof_path
        # 2 parts across shards: every frame must carry the op's ONE id
        os.environ["BYTEPS_PARTITION_BYTES"] = "8192"
        reset_config()
        reset_tracer()
        servers = [_spawn_server() for _ in range(n_shards)]
        addrs = [a for _, a in servers]
        store = ps_server.RemoteStore(addrs)
        x = np.ones(4096, np.float32)
        store.init_tensor("w", x)
        store.push_pull("w", x)
        store.record_clock_offsets(samples=2)
        store.close()
        for srv, _ in servers:
            if srv.profiler is not None:
                srv.profiler.close()
            srv.shutdown()
        get_tracer().flush()
        return trace_path, prof_path, addrs

    def test_trace_id_propagates_client_to_server(self, tmp_path):
        trace_path, prof_path, addrs = self._run_traced_op(tmp_path)
        client_evs = load_trace_events(trace_path)
        ops = {e["args"]["trace_id"]: e["name"] for e in client_evs
               if e.get("ph") == "X" and e.get("tid") == "client"
               and e.get("args", {}).get("trace_id")}
        pp_ids = [tid for tid, name in ops.items()
                  if name.startswith("push_pull")]
        assert len(pp_ids) == 1
        server_evs = load_trace_events(prof_path)
        server_ids = {e["args"]["trace_id"] for e in server_evs
                      if e.get("args", {}).get("trace_id")}
        assert pp_ids[0] in server_ids
        # client-queue and wire sub-spans carry the same id
        stages = {e["tid"] for e in client_evs
                  if e.get("args", {}).get("trace_id") == pp_ids[0]}
        assert {"client", "client-queue", "wire"} <= stages
        # clock offsets were recorded in-band for the merge tool
        offs = clock_offsets_from_events(client_evs)
        assert set(offs) == set(addrs)

    def test_trace_merge_cli(self, tmp_path):
        trace_path, prof_path, addrs = self._run_traced_op(tmp_path)
        out = str(tmp_path / "merged.json")
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/trace_merge.py"),
             "--client", trace_path,
             "--server", f"{addrs[0]}={prof_path}",
             "-o", out, "--by-trace"],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        doc = json.load(open(out))
        evs = doc["traceEvents"]
        pids = {e["pid"] for e in evs}
        assert len(pids) >= 3  # client + server + by-trace-id groups
        by_trace = [e for e in evs if e.get("ph") != "M"
                    and isinstance(e.get("tid"), str)
                    and re.fullmatch(r"[0-9a-f]{16}", str(e["tid"]))]
        assert by_trace, "no per-trace-id rows in --by-trace output"
        # every by-trace span is COMPLETE ('X'): raw B events would
        # render as unterminated did-not-finish spans in Perfetto
        # (server E events carry no trace_id to pair them)
        assert all(e["ph"] in ("X", "i") for e in by_trace)
        # client and server spans meet under at least one shared id:
        # server-derived spans carry the profiler's args.tensor, client
        # spans don't
        rows = {}
        for e in by_trace:
            if e["ph"] != "X":
                continue
            origin = "server" if "tensor" in e.get("args", {}) else "client"
            rows.setdefault(e["tid"], set()).add(origin)
        assert any({"client", "server"} <= o for o in rows.values())

    def test_trace_report_cli(self, tmp_path):
        trace_path, _, _ = self._run_traced_op(tmp_path)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/trace_report.py"),
             trace_path],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "slowest keys" in proc.stdout
        assert "per-stage time breakdown" in proc.stdout
        assert "client-queue" in proc.stdout

    def test_trace_report_metrics_dump(self, tmp_path):
        reg = MetricsRegistry(tracer=Tracer(path=""))
        reg.counter("c").inc(4)
        reg.histogram("h").observe(0.01)
        p = tmp_path / "metrics.json"
        p.write_text(json.dumps(reg.snapshot()))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/trace_report.py"),
             str(p)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "counters" in proc.stdout and "p99" in proc.stdout

    def test_span_durations_matches_be_pairs(self):
        evs = [{"ph": "B", "pid": 1, "tid": 1, "name": "op", "ts": 10.0},
               {"ph": "E", "pid": 1, "tid": 1, "name": "op", "ts": 35.0},
               {"ph": "X", "pid": 1, "tid": "wire", "name": "w",
                "ts": 0.0, "dur": 7.0}]
        rows = span_durations(evs)
        assert ("op", "1", 25.0) in rows and ("w", "wire", 7.0) in rows

    def test_merge_shifts_by_offset(self):
        client = [{"ph": "X", "name": "a", "ts": 100.0, "dur": 1.0,
                   "tid": "t", "args": {}}]
        server = [{"ph": "X", "name": "b", "ts": 1100.0, "dur": 1.0,
                   "tid": "t", "args": {}}]
        doc = merge_traces([("client", client, 0.0),
                            ("server", server, 1000.0)])
        by_name = {e["name"]: e for e in doc["traceEvents"]
                   if e.get("ph") == "X"}
        assert by_name["a"]["ts"] == 100.0
        assert by_name["b"]["ts"] == 100.0  # aligned onto client axis


# ----------------------------------------------------------- serving hooks


class TestServingObservability:
    def test_submit_mints_trace_id_and_finish_span(self, tmp_path):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp

        from byteps_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        from byteps_tpu.serving import ServeMetrics, ServingEngine
        from byteps_tpu.serving import metrics as sm

        os.environ["BYTEPS_TRACE_PATH"] = str(tmp_path / "serve.json")
        reset_config()
        reset_tracer()
        cfg = TransformerConfig(vocab_size=64, num_layers=1, num_heads=2,
                                d_model=32, d_ff=64, max_seq_len=64,
                                dtype=jnp.float32)
        model = Transformer(cfg)
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 4), jnp.int32))
        metrics = ServeMetrics()
        engine = ServingEngine(model, variables, n_slots=2, max_seq=64,
                               temperature=0.0, metrics=metrics)
        req = engine.submit(np.arange(4, dtype=np.int32), 3)
        assert re.fullmatch(r"[0-9a-f]{16}", req.trace_id)
        while req.state.value in ("queued", "prefilling", "active"):
            engine.step()
        assert req.state.value == "done"
        spans = [e for e in get_tracer().events()
                 if e.get("args", {}).get("trace_id") == req.trace_id]
        assert any(e["name"] == f"serve:req{req.id}" for e in spans)
        # credit-level gauge is live in the engine's registry
        credits = metrics.registry.get(sm.PREFILL_CREDITS)
        assert credits is not None and credits.value > 0

    def test_serve_metrics_histograms_back_summary(self):
        from byteps_tpu.serving.metrics import ServeMetrics

        m = ServeMetrics(tracer=Tracer(path=""))
        for i in range(10):
            m.observe_request(queue_wait_s=0.001 * i, ttft_s=0.01 * (i + 1),
                              tpot_s=0.002, tokens=4)
        s = m.summary()
        assert s["ttft_n"] == 10
        assert 0.04 <= s["ttft_p50_s"] <= 0.07
        # registry histograms are scrape-visible
        snap = m.registry.snapshot()
        assert snap["histograms"]["serve.ttft_s"]["count"] == 10


# The env.md knob lint that lived here (PR 6's
# test_every_config_knob_is_documented_in_env_md) moved into the
# analysis subsystem: byteps_tpu/analysis/envknobs.py, exercised by
# tests/test_analysis.py::test_every_config_knob_documented and
# scripts/lint.py — AST-accurate, and extended to flag raw BYTEPS_*
# environ reads anywhere in the package.


# ------------------------------------------------------------ bench (slow)


@pytest.mark.slow
def test_bench_obs_overhead():
    """Full observability ON must cost < 3% step time on the wire path
    and < 3% burst time on the serve path (paired-median protocol —
    see bench_obs.py's module doc for why min-of-reps cannot resolve
    this on a throttled host)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_obs.py"),
         "--steps", "30", "--pairs", "9", "--requests", "6",
         "--tokens", "16", "--no-archive"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    by_metric = {r["metric"]: r for r in rows}
    wire = by_metric["obs_overhead_wire"]
    serve = by_metric["obs_overhead_serve"]
    assert wire["overhead_pct"] < 3.0, wire
    assert serve["overhead_pct"] < 3.0, serve
