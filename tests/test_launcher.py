"""Launcher env-contract tests (reference launcher/launch.py:10-64)."""

import subprocess
import sys

import pytest

from byteps_tpu.launcher import build_child_env, main


def test_build_child_env_single_worker():
    env = {"DMLC_ROLE": "worker", "DMLC_NUM_WORKER": "1"}
    child = build_child_env(env)
    assert child["BYTEPS_LOCAL_RANK"] == "0"
    assert "BYTEPS_DISTRIBUTED_INIT" not in child


def test_build_child_env_multi_worker():
    env = {
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": "4",
        "DMLC_WORKER_ID": "2",
        "DMLC_PS_ROOT_URI": "10.0.0.1",
        "DMLC_PS_ROOT_PORT": "9000",
    }
    child = build_child_env(env)
    assert child["BYTEPS_COORDINATOR_ADDR"] == "10.0.0.1:9000"
    assert child["BYTEPS_NUM_PROCESSES"] == "4"
    assert child["BYTEPS_PROCESS_ID"] == "2"
    assert child["BYTEPS_DISTRIBUTED_INIT"] == "1"


def test_server_role_exits_cleanly(monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "server")
    assert main(["python", "-c", "pass"]) == 0


def test_missing_env_raises(monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "worker")
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    for k in ("DMLC_WORKER_ID", "DMLC_PS_ROOT_URI", "DMLC_PS_ROOT_PORT"):
        monkeypatch.delenv(k, raising=False)
    with pytest.raises(SystemExit):
        main(["python", "-c", "pass"])


def test_launcher_runs_command(monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "worker")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    assert main([sys.executable, "-c", "import os; assert os.environ['BYTEPS_LOCAL_RANK'] == '0'"]) == 0


def test_server_role_supervision_restarts_crashed_shard():
    """BYTEPS_SERVER_MAX_RESTARTS: the server role restarts a crashed PS
    shard (fresh serve() call, same port) up to the budget, then gives
    up with exit 1."""
    from byteps_tpu.launcher import _serve_supervised

    calls = []

    def crashy_serve(port):
        calls.append(port)
        if len(calls) < 3:
            raise OSError("simulated shard crash")

    env = {"BYTEPS_SERVER_MAX_RESTARTS": "5",
           "BYTEPS_SERVER_RESTART_BACKOFF_MS": "1"}
    assert _serve_supervised(crashy_serve, 1234, env) == 0
    assert calls == [1234, 1234, 1234]  # crashed twice, third run served

    calls.clear()

    def always_crash(port):
        calls.append(port)
        raise OSError("boom")

    env = {"BYTEPS_SERVER_MAX_RESTARTS": "2",
           "BYTEPS_SERVER_RESTART_BACKOFF_MS": "1"}
    assert _serve_supervised(always_crash, 1234, env) == 1
    assert len(calls) == 3  # initial try + 2 restarts

    # default: old die-on-crash behavior (no restarts)
    calls.clear()
    assert _serve_supervised(always_crash, 1234, {}) == 1
    assert len(calls) == 1
