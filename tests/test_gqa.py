"""Grouped-query attention (GQA/MQA) end-to-end.

``TransformerConfig.num_kv_heads`` shares each K/V head across a group
of query heads — shrinking the KV cache (decode's second-largest HBM
stream) by ``num_heads / num_kv_heads``.  The reference has no GQA
(2019-era models); this is the TPU-first decode-bandwidth lever.  These
tests pin the contract: grouped == materialized-repeat on every path
(train local/flash, cached prefill/decode, int8 cache, generation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import make_generate_fn
from byteps_tpu.models import Transformer, TransformerConfig
from byteps_tpu.models.transformer import (
    _cached_attention,
    _cached_attention_q8,
    _quantize_kv,
    init_cache,
)

KW = dict(vocab_size=64, num_layers=2, d_model=32, d_ff=64,
          max_seq_len=64, dtype=jnp.float32)


def test_bad_group_factor_raises():
    cfg = TransformerConfig(num_heads=4, num_kv_heads=3, **KW)
    with pytest.raises(ValueError, match="divide"):
        _ = cfg.kv_heads


def test_cache_shape_carries_kv_heads():
    cfg = TransformerConfig(num_heads=8, num_kv_heads=2, **KW)
    caches = init_cache(cfg, 3, 16)
    assert caches[0]["k"].shape == (3, 16, 2, KW["d_model"] // 8)


@pytest.mark.parametrize("kv", [1, 2, 4])
def test_grouped_cached_attention_matches_repeat(kv):
    """The grouped dot against the un-repeated cache == dense attention
    against the cache with K/V heads explicitly repeated."""
    B, tq, H, D, S, pos = 2, 3, 4, 8, 12, 5
    rng = np.random.RandomState(kv)
    q = jnp.asarray(rng.randn(B, tq, H, D), jnp.float32)
    ck = jnp.asarray(rng.randn(B, S, kv, D), jnp.float32)
    cv = jnp.asarray(rng.randn(B, S, kv, D), jnp.float32)
    out = _cached_attention(q, ck, cv, pos)
    ref = _cached_attention(q, jnp.repeat(ck, H // kv, axis=2),
                            jnp.repeat(cv, H // kv, axis=2), pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)


def test_grouped_q8_cached_attention_matches_repeat():
    B, tq, H, kv, D, S, pos = 2, 1, 4, 2, 8, 12, 7
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(B, tq, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, kv, D), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, kv, D), jnp.float32)
    kq, ks = _quantize_kv(k)
    vq, vs = _quantize_kv(v)
    out = _cached_attention_q8(q, kq, ks, vq, vs, pos)
    rep = lambda x: jnp.repeat(x, H // kv, axis=2)  # noqa: E731
    ref = _cached_attention_q8(q, rep(kq), rep(ks), rep(vq), rep(vs), pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_groups_of_one_is_mha():
    """num_kv_heads == num_heads produces the identical parameter tree
    and identical outputs to num_kv_heads=None (pure MHA)."""
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
    cfg_a = TransformerConfig(num_heads=4, num_kv_heads=4, **KW)
    cfg_b = TransformerConfig(num_heads=4, **KW)
    va = Transformer(cfg_a).init(jax.random.PRNGKey(0), toks)
    vb = Transformer(cfg_b).init(jax.random.PRNGKey(0), toks)
    assert (jax.tree_util.tree_structure(va)
            == jax.tree_util.tree_structure(vb))
    np.testing.assert_array_equal(
        np.asarray(Transformer(cfg_a).apply(va, toks)),
        np.asarray(Transformer(cfg_b).apply(vb, toks)))


@pytest.mark.parametrize("kv", [1, 2])
def test_gqa_decode_matches_full_forward(kv):
    """Cached prefill + per-token decode reproduces the no-cache full
    forward exactly (the causal-consistency contract, now under GQA)."""
    cfg = TransformerConfig(num_heads=4, num_kv_heads=kv, **KW)
    m = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), toks)
    full = m.apply(vs, toks)
    caches = init_cache(cfg, 2, 16)
    lg, caches = m.apply(vs, toks[:, :6], caches, 0, False,
                         method=Transformer.decode)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, :6]),
                               atol=2e-5, rtol=2e-5)
    for i in range(6, 10):
        lg, caches = m.apply(vs, toks[:, i:i + 1], caches, i, False,
                             method=Transformer.decode)
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full[:, i]),
            atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # ~8s: naive reference decode loop (tier-1 duration budget); groups_of_one_is_mha + grouped_q8_cached stay fast
def test_gqa_generate_matches_naive_and_int8_cache():
    cfg = TransformerConfig(num_heads=4, num_kv_heads=1, **KW)
    m = Transformer(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), prompt)
    out = make_generate_fn(m, 6, temperature=0)(
        vs, prompt, jax.random.PRNGKey(0))
    toks = prompt
    for _ in range(6):
        lg = m.apply(vs, toks)
        toks = jnp.concatenate([toks, jnp.argmax(lg[:, -1:], -1)], 1)
    np.testing.assert_array_equal(np.asarray(out["tokens"]),
                                  np.asarray(toks[:, 8:]))
    outq = make_generate_fn(m, 6, temperature=0, kv_quant=True)(
        vs, prompt, jax.random.PRNGKey(0))
    # int8 cache quantization can flip a near-tie argmax; on this tiny
    # fixed seed it does not
    np.testing.assert_array_equal(np.asarray(outq["tokens"]),
                                  np.asarray(out["tokens"]))


def test_gqa_flash_training_matches_local():
    """attn_impl='flash' consumes grouped K/V natively (no repeat); the
    training forward matches the local-attention model bit-for-bit in
    fp32 interpret mode."""
    kw = dict(KW, max_seq_len=128)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
    cfg_f = TransformerConfig(num_heads=4, num_kv_heads=2,
                              attn_impl="flash", **kw)
    cfg_l = TransformerConfig(num_heads=4, num_kv_heads=2,
                              attn_impl="local", **kw)
    vs = Transformer(cfg_l).init(jax.random.PRNGKey(0), toks)
    expected = Transformer(cfg_l).apply(vs, toks)
    got = Transformer(cfg_f).apply(vs, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=3e-5, rtol=3e-5)


def test_gqa_train_grads_flow():
    """One SGD step on the GQA model moves every parameter (k/v kernels
    included) and decreases loss on a fixed batch."""
    import optax

    from byteps_tpu.training import lm_loss_fn

    cfg = TransformerConfig(num_heads=4, num_kv_heads=2, **KW)
    m = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), toks)
    lf = lm_loss_fn(m)
    tx = optax.sgd(0.5)

    def loss(p):
        return lf(p, {}, {"tokens": toks})[0]

    params = vs["params"]
    opt = tx.init(params)
    l0, grads = jax.value_and_grad(loss)(params)
    gnorms = [float(jnp.linalg.norm(g))
              for g in jax.tree_util.tree_leaves(grads)]
    assert all(n > 0 for n in gnorms)
    for _ in range(5):
        _, grads = jax.value_and_grad(loss)(params)
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < float(l0)
