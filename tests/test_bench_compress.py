"""CI wiring for bench_compress.py (slow bucket, like test_chaos_smoke):
the acceptance-criteria numbers must hold on the measured wire path —
>=4x byte reduction for onebit/topk vs the bf16 baseline, with loss
parity on the small-transformer training leg.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


@pytest.mark.slow
def test_bench_compress_reduction_and_parity(tmp_path):
    import bench_compress

    result = bench_compress.run(steps=30, sweeps=2,
                                out_path=str(tmp_path / "BENCH.json"))

    wire = result["wire"]
    # acceptance: >=4x fewer measured wire bytes than the bf16 cast
    assert wire["onebit"]["reduction_vs_bf16"] >= 4.0, wire["onebit"]
    assert wire["topk"]["reduction_vs_bf16"] >= 4.0, wire["topk"]
    assert wire["randomk"]["reduction_vs_bf16"] >= 4.0, wire["randomk"]
    # sanity: the cast halves fp32 exactly (modulo frame headers)
    assert 1.9 < wire["bf16"]["reduction_vs_raw"] <= 2.1

    parity = result["parity"]
    for scheme in ("bf16", "onebit", "topk"):
        r = parity[scheme]
        # loss-parity within tolerance: the compressed run achieves at
        # least 70% of the uncompressed loss drop and ends within 0.1
        # nats of it (EF is what makes this hold for onebit/topk)
        assert r["progress_vs_none"] >= 0.7, (scheme, r)
        assert r["final_gap_vs_none"] <= 0.1, (scheme, r)
