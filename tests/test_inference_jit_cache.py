"""LRU bookkeeping of the AUTO-layout jit cache (inference.py
``_AutoLayoutCache``, the machinery behind ``_layout_aware_jit``).

The real compile path only runs on TPU (int8 trees + AUTO input
layouts), but the cache semantics — executable LRU eviction order,
alternating placed-copy reuse, and the evict-BEFORE-place invariant
that bounds live full-parameter device copies — are pure bookkeeping,
unit-tested here on CPU by stubbing the compile and placement hooks.
"""

import numpy as np
import pytest

from byteps_tpu.inference import _AutoLayoutCache


def _tree(seed, shape=(4,)):
    rng = np.random.RandomState(seed)
    return {"w": rng.rand(*shape).astype(np.float32),
            "b": rng.rand(2).astype(np.float32)}


def _prompt(n):
    return np.zeros((1, n), np.int32)


_RNG = np.zeros(2, np.uint32)


class _Stub:
    """Injectable compile/place hooks with call accounting."""

    def __init__(self):
        self.compiles = []
        self.places = []

    def compile_fn(self, variables, prompt, rng):
        self.compiles.append(prompt.shape)

        def compiled(pvars, p, r):
            return ("out", p.shape)

        # formats[0] feeds variable placement, [1]/[2] prompt/rng
        return compiled, ("fmt_vars", "fmt_prompt", "fmt_rng")

    def place_fn(self, tree_or_args, fmt):
        self.places.append((type(tree_or_args).__name__, fmt))
        if isinstance(tree_or_args, tuple):
            return tree_or_args  # (prompt, rng) passthrough
        return tree_or_args


def test_compiled_lru_eviction_order():
    """Exceeding max_compiled evicts the LEAST recENTLY USED entry; a
    cache hit refreshes recency."""
    stub = _Stub()
    cache = _AutoLayoutCache(stub.compile_fn, stub.place_fn,
                             max_compiled=2, max_placed=2)
    tree = _tree(0)
    cache(tree, _prompt(8), _RNG)    # compile A
    cache(tree, _prompt(16), _RNG)   # compile B
    assert len(stub.compiles) == 2
    cache(tree, _prompt(8), _RNG)    # hit A -> A most recent
    assert len(stub.compiles) == 2   # no recompile on hit
    cache(tree, _prompt(32), _RNG)   # compile C -> evicts B (LRU)
    assert len(cache.cache) == 2
    kept = {k[2] for k in cache.cache}          # prompt shapes kept
    assert kept == {(1, 8), (1, 32)}
    cache(tree, _prompt(16), _RNG)   # B again -> must recompile
    assert len(stub.compiles) == 4
    assert [s for s in stub.compiles] == [(1, 8), (1, 16), (1, 32),
                                          (1, 16)]


def test_alternating_trees_reuse_placed_copies():
    """Two distinct same-shape trees alternating must each be placed
    exactly once (max_placed=2 keeps both alive) — the A/B serving
    pattern must not re-device_put the full params per call."""
    stub = _Stub()
    cache = _AutoLayoutCache(stub.compile_fn, stub.place_fn,
                             max_compiled=2, max_placed=2)
    a, b = _tree(1), _tree(2)
    for _ in range(3):
        cache(a, _prompt(8), _RNG)
        cache(b, _prompt(8), _RNG)
    # one compile (same shapes), two variable placements (one per tree);
    # every further call placed only the (prompt, rng) tuple
    assert len(stub.compiles) == 1
    var_places = [p for p in stub.places if p[0] == "dict"]
    assert len(var_places) == 2
    entry = next(iter(cache.cache.values()))
    assert len(entry[2]) == 2  # both placed copies alive


def test_placed_copy_keyed_on_every_leaf_identity():
    """A tree sharing only its FIRST leaf with a placed one is a
    different tree — it must be re-placed, not reuse the hit."""
    stub = _Stub()
    cache = _AutoLayoutCache(stub.compile_fn, stub.place_fn,
                             max_compiled=2, max_placed=2)
    a = _tree(3)
    cache(a, _prompt(8), _RNG)
    shared_first = {"w": a["w"], "b": a["b"].copy()}  # same w, new b
    cache(shared_first, _prompt(8), _RNG)
    var_places = [p for p in stub.places if p[0] == "dict"]
    assert len(var_places) == 2


def test_evict_before_place_invariant():
    """Placing a third distinct tree must evict the LRU placed copy
    BEFORE the new device_put runs — at no instant may more than
    max_placed full device copies be alive (the OOM hazard for params
    near half of HBM)."""
    stub = _Stub()
    cache = _AutoLayoutCache(stub.compile_fn, None, max_compiled=2,
                             max_placed=2)
    seen_at_place = []

    def place_fn(tree_or_args, fmt):
        if isinstance(tree_or_args, dict):
            entry = next(iter(cache.cache.values()))
            # count of ALREADY-placed copies while the new one is being
            # created: must leave room (<= max_placed - 1)
            seen_at_place.append(len(entry[2]))
        return tree_or_args

    cache.place_fn = place_fn
    a, b, c = _tree(4), _tree(5), _tree(6)
    cache(a, _prompt(8), _RNG)
    cache(b, _prompt(8), _RNG)
    cache(c, _prompt(8), _RNG)   # must evict a's copy FIRST
    assert seen_at_place == [0, 1, 1]   # never 2 at place time
    entry = next(iter(cache.cache.values()))
    assert len(entry[2]) == 2
    # and the eviction was LRU: re-placing a costs a new place, b is
    # gone too (a's re-place evicted it... LRU order: after c placed,
    # alive = {b, c}; 'a' again evicts b)
    cache(a, _prompt(8), _RNG)
    assert seen_at_place == [0, 1, 1, 1]
    cache(c, _prompt(8), _RNG)   # c still alive -> no new placement
    assert seen_at_place == [0, 1, 1, 1]
    cache(b, _prompt(8), _RNG)   # b was evicted -> placed again
    assert seen_at_place == [0, 1, 1, 1, 1]


def test_layout_aware_jit_exposes_cache_and_cpu_fallback():
    """The public wrapper takes the plain-jit path for float trees on
    CPU (no AUTO-layout machinery engaged) and exposes its LRU cache
    for introspection when the layout API exists."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.inference import _layout_aware_jit

    def run(variables, prompt, rng):
        return prompt * variables["s"]

    fn = _layout_aware_jit(run)
    out = fn({"s": jnp.ones((), jnp.float32)}, jnp.ones((2,)), _RNG)
    np.testing.assert_allclose(np.asarray(out), np.ones(2))
    cache = getattr(fn, "_cache", None)
    if cache is not None:  # layout API present in this jax
        assert len(cache.cache) == 0  # float tree never engaged AUTO
        assert cache.max_compiled == 8 and cache.max_placed == 2
