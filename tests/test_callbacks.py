"""Callback/schedule tests (reference _keras/callbacks.py behaviors)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from byteps_tpu.training.callbacks import (
    momentum_corrected_sgd,
    multiplier_schedule,
    scaled_lr,
    warmup_schedule,
)


def test_warmup_ramps_to_scaled_lr():
    sched = warmup_schedule(base_lr=0.1, world_size=8, warmup_steps=10)
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(10)), 0.8, rtol=1e-6)
    np.testing.assert_allclose(float(sched(5)), 0.1 + 0.7 * 0.5, rtol=1e-6)
    # holds at peak after warmup
    np.testing.assert_allclose(float(sched(100)), 0.8, rtol=1e-6)


def test_warmup_hands_off_to_after_schedule():
    after = optax.constant_schedule(0.01)
    sched = warmup_schedule(0.1, 4, 10, after=after)
    np.testing.assert_allclose(float(sched(20)), 0.01, rtol=1e-6)


def test_multiplier_schedule_staircase():
    sched = multiplier_schedule(0.4, {30: 0.1, 60: 0.01})
    np.testing.assert_allclose(float(sched(0)), 0.4, rtol=1e-6)
    np.testing.assert_allclose(float(sched(30)), 0.04, rtol=1e-6)
    np.testing.assert_allclose(float(sched(61)), 0.004, rtol=1e-6)


def test_momentum_correction_rescales_velocity():
    """After an LR change the velocity is scaled by lr1/lr0 (reference
    _keras/callbacks.py:143-171)."""
    lrs = {0: 1.0}  # base 0.1, drops 10x at step 2
    sched = multiplier_schedule(0.1, {2: 0.1})
    tx = momentum_corrected_sgd(sched, momentum=0.9)
    params = {"w": jnp.zeros(1)}
    state = tx.init(params)
    g = {"w": jnp.ones(1)}

    # step 0: lr=0.1, trace=1, update=-0.1
    up, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(up["w"]), [-0.1], rtol=1e-6)
    # step 1: lr=0.1, trace=0.9*1*1 + 1=1.9
    up, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(up["w"]), [-0.19], rtol=1e-6)
    # step 2: lr drops to 0.01 -> correction 0.1: trace=0.9*1.9*0.1+1=1.171
    up, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(up["w"]), [-0.01171], rtol=1e-5)


def test_momentum_corrected_sgd_trains():
    sched = warmup_schedule(0.05, 2, 5)
    tx = momentum_corrected_sgd(sched, momentum=0.9)
    params = jnp.array([5.0])
    state = tx.init(params)
    for _ in range(200):
        grads = params  # minimize 0.5*x^2
        updates, state = tx.update(grads, state)
        params = optax.apply_updates(params, updates)
    assert abs(float(params[0])) < 0.1
