"""Speculative decoding (inference.speculative_generate).

The algorithm's defining property: greedy speculative output is EXACTLY
the target model's own greedy output — the draft model only changes
speed, never content.  These tests pin that for agreeing drafts (draft ==
target), disagreeing drafts (independent random models), and partial
agreement, plus EOS freezing inside an accepted block.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate, speculative_generate
from byteps_tpu.models.transformer import Transformer, TransformerConfig


def _model(layers, seed, vocab=31, max_len=96):
    cfg = TransformerConfig(
        vocab_size=vocab, num_layers=layers, num_heads=2, d_model=32,
        d_ff=64, max_seq_len=max_len, dtype=jnp.float32)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (3, 8), 0, vocab)
    variables = model.init(jax.random.PRNGKey(seed), tokens)
    return model, variables, tokens


def test_spec_exact_disagreeing_draft():
    """Independent random draft: near-zero acceptance, output still equals
    target-only greedy."""
    target, tvars, tokens = _model(2, 1)
    draft, dvars, _ = _model(1, 99)
    want = generate(target, tvars, tokens, 12, temperature=0)
    got = speculative_generate(target, tvars, draft, dvars, tokens, 12,
                               gamma=3)
    np.testing.assert_array_equal(np.asarray(got["tokens"]),
                                  np.asarray(want["tokens"]))


def test_spec_exact_perfect_draft():
    """Draft == target: near-total acceptance and identical output.
    Acceptance can fall a hair short of 1.0: the draft decodes tq=1
    while the verifier runs tq=G+1, so fp reduction orders differ and a
    near-tie argmax can flip — output equality is what the algorithm
    guarantees (regression guard: a draft-cache hole at pos+G once
    capped this at ~0.87)."""
    target, tvars, tokens = _model(2, 1)
    want = generate(target, tvars, tokens, 12, temperature=0)
    got = speculative_generate(target, tvars, target, tvars, tokens, 12,
                               gamma=4)
    np.testing.assert_array_equal(np.asarray(got["tokens"]),
                                  np.asarray(want["tokens"]))
    # acceptance asserted on a single row: the lockstep batch-min
    # amplifies rare per-row fp flips (3 rows x 4 drafts all must agree)
    row = tokens[:1]
    got1 = speculative_generate(target, tvars, target, tvars, row, 12,
                                gamma=4)
    assert float(got1["acceptance"]) > 0.75
    assert int(got1["rounds"]) <= 4  # near-optimal: ceil(11/5)=3 rounds


def test_spec_gamma_one_and_large():
    target, tvars, tokens = _model(2, 1)
    draft, dvars, _ = _model(1, 7)
    want = generate(target, tvars, tokens, 10, temperature=0)
    for gamma in (1, 8):
        got = speculative_generate(target, tvars, draft, dvars, tokens,
                                   10, gamma=gamma)
        np.testing.assert_array_equal(np.asarray(got["tokens"]),
                                      np.asarray(want["tokens"]))


def test_spec_eos_matches_generate():
    """EOS freezing must match generate()'s semantics even when the eos
    lands inside an accepted block."""
    target, tvars, tokens = _model(2, 1)
    ref = generate(target, tvars, tokens, 10, temperature=0)
    # pick a token that actually appears early in the greedy output
    eos = int(np.asarray(ref["tokens"])[0, 2])
    want = generate(target, tvars, tokens, 10, temperature=0,
                    eos_id=eos, pad_id=0)
    got = speculative_generate(target, tvars, target, tvars, tokens, 10,
                               gamma=4, eos_id=eos, pad_id=0)
    np.testing.assert_array_equal(np.asarray(got["tokens"]),
                                  np.asarray(want["tokens"]))


def test_truncated_self_draft_exact_and_cheap():
    """LayerSkip-style self-draft (inference.truncated_draft): the
    target's own first layers as draft — output still equals target-only
    greedy (the speculative contract is draft-independent), the draft's
    param tree is a strict subset sharing the target's arrays, and bad
    layer counts raise."""
    import pytest

    from byteps_tpu.inference import truncated_draft

    target, tvars, tokens = _model(4, 1)
    dmodel, dvars = truncated_draft(target.cfg, tvars, 2)
    assert dmodel.cfg.num_layers == 2
    assert set(dvars["params"]) == {
        "embed", "pos", "block_0", "block_1", "ln_f", "lm_head"}
    # shared leaves, not copies
    assert dvars["params"]["block_0"] is tvars["params"]["block_0"]
    want = generate(target, tvars, tokens, 12, temperature=0)
    got = speculative_generate(target, tvars, dmodel, dvars, tokens, 12,
                               gamma=3)
    np.testing.assert_array_equal(np.asarray(got["tokens"]),
                                  np.asarray(want["tokens"]))
    with pytest.raises(ValueError, match="num_layers"):
        truncated_draft(target.cfg, tvars, 5)


@pytest.mark.slow  # ~40s on CPU: trains the target model to convergence
def test_truncated_draft_acceptance_rises_with_training():
    """The LayerSkip premise, empirically: on RANDOM weights a truncated
    self-draft is uncorrelated with the full model (acceptance ~0, the
    bench's honest finding), but once the model is TRAINED the early
    layers carry the signal and the same draft's proposals are accepted
    at a high rate.  (Output correctness is draft-independent either
    way — pinned by the other tests.)"""
    import optax

    from byteps_tpu.inference import truncated_draft

    vocab = 64
    cfg = TransformerConfig(
        vocab_size=vocab, num_layers=2, num_heads=2, d_model=64,
        d_ff=128, max_seq_len=48, dtype=jnp.float32)
    model = Transformer(cfg)

    def batch(key, B=16, T=16):
        # repeating 4-token patterns: learnable by one layer
        pat = jax.random.randint(key, (B, 4), 0, vocab)
        return jnp.tile(pat, (1, (T + 3) // 4))[:, :T]

    toks0 = batch(jax.random.PRNGKey(0))
    variables = model.init(jax.random.PRNGKey(1), toks0)
    params = variables["params"]

    def acceptance(p):
        # single prompt row: batched speculation accepts the lockstep
        # minimum across rows, which amplifies per-row noise (see
        # test_spec_exact_perfect_draft)
        dmodel, dvars = truncated_draft(cfg, {"params": p}, 1)
        prompt = batch(jax.random.PRNGKey(99), B=1, T=8)
        out = speculative_generate(model, {"params": p}, dmodel, dvars,
                                   prompt, 12, gamma=4)
        return float(out["acceptance"])

    acc_random = acceptance(params)

    tx = optax.adam(3e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, toks):
        def loss_of(p):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]).mean()

        loss, grads = jax.value_and_grad(loss_of)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rng = jax.random.PRNGKey(2)
    for _ in range(300):
        rng, sub = jax.random.split(rng)
        params, opt_state, _ = step(params, opt_state,
                                    batch(sub, B=32))

    acc_trained = acceptance(params)
    # ~0.67 on this config: a vanilla-trained model's early-exit readout
    # (ln_f + head on block_0's output) was never itself trained, which
    # is why LayerSkip adds early-exit losses — the test pins the RISE,
    # not perfection
    assert acc_trained > 0.5, acc_trained
    assert acc_trained > acc_random + 0.4, (acc_random, acc_trained)
