"""Cross-iteration overlap (ByteScheduler analog) tests.

Three contracts:
  1. exact staleness semantics — the delayed step applies iteration N-1's
     (averaged) gradients at iteration N, verified against a manual numpy
     simulation;
  2. convergence — delayed SGD still solves least squares;
  3. the overlap invariant — via jaxpr dependency analysis: the parameter
     update (and the gradient-reduce collectives feeding it) depends only
     on the carried state, never on this step's batch, which is what lets
     XLA run the collectives concurrently with forward+backward (the
     program-structure rendering of bytescheduler/torch/optimizer.py's
     barrier removal).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from byteps_tpu.training.overlap import OverlapState, make_delayed_grad_step
from byteps_tpu.training.step import shard_batch

COLLECTIVE_TAGS = ("psum", "all_gather", "reduce_scatter", "all_to_all",
                   "ppermute")


def _origin_sets(jaxpr, invar_origins, collectives_out):
    """Propagate, for every var, the set of top-level invar indices it
    transitively depends on; record each collective eqn's dependency set."""
    from jax._src.core import Literal

    env = {}
    for v, o in zip(jaxpr.invars, invar_origins):
        env[v] = o
    for v in getattr(jaxpr, "constvars", ()):
        env[v] = frozenset()

    def get(v):
        return frozenset() if isinstance(v, Literal) else env.get(v, frozenset())

    for eqn in jaxpr.eqns:
        in_origins = [get(v) for v in eqn.invars]
        union = frozenset().union(*in_origins) if in_origins else frozenset()
        name = eqn.primitive.name
        if any(t in name for t in COLLECTIVE_TAGS):
            collectives_out.append((name, union))
        sub = None
        for key in ("jaxpr", "call_jaxpr"):
            if key in eqn.params:
                sub = eqn.params[key]
                break
        if sub is not None:
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            n_const = len(getattr(inner, "constvars", ()))
            # align trailing invars (leading eqn invars may be consts)
            n = len(inner.invars)
            aligned = in_origins[-n:] if len(in_origins) >= n else (
                [frozenset()] * (n - len(in_origins)) + in_origins
            )
            outs = _origin_sets(inner, aligned, collectives_out)
            for ov, o in zip(eqn.outvars, outs):
                env[ov] = o
        else:
            for ov in eqn.outvars:
                env[ov] = union
    return [get(v) for v in jaxpr.outvars]


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()), ("dp",))


def _make(mesh, lr=0.1):
    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    return loss_fn, make_delayed_grad_step(
        loss_fn, optax.sgd(lr), mesh
    )


def test_delayed_semantics_match_manual_staleness(mesh):
    """Step N applies the global (averaged) gradient computed at step N-1."""
    lr = 0.1
    _, step = _make(mesh, lr)
    w0 = np.array([1.0, -1.0, 0.5, 2.0], np.float32)
    state = step.init_state({"w": jnp.asarray(w0)})
    rng = np.random.RandomState(0)
    xs = [rng.randn(16, 4).astype(np.float32) for _ in range(4)]
    w_true = np.array([0.0, 1.0, 2.0, 3.0], np.float32)

    # manual 1-step-delayed SGD on the full batch (global average == full-
    # batch gradient since every worker shard is averaged)
    w_ref = w0.copy()
    pending_ref = np.zeros_like(w0)
    for x in xs:
        g_now = 2.0 * x.T @ (x @ w_ref - x @ w_true) / x.shape[0]
        w_ref = w_ref - lr * pending_ref  # applies previous grad
        pending_ref = g_now

    for x in xs:
        batch = shard_batch({"x": x, "y": x @ w_true}, mesh)
        state, _ = step(state, batch)
    np.testing.assert_allclose(np.asarray(state.params["w"]), w_ref,
                               rtol=1e-5, atol=1e-6)
    # flush applies the final pending gradient
    state = step.flush(state)
    np.testing.assert_allclose(np.asarray(state.params["w"]),
                               w_ref - lr * pending_ref, rtol=1e-5, atol=1e-6)


def test_delayed_sgd_converges(mesh):
    _, step = _make(mesh, lr=0.05)
    w_true = jnp.array([1.0, -2.0, 0.5, 3.0])
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
    batch = shard_batch({"x": x, "y": x @ w_true}, mesh)
    state = step.init_state({"w": jnp.zeros((4,))})
    for _ in range(200):
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics)
    state = step.flush(state)
    np.testing.assert_allclose(np.asarray(state.params["w"]),
                               np.asarray(w_true), atol=0.05)
    assert float(metrics["loss"]) < 1e-2


def test_collectives_independent_of_batch(mesh):
    """The overlap invariant, proven on the program: the new params (and
    the gradient-reduce collectives) transitively depend only on state
    inputs — never on the batch — so XLA may overlap the entire reduce
    chain with this step's forward+backward."""
    _, step = _make(mesh)
    state = step.init_state({"w": jnp.zeros((4,))})
    x = jnp.zeros((16, 4))
    batch = shard_batch({"x": x, "y": jnp.zeros((16,))}, mesh)

    closed = jax.make_jaxpr(lambda s, b: step._fn(s, b))(state, batch)
    n_state = len(jax.tree_util.tree_leaves(state))
    n_batch = len(jax.tree_util.tree_leaves(batch))
    batch_positions = frozenset(range(n_state, n_state + n_batch))

    collectives = []
    out_origins = _origin_sets(
        closed.jaxpr,
        [frozenset([i]) for i in range(n_state + n_batch)],
        collectives,
    )
    assert collectives, "no collectives found in the step program"

    # output layout: (OverlapState, metrics) flattened — find params leaves
    out_struct = jax.eval_shape(lambda s, b: step._fn(s, b), state, batch)
    flat_paths = jax.tree_util.tree_flatten_with_path(out_struct)[0]
    params_idx = [
        i for i, (path, _) in enumerate(flat_paths)
        if any(getattr(p, "name", "") == "params" for p in path)
    ]
    assert params_idx
    for i in params_idx:
        assert not (out_origins[i] & batch_positions), (
            f"params output {i} depends on batch inputs: "
            f"{sorted(out_origins[i] & batch_positions)}"
        )

    # and at least one collective is batch-free (the gradient reduce),
    # while the loss psum legitimately touches the batch
    batch_free = [c for c in collectives if not (c[1] & batch_positions)]
    assert batch_free, f"all collectives depend on the batch: {collectives}"


def test_trainer_overlap_mode_converges():
    """Trainer(overlap=True) — the user-facing ByteScheduler opt-in
    (reference wraps the optimizer; here a Trainer flag) — trains to
    convergence and flushes the final pending gradients."""
    from byteps_tpu.training.trainer import Trainer

    def loss_fn(params, mstate, batch):
        pred = batch["x"] @ params["w"]
        return jnp.mean((pred - batch["y"]) ** 2), mstate

    w_true = jnp.array([1.0, -2.0, 0.5, 3.0])
    x = jax.random.normal(jax.random.PRNGKey(0), (64, 4))
    data = [{"x": x, "y": x @ w_true}] * 200

    trainer = Trainer(loss_fn=loss_fn, optimizer=optax.sgd(0.05),
                      log_every=0, overlap=True)
    state = trainer.fit({"w": jnp.zeros((4,))}, {}, iter(data))
    assert isinstance(state, OverlapState)
    np.testing.assert_allclose(np.asarray(state.params["w"]),
                               np.asarray(w_true), atol=0.05)
    # flush already applied: pending is all zeros
    for leaf in jax.tree_util.tree_leaves(state.pending):
        np.testing.assert_allclose(np.asarray(leaf), 0.0)


def test_trainer_overlap_rejects_async():
    from byteps_tpu.training.trainer import Trainer

    with pytest.raises(ValueError):
        Trainer(loss_fn=lambda p, m, b: (0.0, m), optimizer=optax.sgd(0.1),
                overlap=True, async_mode=True)
