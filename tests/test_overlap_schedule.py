"""Compiled-schedule overlap proof (VERDICT r2 #2).

``tests/test_overlap.py`` proves at the *jaxpr* level that the delayed-grad
step's collectives are independent of the current batch — necessary but not
sufficient.  These tests assert the property the user actually pays for: in
the **optimized, scheduled HLO module** (``is_scheduled=true`` — instruction
order in the entry computation *is* the execution schedule), the gradient
collectives are placed in the middle of the compute stream, with substantial
compute scheduled after them:

  * sync bucketed step: early buckets' reduce-scatter is issued while later
    backward compute is still scheduled behind it (per-bucket independence —
    the reference's per-tensor hook overlap, torch/__init__.py:112-154);
  * delayed-grad step: the whole reduce chain (through the final all-gather)
    straddles the batch's forward+backward (cross-iteration independence —
    the ByteScheduler barrier removal, bytescheduler/torch/optimizer.py:180-214).

On TPU backends collectives execute on the DMA/ICI queues, so mid-schedule
issue = concurrent execution; the same structural check compiled against a
real TPU topology (AOT, no chips needed) runs in
``scripts/prove_overlap_schedule.py`` and its output is archived in
``docs/overlap_proof.md``.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import ShapeDtypeStruct as S
from jax.sharding import Mesh

from byteps_tpu.training import make_data_parallel_step
from byteps_tpu.training.overlap import OverlapState, make_delayed_grad_step
from byteps_tpu.training.step import create_train_state

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute")
COMPUTE = ("fusion", "dot", "convolution", "custom-call")


def entry_schedule(compiled_text: str):
    """(index, op) pairs of the ENTRY computation in schedule order."""
    entry, in_entry = [], False
    for ln in compiled_text.splitlines():
        if ln.startswith("ENTRY "):
            in_entry = True
            continue
        if in_entry:
            if ln.startswith("}"):
                break
            entry.append(ln)
    op_re = re.compile(r"\b([a-z][a-z0-9\-_\.]*)\(")
    events = []
    for i, ln in enumerate(entry):
        if " = " not in ln:
            continue
        m = op_re.search(ln.split(" = ", 1)[1])
        if m:
            events.append((i, m.group(1)))
    return events


def overlap_stats(compiled_text: str):
    """(first grad-collective index, #compute before it, #compute after it,
    last collective index, #compute after last collective)."""
    ev = entry_schedule(compiled_text)
    coll = [i for i, o in ev if o.startswith(COLLECTIVES)]
    comp = [i for i, o in ev if o in COMPUTE]
    assert coll, "no collectives in compiled module"
    assert comp, "no compute in compiled module"
    first, last = coll[0], coll[-1]
    return (
        first,
        sum(1 for i in comp if i < first),
        sum(1 for i in comp if i > first),
        last,
        sum(1 for i in comp if i > last),
    )


def _loss_fn(params, mstate, batch):
    h = jnp.tanh(batch["x"] @ params["w1"])
    h = jnp.tanh(h @ params["w2"])
    pred = h @ params["w3"]
    return jnp.mean((pred - batch["y"]) ** 2), mstate


_PARAMS = {
    "w1": jnp.zeros((256, 512)),
    "w2": jnp.zeros((512, 512)),
    "w3": jnp.zeros((512, 8)),
}
_BATCH = {"x": S((64, 256), jnp.float32), "y": S((64, 8), jnp.float32)}


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    return Mesh(np.array(jax.devices()), ("dp",))


def test_sync_step_buckets_straddle_backward(mesh):
    """Bucketed DP step: the compiled schedule issues bucket collectives
    with compute still behind them — per-bucket overlap with backward.

    History: this carried ``xfail(strict=False)`` for an XLA:CPU
    scheduler regression (collectives sunk to ~the end of the entry
    schedule — PARITY.md) and silently xpassed once the build moved on.
    The mark is dropped so a real schedule regression fails loudly
    again; the delayed-grad variant below still genuinely xfails on
    this build and keeps its mark."""
    tx = optax.sgd(0.1, momentum=0.9)
    step = make_data_parallel_step(_loss_fn, tx, mesh)
    state = jax.eval_shape(lambda p: create_train_state(p, step.tx), _PARAMS)
    txt = step._fn.lower(state, _BATCH).compile().as_text()
    assert "is_scheduled=true" in txt

    first, before, after, _, _ = overlap_stats(txt)
    # schedule sandwiches the collectives: real compute on both sides
    assert before >= 2, f"no compute before first collective (idx {first})"
    assert after >= 3, (
        f"collectives scheduled after essentially all compute "
        f"({after} compute ops after) — no overlap in the schedule")


@pytest.mark.xfail(
    strict=False,
    reason="XLA:CPU scheduler placement divergence (documented in "
    "PARITY.md): 1 compute op scheduled after the grad reduce chain vs "
    "the >=3 the assertion demands.  Structural independence is still "
    "proven by test_overlap.py; the TPU schedule proof is archived in "
    "docs/overlap_proof.md.")
def test_delayed_step_collectives_straddle_whole_batch_compute(mesh):
    """Delayed-grad step: the *entire* reduce chain — including the final
    all-gather — is scheduled with this batch's compute still pending,
    which is impossible for a synchronous step (its update is terminal)."""
    tx = optax.sgd(0.1, momentum=0.9)
    step = make_delayed_grad_step(_loss_fn, tx, mesh)
    state = jax.eval_shape(
        lambda p: OverlapState(p, tx.init(p), {}, jnp.zeros((), jnp.int32),
                               jax.tree_util.tree_map(jnp.zeros_like, p)),
        _PARAMS)
    txt = step._fn.lower(state, _BATCH).compile().as_text()
    assert "is_scheduled=true" in txt

    ev = entry_schedule(txt)
    comp = [i for i, o in ev if o in COMPUTE]
    # the *gradient* collectives are the reduce-scatter/all-gather pair
    # (loss/model-state psums lower to plain all-reduce)
    grad_coll = [i for i, o in ev
                 if o.startswith(("reduce-scatter", "all-gather"))]
    assert grad_coll, "no grad bucket collectives found"
    after_last = sum(1 for i in comp if i > grad_coll[-1])
    assert after_last >= 3, (
        "grad reduce chain is scheduled after the batch compute "
        f"({after_last} compute ops after its last collective) — the "
        "cross-iteration independence bought no schedule overlap")

    # and it must beat the synchronous step's placement
    sync = make_data_parallel_step(_loss_fn, tx, mesh)
    sstate = jax.eval_shape(lambda p: create_train_state(p, sync.tx), _PARAMS)
    stxt = sync._fn.lower(sstate, _BATCH).compile().as_text()
    sev = entry_schedule(stxt)
    scomp = [i for i, o in sev if i and o in COMPUTE]
    sgrad = [i for i, o in sev
             if o.startswith(("reduce-scatter", "all-gather"))]
    sync_after = sum(1 for i in scomp if i > sgrad[-1])
    assert after_last >= sync_after, (
        "delayed step should leave at least as much compute after its "
        f"reduce chain as the sync step ({after_last} vs {sync_after})")
