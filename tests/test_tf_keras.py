"""TensorFlow / Keras front-end tests (byteps_tpu.tensorflow,
byteps_tpu.keras) — the reference's ``byteps.tensorflow`` +
``byteps.keras`` surface: push_pull on tf tensors,
DistributedGradientTape, keras DistributedOptimizer through model.fit,
broadcast_variables, the callback set, and load_model re-wrapping.

Single-process here (worker == process, size()==1: push_pull is the
identity-average, like the reference when size()==1); the cross-process
reduce path shares api.push_pull_async_process with the torch front-end,
whose 2-process coverage lives in tests/test_multihost.py.
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")

import byteps_tpu.tensorflow as bps_tf
import byteps_tpu.keras as bps_k


@pytest.fixture(autouse=True)
def _init():
    bps_tf.init()
    yield


def test_push_pull_identity_and_dtype():
    x = tf.constant([[1.0, 2.0], [3.0, 4.0]], dtype=tf.float32)
    out = bps_tf.push_pull(x, average=True, name="tf0")
    assert isinstance(out, tf.Tensor) and out.dtype == tf.float32
    np.testing.assert_allclose(out.numpy(), x.numpy())
    out = bps_tf.push_pull(x, average=False, name="tf0_sum")
    np.testing.assert_allclose(out.numpy(), x.numpy())


def test_push_pull_async_poll_synchronize():
    x = tf.ones([8])
    h = bps_tf.push_pull_async(x, name="tf1")
    bps_tf.poll(h)
    out = bps_tf.synchronize(h)
    np.testing.assert_allclose(out.numpy(), np.ones(8))


def test_broadcast_and_broadcast_variables():
    x = tf.constant([5.0, 6.0])
    np.testing.assert_allclose(bps_tf.broadcast(x, 0).numpy(), x.numpy())
    v = tf.Variable([1.0, 2.0, 3.0])
    bps_tf.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [1.0, 2.0, 3.0])


def test_broadcast_global_variables_raises_with_recipe():
    with pytest.raises(NotImplementedError, match="broadcast_variables"):
        bps_tf.broadcast_global_variables(0)


def test_distributed_gradient_tape_trains():
    """Reference tensorflow/__init__.py:285-307: tape.gradient returns
    worker-averaged gradients; a linear model fits its target."""
    w = tf.Variable([[0.0], [0.0], [0.0], [0.0]])
    x = tf.constant(np.random.RandomState(0).randn(64, 4), tf.float32)
    y = x @ tf.constant([[1.0], [-2.0], [0.5], [3.0]])
    for _ in range(200):
        with bps_tf.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = tf.reduce_mean((x @ w - y) ** 2)
        (g,) = tape.gradient(loss, [w])
        assert g is not None
        w.assign_sub(0.1 * g)
    assert float(loss) < 1e-3


def test_distributed_optimizer_none_grads_preserved():
    opt = bps_tf.DistributedOptimizer(keras.optimizers.SGD(0.1))
    v = tf.Variable([1.0, 2.0])
    # keras rejects all-None applies; mix a real grad with a None slot via
    # the internal reducer to pin the None-preserving contract
    from byteps_tpu.tensorflow import _reduce_grads
    out = _reduce_grads([None, tf.ones([2])], [v, v],
                        bps_tf.Compression.none)
    assert out[0] is None
    np.testing.assert_allclose(np.asarray(out[1]), [1.0, 1.0])


def test_keras_distributed_optimizer_fit():
    """The wrapped keras optimizer drives model.fit (graph mode via
    tf.py_function — jit_compile=False) and the model fits a linear
    target."""
    rng = np.random.RandomState(0)
    x = rng.randn(256, 4).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w_true

    model = keras.Sequential([keras.layers.Dense(1, use_bias=False)])
    opt = bps_tf.DistributedOptimizer(keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="mse", jit_compile=False)
    hist = model.fit(x, y, batch_size=64, epochs=30, verbose=0)
    assert hist.history["loss"][-1] < 1e-2
    np.testing.assert_allclose(model.layers[0].kernel.numpy(), w_true,
                               atol=0.05)


def test_keras_callbacks_fit():
    """BroadcastGlobalVariablesCallback + MetricAverageCallback +
    LearningRateWarmupCallback compose through model.fit."""
    from byteps_tpu.keras.callbacks import (
        BroadcastGlobalVariablesCallback,
        LearningRateWarmupCallback,
        MetricAverageCallback,
    )

    rng = np.random.RandomState(1)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x @ rng.randn(4, 1).astype(np.float32))
    model = keras.Sequential([keras.layers.Dense(1)])
    model.compile(optimizer=bps_tf.DistributedOptimizer(
        keras.optimizers.SGD(0.05)), loss="mse", jit_compile=False)
    bcast = BroadcastGlobalVariablesCallback(0)
    warm = LearningRateWarmupCallback(warmup_epochs=2, steps_per_epoch=2)
    hist = model.fit(x, y, batch_size=32, epochs=3, verbose=0,
                     callbacks=[bcast, MetricAverageCallback(), warm])
    assert bcast.broadcast_done
    assert "lr" in hist.history and len(hist.history["lr"]) == 3
    # single worker: warmup multiplier is 1 -> lr unchanged
    np.testing.assert_allclose(hist.history["lr"][-1], 0.05, rtol=1e-6)


def test_keras_value_push_pull_and_broadcast():
    out = bps_k.push_pull(np.arange(4.0), average=True, name="kv")
    np.testing.assert_allclose(out, np.arange(4.0))
    out = bps_k.broadcast(np.ones(3), root_rank=0, name="kb")
    np.testing.assert_allclose(out, 1.0)


def test_keras_load_model_rewraps_optimizer(tmp_path):
    """Reference keras/__init__.py:95-123: a model saved *after wrapping*
    round-trips (the wrapper serializes as its base class) and the loaded
    optimizer communicates again (re-wrapped in place)."""
    model = keras.Sequential([keras.layers.Dense(1, use_bias=False)])
    model.compile(optimizer=bps_tf.DistributedOptimizer(
        keras.optimizers.SGD(0.1)), loss="mse", jit_compile=False)
    x = np.ones((8, 4), np.float32)
    model.fit(x, np.ones((8, 1), np.float32), verbose=0)
    path = str(tmp_path / "m.keras")
    model.save(path)  # wrapped optimizer must serialize as plain SGD

    loaded = bps_k.load_model(path)
    assert getattr(type(loaded.optimizer), "_bps_distributed", False)
    assert type(loaded.optimizer).__name__ == "SGD"
    loaded.fit(x, np.ones((8, 1), np.float32), verbose=0)


def test_warmup_callback_ramps_without_steps_per_epoch(monkeypatch):
    """Default-arg warmup (no steps_per_epoch) must still ramp the lr at
    epoch granularity — with a faked 4-worker size, lr reaches
    base*size, not stay frozen (r3 review finding)."""
    from byteps_tpu.keras.callbacks import LearningRateWarmupCallback
    import byteps_tpu.tensorflow as btf

    monkeypatch.setattr(btf, "size", lambda: 4)
    rng = np.random.RandomState(2)
    x = rng.randn(32, 4).astype(np.float32)
    y = rng.randn(32, 1).astype(np.float32)
    model = keras.Sequential([keras.layers.Dense(1)])
    model.compile(optimizer=keras.optimizers.SGD(0.01), loss="mse",
                  jit_compile=False)
    hist = model.fit(x, y, batch_size=32, epochs=4, verbose=0,
                     callbacks=[LearningRateWarmupCallback(warmup_epochs=2)])
    lrs = hist.history["lr"]
    assert lrs[0] == pytest.approx(0.01, rel=1e-5)          # epoch 0: 1x
    assert lrs[1] == pytest.approx(0.025, rel=1e-5)         # halfway ramp
    assert max(lrs) == pytest.approx(0.04, rel=1e-5)        # reaches 4x
