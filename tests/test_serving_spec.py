"""Speculative decoding (serving/spec.py + the engine's verify path).

THE parity anchor, extended to multi-token ticks: a speculating engine
— n-gram prompt-lookup proposals, one batched ``verify_tokens`` pass
per tick, longest-matching-prefix acceptance — must emit streams
token-identical to sequential ``generate()`` (and so to the
non-speculative engine), greedy AND seeded, dense AND paged, across
budget/EOS truncation, router-style resume, and a preempt/resume cycle
fired between verify ticks.  Acceptance-only-on-match makes wrong
proposals harmless by construction; these tests pin it bit-for-bit.

Compile discipline rides along: exactly one verify program per
speculation-depth bucket (the chunk-bucket rule), and the metric
contract — accepted-but-never-emitted tokens count nowhere, so
TPOT/`serve.tokens` cannot be skewed by work no client saw.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate
from byteps_tpu.models.transformer import Transformer, TransformerConfig
from byteps_tpu.serving import NgramProposer, ServeMetrics, ServingEngine
from byteps_tpu.serving import metrics as sm

M = 8  # tokens per request, shared so generate() compiles once per mode


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), toks)
    return cfg, model, variables


@pytest.fixture(scope="module")
def prompts():
    # one highly repetitive prompt (the proposer's sweet spot) and one
    # random prompt (proposals must be harmless when wrong)
    rep = np.asarray((list(range(5)) * 4)[:18], np.int32)
    rnd = np.asarray(jax.random.randint(
        jax.random.PRNGKey(10), (7,), 0, 61), np.int32)
    return [rep, rnd]


@pytest.fixture(scope="module")
def greedy_base(tiny, prompts):
    _, model, variables = tiny
    return [np.asarray(generate(model, variables, p[None], M,
                                temperature=0.0)["tokens"])[0]
            for p in prompts]


# ---------------------------------------------------------------- proposer


def test_proposer_prompt_lookup_semantics():
    p = NgramProposer(4, ngram=3, min_ngram=1)
    # trailing [1, 2] last occurred at index 1; continuation follows it
    ctx = np.asarray([0, 1, 2, 3, 4, 1, 2], np.int32)
    assert p.propose(ctx, 4) == [3, 4, 1, 2]
    assert p.propose(ctx, 2) == [3, 4]  # cap bounds the proposal
    # a full-depth continuation is preferred over a more recent but
    # shorter one (short-period repetition would otherwise cap
    # proposals at the period length)...
    ctx = np.asarray([1, 2, 9, 1, 2, 8, 1, 2], np.int32)
    assert p.propose(ctx, 4) == [9, 1, 2, 8]
    # ...and when no occurrence has full depth, the most recent wins
    ctx2 = np.asarray([5, 9, 1, 2, 8, 1, 2], np.int32)
    assert p.propose(ctx2, 4) == [8, 1, 2]
    # pure-period output proposes full depth, not one period
    sevens = np.full(10, 7, np.int32)
    assert p.propose(sevens, 4) == [7, 7, 7, 7]
    # longest n-gram first: the 3-gram match beats the 1-gram one
    ctx = np.asarray([5, 6, 7, 1, 0, 7, 5, 6, 7], np.int32)
    assert p.propose(ctx, 2) == [1, 0]
    # cap bounds the proposal length
    assert p.propose(np.asarray([3, 4, 3], np.int32), 1) == [4]
    # nothing to match -> no proposal, and degenerate contexts are safe
    assert p.propose(np.asarray([1, 2, 3], np.int32), 4) == []
    assert p.propose(np.asarray([7], np.int32), 4) == []
    assert p.propose(np.asarray([1, 1], np.int32), 0) == []


def test_proposer_min_ngram_floor_stands_down():
    """A single repeated token is noise on non-repetitive output: the
    default floor of 2 refuses to propose from it (every false proposal
    costs a widened verify forward)."""
    ctx = np.asarray([1, 2, 3, 4, 5, 6, 3], np.int32)
    assert NgramProposer(4, ngram=3).propose(ctx, 4) == []
    assert NgramProposer(4, ngram=3,
                         min_ngram=1).propose(ctx, 4) == [4, 5, 6, 3]


def test_proposer_validation():
    with pytest.raises(ValueError):
        NgramProposer(0)
    with pytest.raises(ValueError):
        NgramProposer(4, ngram=0)


# ------------------------------------------------------------------ parity


def test_spec_greedy_parity_and_compile_counts(tiny, prompts, greedy_base):
    """Speculating engine output is bit-identical to generate() for a
    repetitive AND a random prompt batched together, with exactly one
    verify program per depth bucket and the decode program untouched."""
    _, model, variables = tiny
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, spec_k=4,
                        metrics=ServeMetrics())
    reqs = [eng.submit(p, M) for p in prompts]
    eng.drain(timeout=120)
    for r, b in zip(reqs, greedy_base):
        np.testing.assert_array_equal(r.result(), b)
    counts = eng.compile_counts()
    assert counts["decode"] == 1
    assert counts["verify"] == counts["verify_buckets"]
    # depth buckets stay on the {1, 2, 4} grid (spec_k rounds to 2^n)
    assert set(eng._verify_fns) <= {2, 3, 5}
    # a second round with warm programs must not retrace anything
    reqs = [eng.submit(p, M) for p in prompts]
    eng.drain(timeout=120)
    for r, b in zip(reqs, greedy_base):
        np.testing.assert_array_equal(r.result(), b)
    assert eng.compile_counts() == counts


def test_spec_seeded_parity(tiny, prompts):
    """Seeded sampling under speculation replays generate()'s exact
    per-step key chain: accepted positions consume exactly one split
    each, rejected positions' splits are discarded with them."""
    _, model, variables = tiny
    base = [np.asarray(generate(
        model, variables, p[None], M, temperature=0.8, top_k=20,
        rng=jax.random.PRNGKey(100 + i))["tokens"])[0]
        for i, p in enumerate(prompts)]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.8, top_k=20, spec_k=4,
                        metrics=ServeMetrics())
    reqs = [eng.submit(p, M, seed=100 + i)
            for i, p in enumerate(prompts)]
    eng.drain(timeout=120)
    for r, b in zip(reqs, base):
        np.testing.assert_array_equal(r.result(), b)


@pytest.mark.slow
def test_spec_paged_parity_with_preempt_mid_speculation(tiny):
    """Paged + speculation + block pressure: a request preempted while
    speculation is active resumes by re-prefill and continues the
    parked token/key chain — both streams bit-identical to generate(),
    greedy and seeded (the ISSUE's preempt-mid-speculation anchor).
    Slow: paged-spec compile x preempt/resume (tier-1 duration
    budget); test_spec_greedy_parity_and_compile_counts /
    test_spec_seeded_parity keep the fast spec parity coverage."""
    _, model, variables = tiny
    pA = np.asarray((list(range(6)) * 4)[:19], np.int32)
    pB = np.asarray((list(range(7, 12)) * 4)[:18], np.int32)
    m = 30  # each needs ~7 of the pool's 8 usable blocks
    for temp, kw in ((0.0, {}), (0.8, {"top_k": 20})):
        base = []
        for i, p in enumerate((pA, pB)):
            g = dict(kw)
            if temp:
                g["rng"] = jax.random.PRNGKey(40 + i)
            base.append(np.asarray(generate(
                model, variables, p[None], m, temperature=temp,
                **g)["tokens"])[0])
        eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                            temperature=temp, paged=True, block=8,
                            kv_blocks=9, spec_k=4,
                            metrics=ServeMetrics(), **kw)
        r0 = eng.submit(pA, m, seed=40)
        r1 = eng.submit(pB, m, seed=41)
        eng.drain(timeout=120)
        np.testing.assert_array_equal(r0.result(), base[0])
        np.testing.assert_array_equal(r1.result(), base[1])
        assert eng.metrics.get(sm.PREEMPTIONS) >= 1
        assert eng.pool.alloc.used_count == 1  # all blocks reclaimed


def test_spec_resume_tokens_feed_proposer(tiny, prompts):
    """Router-style resume on a speculating engine: the resumed history
    seeds the proposer's context and the continued stream is
    token-identical to the never-interrupted run — greedy and seeded."""
    _, model, variables = tiny
    p = prompts[0]  # repetitive: the resumed tokens must drive matches
    cut = 3
    for temp, kw, seed in ((0.0, {}, 0), (0.8, {"top_k": 20}, 77)):
        g = dict(kw)
        if temp:
            g["rng"] = jax.random.PRNGKey(seed)
        full = np.asarray(generate(model, variables, p[None], M,
                                   temperature=temp, **g)["tokens"])[0]
        eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                            temperature=temp, spec_k=4,
                            metrics=ServeMetrics(), **kw)
        req = eng.submit(p, M, seed=seed,
                         resume_tokens=[int(t) for t in full[:cut]])
        eng.drain(timeout=120)
        np.testing.assert_array_equal(req.result(), full)


def test_spec_eos_truncates_accepted_span(tiny, prompts, greedy_base):
    """An EOS inside an accepted span ends the request AT the EOS:
    later accepted tokens are never emitted (greedy trajectories are
    prefix-stable, so the expectation is the no-EOS baseline cut at
    the first EOS)."""
    _, model, variables = tiny
    full = greedy_base[0]
    eos = int(full[4])
    want = list(full[:list(full).index(eos) + 1])
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        temperature=0.0, eos_id=eos, spec_k=4,
                        metrics=ServeMetrics())
    req = eng.submit(prompts[0], M)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(req.result(), want)
    assert eng.metrics.get(sm.TOKENS) == len(want)


# ----------------------------------------------------------------- metrics


def test_spec_metrics_count_only_emitted_tokens(tiny, prompts):
    """Metric accuracy under speculation: `serve.tokens` and the
    per-request completion count reflect EMITTED tokens only — an
    accepted span truncated by the budget contributes nothing beyond
    it (the mirror of the PR 10 resumed-token exclusion), and
    tokens-per-tick accounting (DECODE_TICKS) includes verify ticks."""
    _, model, variables = tiny
    budget = 3  # small budget: accepted spans will overrun it
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, spec_k=4,
                        metrics=ServeMetrics())
    reqs = [eng.submit(p, budget) for p in prompts]
    eng.drain(timeout=120)
    for r in reqs:
        assert len(r.result()) == budget
    snap = eng.metrics.snapshot()
    assert snap[sm.TOKENS] == budget * len(prompts)
    assert snap[sm.DECODE_TICKS] >= 1
    # and a resumed request still counts only THIS engine's emissions
    full = np.asarray(generate(model, variables, prompts[0][None], M,
                               temperature=0.0)["tokens"])[0]
    eng2 = ServingEngine(model, variables, n_slots=1, max_seq=64,
                         temperature=0.0, spec_k=4,
                         metrics=ServeMetrics())
    req = eng2.submit(prompts[0], M,
                      resume_tokens=[int(t) for t in full[:3]])
    eng2.drain(timeout=120)
    np.testing.assert_array_equal(req.result(), full)
    assert eng2.metrics.get(sm.TOKENS) == M - 3


# ------------------------------------------------------------------ guards


def test_spec_guards_and_depth_rounding(tiny):
    _, model, variables = tiny
    # kv_quant has no speculative path (accumulation-order divergence)
    with pytest.raises(ValueError, match="dense fp"):
        ServingEngine(model, variables, n_slots=1, max_seq=64,
                      kv_quant=True, spec_k=4, metrics=ServeMetrics())
    # only the grouped cache layout decodes and verifies through the
    # same (dense) attention path
    with pytest.raises(ValueError, match="grouped"):
        ServingEngine(model, variables, n_slots=1, max_seq=64,
                      cache_layout="auto", spec_k=4,
                      metrics=ServeMetrics())
    # depth rounds down to the power-of-two bucket grid, and the ngram
    # floor of 2 survives an operator asking for 1 (single-token
    # matches are noise — the documented env.md contract)
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        spec_k=7, spec_ngram=1, metrics=ServeMetrics())
    assert eng.spec.k == 4
    assert eng.spec.min_ngram == 2
    assert ServingEngine(model, variables, n_slots=1, max_seq=64,
                         metrics=ServeMetrics()).spec is None
