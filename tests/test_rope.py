"""Rotary position embeddings + SwiGLU MLP (the LLaMA-family model axes,
`TransformerConfig(pos_emb="rope", mlp="swiglu")`).

RoPE's contract: scores depend only on position *deltas* (so cached
decode can store rotated keys and stay exact at any offset), and every
attention path — dense, flash, cached, GQA-grouped, int8 cache —
consumes rotated q/k identically.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import make_generate_fn
from byteps_tpu.models import Transformer, TransformerConfig
from byteps_tpu.models.transformer import apply_rope, init_cache

KW = dict(vocab_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
          d_model=32, d_ff=48, max_seq_len=64, dtype=jnp.float32,
          pos_emb="rope", mlp="swiglu")


def test_rope_relative_shift_invariance():
    """QK^T scores under RoPE are invariant to a global position shift."""
    B, T, H, D = 1, 6, 2, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    k = jnp.asarray(rng.randn(B, T, H, D), jnp.float32)

    def scores(off):
        pos = off + jnp.arange(T)
        return jnp.einsum("bqhd,bkhd->bhqk", apply_rope(q, pos),
                          apply_rope(k, pos))

    np.testing.assert_allclose(np.asarray(scores(0)),
                               np.asarray(scores(17)),
                               atol=1e-5, rtol=1e-5)


def test_rope_swiglu_decode_matches_full_forward():
    cfg = TransformerConfig(**KW)
    m = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), toks)
    assert "pos" not in vs["params"]  # no learned table under rope
    assert set(vs["params"]["block_0"]["mlp"]) == {"gate", "up", "down"}
    full = m.apply(vs, toks)
    caches = init_cache(cfg, 2, 20)
    lg, caches = m.apply(vs, toks[:, :7], caches, 0, False,
                         method=Transformer.decode)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(full[:, :7]),
                               atol=2e-5, rtol=2e-5)
    for i in range(7, 12):
        lg, caches = m.apply(vs, toks[:, i:i + 1], caches, i, False,
                             method=Transformer.decode)
        np.testing.assert_allclose(
            np.asarray(lg[:, 0]), np.asarray(full[:, i]),
            atol=2e-5, rtol=2e-5)


def test_rope_flash_matches_local():
    kw = dict(KW, max_seq_len=128)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
    cfg_f = TransformerConfig(attn_impl="flash", **kw)
    cfg_l = TransformerConfig(attn_impl="local", **kw)
    vs = Transformer(cfg_l).init(jax.random.PRNGKey(2), toks)
    np.testing.assert_allclose(
        np.asarray(Transformer(cfg_f).apply(vs, toks)),
        np.asarray(Transformer(cfg_l).apply(vs, toks)),
        atol=3e-5, rtol=3e-5)


@pytest.mark.slow  # ~10s: naive reference decode loop (tier-1 duration budget); rope_swiglu_decode_matches_full_forward stays fast
def test_rope_generate_matches_naive_and_int8_cache():
    cfg = TransformerConfig(**KW)
    m = Transformer(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), prompt)
    out = make_generate_fn(m, 5, temperature=0)(
        vs, prompt, jax.random.PRNGKey(0))
    toks = prompt
    for _ in range(5):
        lg = m.apply(vs, toks)
        toks = jnp.concatenate([toks, jnp.argmax(lg[:, -1:], -1)], 1)
    np.testing.assert_array_equal(np.asarray(out["tokens"]),
                                  np.asarray(toks[:, 8:]))
    outq = make_generate_fn(m, 5, temperature=0, kv_quant=True)(
        vs, prompt, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(outq["tokens"]),
                                  np.asarray(out["tokens"]))


@pytest.mark.slow  # ~11s: full train-step compile (tier-1 duration budget); rope decode/generate/flash/ring parity stays fast
def test_rope_swiglu_train_step_decreases_loss():
    import optax

    from byteps_tpu.training import lm_loss_fn

    cfg = TransformerConfig(**KW)
    m = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    vs = m.init(jax.random.PRNGKey(2), toks)
    lf = lm_loss_fn(m)
    tx = optax.sgd(0.5)

    def loss(p):
        return lf(p, {}, {"tokens": toks})[0]

    params, opt = vs["params"], tx.init(vs["params"])
    l0 = float(loss(params))
    for _ in range(5):
        _, grads = jax.value_and_grad(loss)(params)
        updates, opt = tx.update(grads, opt, params)
        params = optax.apply_updates(params, updates)
    assert float(loss(params)) < l0


def test_bad_pos_emb_and_mlp_raise():
    toks = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="pos_emb"):
        Transformer(TransformerConfig(**dict(KW, pos_emb="alibi"))).init(
            jax.random.PRNGKey(0), toks)
    with pytest.raises(ValueError, match="mlp"):
        Transformer(TransformerConfig(**dict(KW, mlp="geglu"))).init(
            jax.random.PRNGKey(0), toks)


def test_rope_ring_sp_matches_local():
    """RoPE composes with sequence parallelism: rotation happens with
    global positions before the ring shard_map splits the sequence, so
    the sp ring path equals the single-device local path."""
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))
    kw = dict(KW, max_seq_len=32)
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    cfg_r = TransformerConfig(attn_impl="ring", mesh=mesh, **kw)
    cfg_l = TransformerConfig(attn_impl="local", **kw)
    vs = Transformer(cfg_l).init(jax.random.PRNGKey(0), toks)
    expected = Transformer(cfg_l).apply(vs, toks)
    with mesh:
        got = jax.jit(
            lambda v, t: Transformer(cfg_r).apply(v, t))(vs, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=3e-5, rtol=3e-5)
