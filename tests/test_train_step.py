"""End-to-end data-parallel training tests on the 8-device CPU mesh.

Behavioral contracts from the reference's tests (SURVEY.md §4): training
loss decreases (non-hanging, converging loop — test_tensorflow_keras.py),
and the data-parallel step equals a single-device step on the concatenated
batch (sum/average correctness — test_mxnet.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax
from jax.sharding import Mesh

import byteps_tpu as bps
from byteps_tpu.models import ResNet18
from byteps_tpu.training import (
    classification_loss_fn,
    create_train_state,
    make_data_parallel_step,
    replicate_state,
    shard_batch,
)


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("dp",))


def _mlp_loss_fn(params, model_state, batch):
    x, y = batch["image"], batch["label"]
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    return loss, model_state


def _mlp_params(key, din=8, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros((dh,)),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros((dout,)),
    }


def test_dp_step_matches_single_device():
    """8-way data-parallel step == single-device step on the full batch."""
    mesh = _mesh()
    key = jax.random.PRNGKey(0)
    params = _mlp_params(key)
    tx = optax.sgd(0.1)

    batch = {
        "image": jax.random.normal(jax.random.PRNGKey(1), (16, 8)),
        "label": jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 4),
    }

    # single-device reference: plain sgd on the full batch
    def ref_step(params, batch):
        loss, grads = jax.value_and_grad(
            lambda p: _mlp_loss_fn(p, {}, batch)[0]
        )(params)
        return jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads), loss

    ref_params, ref_loss = ref_step(params, batch)

    step = make_data_parallel_step(_mlp_loss_fn, tx, mesh, donate=False)
    state = step.init_state(params)
    new_state, metrics = step(state, shard_batch(batch, mesh))

    np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss), atol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(new_state.params),
        jax.tree_util.tree_leaves(ref_params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    assert int(new_state.step) == 1


def test_dp_training_loss_decreases():
    mesh = _mesh()
    params = _mlp_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.5)
    step = make_data_parallel_step(_mlp_loss_fn, tx, mesh)
    state = step.init_state(params)

    x = jax.random.normal(jax.random.PRNGKey(1), (32, 8))
    y = (x.sum(-1) > 0).astype(jnp.int32)
    batch = shard_batch({"image": x, "label": y}, mesh)

    losses = []
    for _ in range(20):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


@pytest.mark.slow  # ~11s in-suite, ~31s cold ResNet compile (tier-1 duration budget); dp_step_matches_single_device + dp_training_loss_decreases keep fast dp-step coverage
def test_resnet_dp_step_runs():
    """Full flax ResNet with BatchNorm state through the dp step."""
    mesh = _mesh()
    model = ResNet18(num_classes=4, num_filters=8)
    x = jnp.zeros((8, 16, 16, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    params = variables["params"]
    model_state = {"batch_stats": variables["batch_stats"]}

    tx = optax.sgd(0.01, momentum=0.9)
    loss_fn = classification_loss_fn(model)
    step = make_data_parallel_step(loss_fn, tx, mesh)
    state = step.init_state(params, model_state=model_state)
    batch = shard_batch(
        {
            "image": jax.random.normal(jax.random.PRNGKey(1), (8, 16, 16, 3)),
            "label": jax.random.randint(jax.random.PRNGKey(2), (8,), 0, 4),
        },
        mesh,
    )
    state, metrics = step(state, batch)
    assert np.isfinite(metrics["loss"])
    state, metrics2 = step(state, batch)
    assert np.isfinite(metrics2["loss"])
    assert int(state.step) == 2


def test_backward_passes_per_step_accumulates():
    """backward_passes_per_step=k: params only move every k-th call
    (reference torch/__init__.py:107-154)."""
    mesh = _mesh()
    params = _mlp_params(jax.random.PRNGKey(0))
    tx = optax.sgd(0.1)
    step = make_data_parallel_step(
        _mlp_loss_fn, tx, mesh, backward_passes_per_step=2, donate=False
    )
    state = step.init_state(params)
    batch = shard_batch(
        {
            "image": jax.random.normal(jax.random.PRNGKey(1), (16, 8)),
            "label": jax.random.randint(jax.random.PRNGKey(2), (16,), 0, 4),
        },
        mesh,
    )
    s1, _ = step(state, batch)
    # after 1 of 2 passes params unchanged
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7)
    s2, _ = step(s1, batch)
    moved = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(s2.params),
            jax.tree_util.tree_leaves(params),
        )
    )
    assert moved
