"""Tensor-parallel paged serving (ISSUE 20 tentpole a).

``tp > 1`` shards the paged block pool into per-KV-head-slice sub-pools
(``[tp, n_blocks, block, (KV/tp)*D]``, serving/blocks.py) and serves
them through either the head-sliced fused kernel
(``paged_decode_attention_sharded``) or the gather fallback, which
reassembles the unsharded flat row byte-for-byte and rides the grouped
dense path.  Attention is exactly partitioned by KV head, so the parity
bar is the same one every serving feature pins: token-identical streams
to sequential ``generate()`` (greedy AND seeded), across prefix hits,
chunked prefill, preempt/resume, int8 pools, and the disagg ship seam.

The refusal-message satellite lives here too: tp NOT dividing
``kv_heads`` keeps a typed refusal naming the grouped-layout fallback
and the padding option (the init_cache twin is pinned in
tests/test_resilience.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate
from byteps_tpu.models.transformer import Transformer, TransformerConfig
from byteps_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_sharded,
)
from byteps_tpu.serving import (
    PagedSlotPool,
    ServeMetrics,
    ServingEngine,
)
from byteps_tpu.serving import metrics as sm

M = 8


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=61, num_layers=2, num_heads=2,
                            d_model=32, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32)
    model = Transformer(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(0), (1, 8), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), toks)
    return cfg, model, variables


@pytest.fixture(scope="module")
def prompts():
    return [np.asarray(jax.random.randint(
        jax.random.PRNGKey(10 + i), (5 + i,), 0, 61), np.int32)
        for i in range(3)]


@pytest.fixture(scope="module")
def greedy_base(tiny, prompts):
    _, model, variables = tiny
    return [np.asarray(generate(model, variables, p[None], M,
                                temperature=0.0)["tokens"])[0]
            for p in prompts]


# --------------------------------------------------- pool shapes + refusals


def test_tp_pool_shapes_and_total_bytes(tiny):
    """tp=2 pools carry a leading shard axis with the per-shard head
    slice on the minor axis; ``block_bytes`` stays the TOTAL across
    shards so byte-budget sizing is tp-independent."""
    cfg, _, _ = tiny
    base = PagedSlotPool(cfg, 2, 64, block=8, layout="flat")
    pool = PagedSlotPool(cfg, 2, 64, block=8, tp=2, layout="flat")
    KVs_D = (cfg.kv_heads // 2) * cfg.d_head
    assert pool.caches[0]["k"].shape == (2, pool.alloc.n_blocks, 8, KVs_D)
    assert pool.layout == "flat"
    assert pool.block_bytes == base.block_bytes
    assert pool.alloc.n_blocks == base.alloc.n_blocks
    # int8: s8 values + f32 scales, both per-shard
    q = PagedSlotPool(cfg, 2, 64, block=8, kv_dtype="int8", tp=2)
    assert q.caches[0]["k"].dtype == jnp.int8
    assert q.caches[0]["k_scale"].shape == (2, q.alloc.n_blocks, 8, 1)


def test_tp_refusal_messages(tiny):
    """Satellite: tp not dividing kv_heads keeps a typed refusal whose
    message names the padding option; the engine refuses tp on dense
    engines and tp not dividing num_heads."""
    cfg, model, variables = tiny  # kv_heads == 2
    with pytest.raises(ValueError, match="divide kv_heads") as ei:
        PagedSlotPool(cfg, 2, 64, block=8, tp=3)
    assert "pad kv_heads" in str(ei.value)
    with pytest.raises(ValueError, match="tp must be >= 1"):
        PagedSlotPool(cfg, 2, 64, block=8, tp=0)
    # grouped layout cannot carry per-shard sub-pools (fp pools)
    with pytest.raises(ValueError, match="flat"):
        PagedSlotPool(cfg, 2, 64, block=8, tp=2, layout="grouped")
    with pytest.raises(ValueError, match="paged=True"):
        ServingEngine(model, variables, n_slots=1, max_seq=64, tp=2,
                      metrics=ServeMetrics())
    # the engine checks query-head alignment before pool construction
    with pytest.raises(ValueError, match="divide num_heads"):
        ServingEngine(model, variables, n_slots=1, max_seq=64,
                      paged=True, block=8, tp=3, metrics=ServeMetrics())


# ------------------------------------------------ op-level bit-exactness


def test_sharded_kernel_bit_identical_to_unsharded():
    """The head-slice exactness argument, pinned at the op: per-shard
    kernel calls over the per-shard pools, concatenated over heads, are
    BIT-identical to the unsharded kernel on the unsharded pool —
    attention is exactly partitioned by KV head (docs/parallel.md)."""
    rng = np.random.RandomState(0)
    B, H, D, KV, blk, mb, nb, tp = 3, 4, 8, 4, 4, 6, 16, 2
    pos = np.array([3, 9, 17], np.int32)
    q = jnp.asarray(rng.randn(B, 1, H, D), jnp.float32)
    pk = jnp.asarray(rng.randn(nb, blk, KV * D), jnp.float32)
    pv = jnp.asarray(rng.randn(nb, blk, KV * D), jnp.float32)
    tables = np.zeros((B, mb), np.int32)
    nxt = iter(range(1, nb))
    for b in range(B):
        for j in range((int(pos[b]) + 1 + blk - 1) // blk + 1):
            tables[b, j] = next(nxt)
    tables = jnp.asarray(tables)
    base = paged_decode_attention(q, pk, pv, tables, jnp.asarray(pos),
                                  interpret=True)
    # per-shard pools: contiguous minor-axis slices ARE the head slices
    X = (KV // tp) * D
    spk = jnp.stack([pk[..., s * X:(s + 1) * X] for s in range(tp)])
    spv = jnp.stack([pv[..., s * X:(s + 1) * X] for s in range(tp)])
    out = paged_decode_attention_sharded(q, spk, spv, tables,
                                         jnp.asarray(pos),
                                         interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(out))


def test_sharded_kernel_int8_bit_identical():
    """Same pin for the int8 pools: per-(position, head) scales are
    head-independent, so the per-shard dequant is an exact slice."""
    rng = np.random.RandomState(1)
    B, H, D, KV, blk, mb, nb, tp = 2, 4, 8, 2, 4, 4, 8, 2
    pos = np.array([2, 11], np.int32)
    q = jnp.asarray(rng.randn(B, 1, H, D), jnp.float32)
    pk = jnp.asarray(rng.randint(-127, 127, (nb, blk, KV * D)), jnp.int8)
    pv = jnp.asarray(rng.randint(-127, 127, (nb, blk, KV * D)), jnp.int8)
    ks = jnp.asarray(rng.rand(nb, blk, KV), jnp.float32)
    vs = jnp.asarray(rng.rand(nb, blk, KV), jnp.float32)
    tables = jnp.asarray([[1, 2, 0, 0], [3, 4, 5, 6]], jnp.int32)
    base = paged_decode_attention(q, pk, pv, tables, jnp.asarray(pos),
                                  k_scale=ks, v_scale=vs, interpret=True)
    X, KVs = (KV // tp) * D, KV // tp
    spk = jnp.stack([pk[..., s * X:(s + 1) * X] for s in range(tp)])
    spv = jnp.stack([pv[..., s * X:(s + 1) * X] for s in range(tp)])
    sks = jnp.stack([ks[..., s * KVs:(s + 1) * KVs] for s in range(tp)])
    svs = jnp.stack([vs[..., s * KVs:(s + 1) * KVs] for s in range(tp)])
    out = paged_decode_attention_sharded(
        q, spk, spv, tables, jnp.asarray(pos), k_scale=sks, v_scale=svs,
        interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(out))


# ------------------------------------------------------- engine parity


def test_tp_gather_greedy_parity(tiny, prompts, greedy_base):
    _, model, variables = tiny
    eng = ServingEngine(model, variables, n_slots=4, max_seq=64,
                        temperature=0.0, paged=True, block=8, tp=2,
                        metrics=ServeMetrics())
    assert eng.pool.caches[0]["k"].ndim == 4  # [tp, nb, blk, X]
    reqs = [eng.submit(p, M) for p in prompts]
    eng.drain(timeout=120)
    for r, b in zip(reqs, greedy_base):
        np.testing.assert_array_equal(r.result(), b)
    assert eng.pool.alloc.used_count == 1  # reclaimed down to null


def test_tp_gather_seeded_parity(tiny, prompts):
    _, model, variables = tiny
    p = prompts[0]
    base = np.asarray(generate(
        model, variables, p[None], M, temperature=0.8, top_k=20,
        rng=jax.random.PRNGKey(100))["tokens"])[0]
    eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                        temperature=0.8, top_k=20, paged=True, block=8,
                        tp=2, metrics=ServeMetrics())
    req = eng.submit(p, M, seed=100)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(req.result(), base)


@pytest.mark.slow  # ~5s (tier-1 duration budget); tp greedy parity stays fast and test_paged_attention covers prefix zero-copy fast
def test_tp_prefix_hit_zero_copy_parity(tiny):
    """Prefix sharing under tp: block ids name the same token span on
    every shard, so hits stay refcount bumps (zero-copy) and chunked
    prefill resumes at the shared boundary — streams bit-identical to
    generate()."""
    _, model, variables = tiny
    shared = np.asarray(jax.random.randint(
        jax.random.PRNGKey(7), (16,), 0, 61), np.int32)
    pA = np.concatenate([shared, np.asarray([3, 9, 4], np.int32)])
    pB = np.concatenate([shared, np.asarray([11, 2], np.int32)])
    base = [np.asarray(generate(model, variables, p[None], M,
                                temperature=0.0)["tokens"])[0]
            for p in (pA, pB)]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, paged=True, block=8, chunk=8,
                        tp=2, prefix_cache=True, metrics=ServeMetrics())
    rA = eng.submit(pA, M)
    eng.drain(timeout=120)
    rB = eng.submit(pB, M)
    eng.drain(timeout=120)
    np.testing.assert_array_equal(rA.result(), base[0])
    np.testing.assert_array_equal(rB.result(), base[1])
    counts = eng.compile_counts()
    assert counts["prefix_copy"] == 0 and counts["prefix_extract"] == 0
    assert eng.metrics.get(sm.PREFIX_HITS) == 1
    assert eng.metrics.get(sm.PREFIX_HIT_TOKENS) == 16


@pytest.mark.slow  # ~6s (tier-1 duration budget); tp gather greedy/seeded parity stays fast and test_serving_paged covers preemption fast
def test_tp_preempt_resume_parity(tiny):
    """Preemption under block pressure with tp=2: the victim re-prefills
    per-shard pools and both streams stay bit-identical to generate()."""
    _, model, variables = tiny
    pA = np.asarray(jax.random.randint(
        jax.random.PRNGKey(2), (19,), 0, 61), np.int32)
    pB = np.asarray(jax.random.randint(
        jax.random.PRNGKey(3), (18,), 0, 61), np.int32)
    m = 30
    base = [np.asarray(generate(model, variables, p[None], m,
                                temperature=0.0)["tokens"])[0]
            for p in (pA, pB)]
    eng = ServingEngine(model, variables, n_slots=2, max_seq=64,
                        temperature=0.0, paged=True, block=8, tp=2,
                        kv_blocks=9, metrics=ServeMetrics())
    r0 = eng.submit(pA, m)
    r1 = eng.submit(pB, m)
    eng.drain(timeout=180)
    np.testing.assert_array_equal(r0.result(), base[0])
    np.testing.assert_array_equal(r1.result(), base[1])
    assert eng.metrics.get(sm.PREEMPTIONS) == 1
    assert eng.pool.alloc.used_count == 1


@pytest.mark.slow  # ~6s (tier-1 duration budget); test_sharded_kernel_int8_bit_identical keeps the int8 head-slice math fast
def test_tp_int8_pool_token_parity(tiny, prompts):
    """int8 per-shard pools: quantize-at-write is per-(position, head),
    so the sharded pool's bytes are an exact slice of the unsharded
    pool's — token streams identical between tp=1 and tp=2."""
    _, model, variables = tiny

    def run(tp):
        eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                            temperature=0.0, paged=True, block=8, tp=tp,
                            kv_dtype="int8", metrics=ServeMetrics())
        r = eng.submit(prompts[0], M)
        eng.drain(timeout=120)
        return r.result()

    np.testing.assert_array_equal(run(1), run(2))


def test_tp_disagg_wire_format_is_tp_independent(tiny, prompts):
    """extract_kv_blocks reassembles per-shard slices head-major into
    the unsharded flat row bytes: a tp=2 extract equals a tp=1 extract
    row-major, and write/extract round-trips byte-exact — ships work
    across tiers with different tp counts."""
    _, model, variables = tiny

    def park(tp):
        eng = ServingEngine(model, variables, n_slots=1, max_seq=64,
                            temperature=0.0, paged=True, block=8, tp=tp,
                            metrics=ServeMetrics())
        r = eng.submit(prompts[0], 4, keep_kv=True)
        eng.drain(timeout=120)
        return eng, eng.take_parked_kv(r.id)

    e1, kv1 = park(1)
    e2, kv2 = park(2)
    b1 = e1.extract_kv_blocks(kv1["ids"])
    b2 = e2.extract_kv_blocks(kv2["ids"])
    for l1, l2 in zip(b1, b2):
        for n in l1:
            np.testing.assert_array_equal(
                l1[n].reshape(l1[n].shape[0], -1),
                l2[n].reshape(l2[n].shape[0], -1))
    # round-trip through the tp=2 pool
    ids2 = e2.stage_alloc(len(kv2["ids"]))
    for j, bid in enumerate(ids2):
        e2.write_kv_block(bid, [{n: l[n][j] for n in l} for l in b2])
    b2rt = e2.extract_kv_blocks(ids2)
    for l1, l2 in zip(b2, b2rt):
        for n in l1:
            np.testing.assert_array_equal(l1[n], l2[n])
    e1.release_kv_ids(kv1["ids"])
    e2.release_kv_ids(kv2["ids"])
    e2.release_kv_ids(ids2)


@pytest.mark.slow
def test_tp_fused_kernel_engine_parity(tiny, prompts):
    """Slow sibling of test_sharded_kernel_bit_identical_to_unsharded:
    the whole engine on the fused kernel path (interpret mode), tp=2 vs
    tp=1, token-identical streams."""
    _, model, variables = tiny

    def run(tp):
        eng = ServingEngine(model, variables, n_slots=2, max_seq=32,
                            temperature=0.0, paged=True, block=8, tp=tp,
                            paged_kernel="on", metrics=ServeMetrics())
        reqs = [eng.submit(p[:5], 6) for p in prompts[:2]]
        eng.drain(timeout=240)
        return [r.result() for r in reqs]

    for a, b in zip(run(1), run(2)):
        np.testing.assert_array_equal(a, b)
