"""dm-haiku drop-in test: like the HF test, any functional param pytree
trains through the scheduled data-parallel step — byteps_tpu is adapter-
free for JAX-family libraries (the reference needs a compiled plugin per
framework, SURVEY.md §2.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

hk = pytest.importorskip("haiku")

from byteps_tpu.training import make_data_parallel_step, shard_batch


def test_haiku_mlp_trains_through_push_pull_step():
    def net(x):
        return hk.Sequential([
            hk.Linear(32), jax.nn.relu, hk.Linear(1),
        ])(x)

    model = hk.without_apply_rng(hk.transform(net))
    x0 = jnp.zeros((4, 8))
    params = model.init(jax.random.PRNGKey(0), x0)
    mesh = Mesh(np.array(jax.devices()), ("dp",))

    w_true = jnp.asarray(np.random.RandomState(0).randn(8, 1), jnp.float32)

    def loss_fn(params, model_state, batch):
        pred = model.apply(params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2), model_state

    step = make_data_parallel_step(loss_fn, optax.adam(1e-2), mesh)
    state = step.init_state(params)

    n = 8 * len(jax.devices())
    x = jnp.asarray(np.random.RandomState(1).randn(n, 8), jnp.float32)
    batch = shard_batch({"x": x, "y": x @ w_true}, mesh)

    losses = []
    for _ in range(150):
        state, metrics = step(state, batch)
        jax.block_until_ready(state)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < 0.05 * losses[0], (losses[0], losses[-1])
