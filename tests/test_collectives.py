"""Collective numerics on the 8-device CPU mesh — the behavioral contracts of
reference tests/test_mxnet.py:76-158 (push_pull sums, broadcast delivers the
root's tensor) plus the bucketed tree path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from byteps_tpu.common.partition import plan_buckets
from byteps_tpu.parallel import (
    broadcast_shard,
    broadcast_stacked,
    build_mesh,
    push_pull_shard,
    push_pull_stacked,
    push_pull_tree,
    shard_map,
)


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(mesh_shape={"dp": 8})


@pytest.fixture(scope="module")
def mesh2d():
    return build_mesh(mesh_shape={"dcn": 2, "dp": 4})


def test_push_pull_stacked_sum(mesh):
    rng = np.random.RandomState(0)
    x = rng.randn(8, 33).astype(np.float32)
    out = push_pull_stacked(jnp.asarray(x), mesh, ("dp",), average=False)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_push_pull_stacked_average(mesh):
    x = np.arange(8 * 10, dtype=np.float32).reshape(8, 10)
    out = push_pull_stacked(jnp.asarray(x), mesh, ("dp",), average=True)
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5)


def test_push_pull_odd_sizes_padding(mesh):
    # 13 elements does not divide 8 — exercises the pad/unpad path.
    x = np.random.RandomState(1).randn(8, 13).astype(np.float32)
    out = push_pull_stacked(jnp.asarray(x), mesh, ("dp",), average=False)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)


def test_push_pull_hierarchical_dcn(mesh2d):
    # 3-level reduction analog: scatter over dp, sum over dcn, gather over dp.
    x = np.random.RandomState(2).randn(8, 21).astype(np.float32)
    out = push_pull_stacked(jnp.asarray(x), mesh2d, ("dcn", "dp"), average=False)
    np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-4)


def test_push_pull_bf16_wire(mesh):
    x = np.ones((8, 16), dtype=np.float32)
    out = push_pull_stacked(jnp.asarray(x), mesh, ("dp",), average=False,
                            wire_dtype="bfloat16")
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), 8 * np.ones(16), rtol=1e-2)


def test_broadcast_stacked(mesh):
    x = np.stack([np.full((5,), r, dtype=np.float32) for r in range(8)])
    out = broadcast_stacked(jnp.asarray(x), mesh, ("dp",), root_rank=3)
    np.testing.assert_array_equal(np.asarray(out), np.full((5,), 3.0))


def test_broadcast_shard_inside_shard_map(mesh):
    def f(x):
        return broadcast_shard(x[0], root_rank=5, axes=("dp",))

    fn = jax.jit(shard_map(f, mesh, in_specs=P("dp"), out_specs=P()))
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = fn(x)
    np.testing.assert_array_equal(np.asarray(out), [5.0])


def test_push_pull_tree_matches_dense_allreduce(mesh):
    rng = np.random.RandomState(3)
    tree = {
        "w1": rng.randn(8, 17, 9).astype(np.float32),
        "b1": rng.randn(8, 9).astype(np.float32),
        "w2": rng.randn(8, 9, 3).astype(np.float32),
    }
    plan = plan_buckets(
        {k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype) for k, v in tree.items()},
        partition_bytes=128,
    )

    def f(t):
        local = {k: v[0] for k, v in t.items()}
        return push_pull_tree(local, plan=plan, scatter_axis="dp", average=True)

    fn = jax.jit(shard_map(
        f, mesh,
        in_specs=({k: P("dp") for k in tree},),
        out_specs={k: P() for k in tree},
    ))
    out = fn({k: jnp.asarray(v) for k, v in tree.items()})
    for k, v in tree.items():
        np.testing.assert_allclose(np.asarray(out[k]), v.mean(0), rtol=1e-5)


def test_push_pull_shard_int_dtype(mesh):
    x = np.arange(8 * 6, dtype=np.int32).reshape(8, 6)

    def f(xs):
        return push_pull_shard(xs[0], scatter_axis="dp", average=False)

    fn = jax.jit(shard_map(f, mesh, in_specs=P("dp"), out_specs=P()))
    out = fn(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(out), x.sum(0))
