"""Pallas flash attention vs the reference attention (interpret mode on CPU;
the same kernel runs compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.flash_attention import flash_attention
from byteps_tpu.parallel.ring_attention import local_attention

B, T, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    expected = local_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_flash_grads_match_reference():
    q, k, v = _qkv(1)

    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            interpret=True) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_uneven_blocks_rejected():
    q, k, v = _qkv(2)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=96, block_k=100, interpret=True)


def test_flash_bf16():
    q, k, v = _qkv(3, jnp.bfloat16)
    expected = local_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32),
        atol=3e-2, rtol=3e-2,
    )
