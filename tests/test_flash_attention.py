"""Pallas flash attention vs the reference attention (interpret mode on CPU;
the same kernel runs compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.flash_attention import flash_attention
from byteps_tpu.parallel.ring_attention import local_attention

B, T, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    expected = local_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


def test_flash_grads_match_reference():
    q, k, v = _qkv(1)

    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            interpret=True) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_flash_uneven_blocks_rejected():
    q, k, v = _qkv(2)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=96, block_k=100, interpret=True)


def test_flash_bf16():
    q, k, v = _qkv(3, jnp.bfloat16)
    expected = local_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def _dense_ref(q, k, v, causal, seg=None):
    """Dense reference with GQA expansion + segment masking."""
    B, T, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (D ** -0.5)
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
    if seg is not None:
        ok = seg[:, None, :, None] == seg[:, None, None, :]
        s = jnp.where(ok, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_ids_match_reference(causal):
    """Packed-sequence / padding-mask masking via segment ids: forward and
    grads match the dense masked softmax (VERDICT r2 missing #5)."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    B, T, H, D = 2, 64, 2, 32
    q, k, v = (jax.random.normal(kk, (B, T, H, D)) for kk in ks)
    seg = jnp.asarray(
        np.repeat(np.array([[0, 1, 1, 2], [0, 0, 3, 3]]), T // 4, axis=1))

    out = flash_attention(q, k, v, causal, None, 16, 16, True, seg)
    want = _dense_ref(q, k, v, causal, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    gf = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal, None, 16, 16, True, seg) ** 2), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(
        _dense_ref(a, b, c, causal, seg) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


@pytest.mark.parametrize("hkv", [1, 2])
def test_flash_gqa_mqa_match_reference(hkv):
    """GQA (grouped kv heads) / MQA (hkv=1): kernel reads the shared kv
    head via the index map; dk/dv group-sum back to [B, T, Hkv, D]."""
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    B, T, H, D = 2, 64, 4, 32
    q = jax.random.normal(ks[0], (B, T, H, D))
    k = jax.random.normal(ks[1], (B, T, hkv, D))
    v = jax.random.normal(ks[2], (B, T, hkv, D))

    out = flash_attention(q, k, v, True, None, 16, 16, True)
    want = _dense_ref(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    gf = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, True, None, 16, 16, True) ** 2), (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(
        _dense_ref(a, b, c, True) ** 2), (0, 1, 2))(q, k, v)
    assert gf[1].shape == (B, T, hkv, D) and gf[2].shape == (B, T, hkv, D)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_gqa_rejects_indivisible_heads():
    q = jnp.zeros((1, 16, 4, 8))
    kv = jnp.zeros((1, 16, 3, 8))
    with pytest.raises(ValueError, match="multiple"):
        flash_attention(q, kv, kv, interpret=True, block_q=16, block_k=16)


def test_bert_classifier_rides_flash_with_padding_mask():
    """Model-level: BertClassifier(attn_impl='flash') with an HF-style
    padding mask computes through the flash kernel's segment ids and
    matches the local masked-softmax path on valid positions."""
    from byteps_tpu.models.bert import BertClassifier, bert_config

    def run(attn_impl):
        cfg = bert_config(vocab_size=64, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64, max_seq_len=32,
                          dtype=jnp.float32, attn_impl=attn_impl)
        model = BertClassifier(cfg, num_classes=2)
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 64)
        mask = jnp.asarray(np.array(
            [[1] * 24 + [0] * 8, [1] * 32]), jnp.int32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((2, 32), jnp.int32))["params"]
        return model.apply({"params": params}, tokens,
                           attention_mask=mask)

    out_flash = run("flash")
    out_local = run("local")
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_local),
                               rtol=1e-4, atol=1e-5)


def _dense_ref_band(q, k, v, causal, window=None, slopes=None):
    """Dense reference with sliding-window band + ALiBi bias."""
    B, T, H, D = q.shape
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (D ** -0.5)
    row = jnp.arange(T)[:, None]
    col = jnp.arange(T)[None, :]
    if slopes is not None:
        s = s + slopes[None, :, None, None] * (col - row)[None, None]
    valid = jnp.ones((T, T), bool)
    if causal:
        valid = row >= col
        if window is not None:
            valid = valid & (row - col < window)
    s = jnp.where(valid[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("window", [1, 16, 40, 64])
def test_flash_sliding_window_matches_reference(window):
    """Mistral-style causal sliding window: fwd + grads match the dense
    banded softmax, including windows not aligned to block boundaries."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    B, T, H, D = 2, 64, 2, 32
    q, k, v = (jax.random.normal(kk, (B, T, H, D)) for kk in ks)

    out = flash_attention(q, k, v, True, None, 16, 16, True,
                          window=window)
    want = _dense_ref_band(q, k, v, True, window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    gf = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, True, None, 16, 16, True, window=window) ** 2),
        (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(
        _dense_ref_band(a, b, c, True, window=window) ** 2),
        (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def test_flash_alibi_matches_reference():
    """ALiBi bias computed in-kernel: fwd + grads match the dense biased
    softmax; also composed with a sliding window."""
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    B, T, H, D = 2, 64, 4, 32
    q, k, v = (jax.random.normal(kk, (B, T, H, D)) for kk in ks)
    slopes = jnp.asarray([2.0 ** (-i) for i in range(1, H + 1)], jnp.float32)

    out = flash_attention(q, k, v, True, None, 16, 16, True,
                          alibi_slopes=slopes)
    want = _dense_ref_band(q, k, v, True, slopes=slopes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    gf = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, True, None, 16, 16, True, alibi_slopes=slopes) ** 2),
        (0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: jnp.sum(
        _dense_ref_band(a, b, c, True, slopes=slopes) ** 2),
        (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)

    # window + alibi composed
    out2 = flash_attention(q, k, v, True, None, 16, 16, True,
                           window=24, alibi_slopes=slopes)
    want2 = _dense_ref_band(q, k, v, True, window=24, slopes=slopes)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(want2),
                               rtol=2e-4, atol=2e-5)


def test_flash_window_requires_causal():
    q, k, v = _qkv(7)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, False, None, 64, 64, True, window=8)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, None, 64, 64, True, window=0)


def test_transformer_attn_window_config():
    """Model-level sliding window: config plumbs through to the kernel and
    changes the output vs full causal attention."""
    from byteps_tpu.models.transformer import Transformer, TransformerConfig

    def run(window):
        cfg = TransformerConfig(
            vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attn_impl="flash",
            attn_window=window)
        model = Transformer(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0, 64)
        variables = model.init(jax.random.PRNGKey(1), tokens)
        return model.apply(variables, tokens)

    full = run(None)
    windowed = run(8)
    assert not np.allclose(np.asarray(full), np.asarray(windowed))

    from byteps_tpu.models.transformer import TransformerConfig as TC
    with pytest.raises(ValueError):
        TC(attn_impl="local", attn_window=8).attention_fn()


def test_attention_window_with_key_mask():
    """attn_window must still apply when a padding mask routes attention
    through the segment-ids flash branch (regression: window was silently
    dropped there)."""
    from byteps_tpu.models.transformer import Attention, TransformerConfig

    def run(window):
        cfg = TransformerConfig(
            vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
            max_seq_len=64, dtype=jnp.float32, attn_impl="flash",
            attn_window=window)
        attn = Attention(cfg)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32))
        mask = jnp.ones((2, 64), jnp.int32).at[:, 48:].set(0)
        variables = attn.init(jax.random.PRNGKey(1), x, key_mask=mask)
        return attn.apply(variables, x, key_mask=mask)

    full = run(None)
    windowed = run(8)
    assert not np.allclose(np.asarray(full), np.asarray(windowed))

    # non-flash masked branch must reject attn_window, not drop it
    from byteps_tpu.models.transformer import Attention as A
    cfg = TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, attn_impl="local", attn_window=8)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32))
    mask = jnp.ones((2, 64), jnp.int32)
    with pytest.raises(ValueError):
        A(cfg).init(jax.random.PRNGKey(1), x, key_mask=mask)
