"""Compression subsystem — wire domain: blob codec, policy gating, the
WireCompressor's post-ack residual commit, compressed (and partitioned)
RemoteStore push/pull over a real in-thread PS server, reply
compression, and retry-replay determinism under ``FaultInjectingProxy``
drop_after faults (the exactly-once × error-feedback interaction).
"""

import numpy as np
import pytest

from byteps_tpu.common.config import Config, reset_config, set_config
from byteps_tpu.compression import (CompressionPolicy, WireCompressor,
                                    decode_blob, derive_seed, encode_blob,
                                    get_compression_stats, get_scheme,
                                    reset_compression_stats)
from byteps_tpu.compression.stats import CompressionStats
from byteps_tpu.compression.wire import WIRE_TAG
from byteps_tpu.engine import ps_server
from byteps_tpu.resilience import (FaultInjectingProxy, ResilienceCounters,
                                   RetryPolicy, reset_counters)
from byteps_tpu.resilience import counters as cn

WIRE_SCHEMES = ["none", "bf16", "fp16", "int8", "topk", "randomk", "onebit"]


@pytest.fixture(autouse=True)
def _fresh_state():
    reset_config()
    reset_counters()
    reset_compression_stats()
    yield
    reset_config()
    reset_counters()
    reset_compression_stats()


def _x(n=1000, seed=0):
    return np.random.default_rng(seed).standard_normal(n).astype(np.float32)


def _spawn():
    srv, _ = ps_server.serve(0, host="127.0.0.1", use_native=False,
                             in_thread=True)
    return srv, f"127.0.0.1:{srv.server_address[1]}"


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("deadline", 20.0)
    return RetryPolicy(**kw)


# --------------------------------------------------------------- blob codec


@pytest.mark.parametrize("name", WIRE_SCHEMES)
def test_blob_roundtrip(name):
    x = _x().reshape(25, 40)
    scheme = get_scheme(name)
    blob, deq = encode_blob(scheme, x, seed=derive_seed(0, "w", 0),
                            ratio=0.05)
    out = decode_blob(WIRE_TAG, blob.data, x.shape)
    assert out.shape == x.shape and out.dtype == x.dtype
    assert np.isfinite(out).all()
    np.testing.assert_array_equal(out, deq.astype(x.dtype))


def test_blob_wire_sizes_beat_bf16_by_4x():
    """The acceptance-criteria ratio at codec level: onebit and topk
    (default 1% ratio) must put >=4x fewer bytes on the wire than the
    bf16 cast."""
    x = _x(100_000)
    bf16 = encode_blob(get_scheme("bf16"), x)[0].nbytes
    onebit = encode_blob(get_scheme("onebit"), x)[0].nbytes
    topk = encode_blob(get_scheme("topk"), x, ratio=0.01)[0].nbytes
    randomk = encode_blob(get_scheme("randomk"), x, seed=1,
                          ratio=0.01)[0].nbytes
    assert bf16 >= 4 * onebit
    assert bf16 >= 4 * topk
    assert bf16 >= 4 * randomk


def test_blob_version_mismatch_is_loud():
    x = _x(64)
    blob, _ = encode_blob(get_scheme("onebit"), x)
    with pytest.raises(ValueError, match="wire tag"):
        decode_blob("bpsc2", blob.data, x.shape)
    with pytest.raises(ValueError, match="truncated"):
        decode_blob(WIRE_TAG, blob.data[:-3], x.shape)


def test_randomk_wire_replay_is_deterministic():
    x = _x(5000)
    seed = derive_seed(7, "grad.w", 3)
    a, _ = encode_blob(get_scheme("randomk"), x, seed=seed, ratio=0.01)
    b, _ = encode_blob(get_scheme("randomk"), x, seed=seed, ratio=0.01)
    assert a.data == b.data  # a resent PUSH carries identical bytes
    c, _ = encode_blob(get_scheme("randomk"), x,
                       seed=derive_seed(7, "grad.w", 4), ratio=0.01)
    assert a.data != c.data  # the next logical push moves the mask


# ----------------------------------------------------------- WireCompressor


def test_wire_compressor_commits_residual_only_on_ack():
    policy = CompressionPolicy(default="onebit", min_bytes=16)
    comp = WireCompressor(policy)
    g = _x(256)

    payload1, commit1 = comp.encode_mutation("w", g)
    # NOT committed: a re-encode (application-level retry path) must not
    # see a folded residual
    payload1b, _ = comp.encode_mutation("w", g)
    assert payload1.data == payload1b.data
    assert comp.residual_norm("w") == 0.0

    commit1()
    assert comp.residual_norm("w") > 0.0
    # after the ack, the next push folds the residual -> different bytes
    payload2, commit2 = comp.encode_mutation("w", g)
    assert payload2.data != payload1.data


def test_wire_compressor_policy_passthrough():
    policy = CompressionPolicy(default="onebit", min_bytes=1 << 20)
    comp = WireCompressor(policy)
    g = _x(256)
    payload, commit = comp.encode_mutation("w", g)
    assert payload is g and commit is None  # below threshold: raw


def test_stats_observe_and_summary_line():
    stats = CompressionStats()
    stats.observe("w", 4000, 500)
    stats.observe("w", 4000, 500)
    stats.observe("b", 100, 100)
    s = stats.summary()
    assert s["raw_bytes"] == 8100
    assert s["wire_bytes_sent"] == 1100
    assert s["wire_bytes_saved"] == 7000
    assert stats.per_tensor()["w"] == (8000, 1000)
    line = stats.log_summary()
    assert "wire compression" in line and "saved" in line


# --------------------------------------------------- RemoteStore end-to-end


def test_remote_store_compressed_ef_converges_and_counts_bytes():
    set_config(Config(compression="onebit", compression_min_bytes=64))
    srv, addr = _spawn()
    try:
        store = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        target = _x(512, seed=1)
        state = np.zeros(512, np.float32)
        store.init_tensor("w", state)
        e0 = np.linalg.norm(state - target)
        for _ in range(200):
            state = store.push_pull("w", (0.2 * (target - state)))
        # timing-independent contraction bound (PR-2 deflake style): EF
        # keeps signSGD contracting; without EF it stalls near the scale
        assert np.linalg.norm(state - target) < e0 / 20
        s = get_compression_stats().summary()
        assert s["wire_bytes_saved"] > 0
        assert s["compression_ratio"] > 4  # onebit >> 4x on the push leg
        store.close()
    finally:
        srv.shutdown(); srv.server_close()


def test_remote_store_partitioned_compressed_roundtrip():
    """Partition composition: a tensor bigger than BYTEPS_PARTITION_BYTES
    splits into independently compressed name#p{i} parts; pull and
    version reassemble/route through them."""
    set_config(Config(compression="int8", compression_min_bytes=64,
                      partition_bytes=1024, partition_align=1))
    srv, addr = _spawn()
    try:
        store = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        init = _x(1000, seed=2)  # 4000 B -> 4 partitions
        store.init_tensor("w", init)
        assert sorted(store.names()) == [f"w#p{i}" for i in range(4)]
        np.testing.assert_array_equal(store.pull("w"), init)
        delta = _x(1000, seed=3)
        out = store.push_pull("w", delta)
        assert out.shape == (1000,)
        # int8 EF: applied delta is the dithered quantization of delta
        err = np.abs(out - (init + delta))
        scale = np.abs(delta).max() / 127.0
        assert err.max() <= 1.5 * scale + 1e-6
        assert store.version("w") == 1  # per-partition counters, p0 asked
        store.close()
    finally:
        srv.shutdown(); srv.server_close()


def test_fresh_client_discovers_partitioned_tensor():
    """A client that never pushed a partitioned tensor (no local meta)
    must still be able to pull it: parts are discovered via names() and
    reassembled flat (original shape is client-local knowledge)."""
    set_config(Config(partition_bytes=1024, partition_align=1))
    srv, addr = _spawn()
    try:
        writer = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        init = _x(1000, seed=8)  # 4000 B -> 4 partitions
        writer.init_tensor("w", init)

        reader = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        out = reader.pull("w")  # no meta: discovery path
        np.testing.assert_array_equal(out, init)  # flat == original here
        assert reader.version("w") == 0
        writer.close(); reader.close()
    finally:
        srv.shutdown(); srv.server_close()


def test_server_decompresses_and_sums_in_fp32():
    """The server-side leg alone: a hand-built compressed PUSH lands in
    the store as exactly the dequantized dense value."""
    set_config(Config())
    srv, addr = _spawn()
    try:
        store = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        base = np.zeros(256, np.float32)
        store.init_tensor("w", base)
        g = _x(256, seed=4)
        blob, deq = encode_blob(get_scheme("onebit"), g)
        # push the raw blob through the private RPC door
        store._rpc(0, ps_server.OP_PUSH, "w", blob)
        np.testing.assert_allclose(store.pull("w"), deq, rtol=1e-6)
        store.close()
    finally:
        srv.shutdown(); srv.server_close()


def test_reply_compression_casts_pull_leg():
    set_config(Config(compression_reply="bf16", compression_min_bytes=64))
    srv, addr = _spawn()
    try:
        store = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
        v = _x(512, seed=5)
        store.init_tensor("w", v)
        pulled = store.pull("w")
        import ml_dtypes

        expect = v.astype(ml_dtypes.bfloat16).astype(np.float32)
        np.testing.assert_array_equal(pulled, expect)
        assert not np.array_equal(pulled, v)  # the cast actually happened
        store.close()
    finally:
        srv.shutdown(); srv.server_close()


# ------------------------------------------- retry replay (exactly-once×EF)


def _ef_train(store, steps, target, dim=256):
    state = np.zeros(dim, np.float32)
    store.init_tensor("w", state)
    for _ in range(steps):
        state = store.push_pull("w", (0.2 * (target - state)))
    return state


@pytest.mark.parametrize("scheme", ["onebit", "randomk"])
def test_retried_compressed_push_never_double_folds(scheme):
    """The acceptance-criteria chaos property, deterministic edition: a
    scripted drop_after (mutation applied, reply lost, connection reset)
    on a compressed PUSH_PULL must be version-guard deduplicated — the
    resent bytes are identical (seeded schemes replay the same
    coordinates) and the EF residual commits exactly once, so the
    faulted run finishes bit-for-bit equal to the clean run."""
    cfgkw = dict(compression=scheme, compression_min_bytes=64,
                 compression_ratio=0.05)
    target = _x(256, seed=6)

    # clean run
    set_config(Config(**cfgkw))
    srv, addr = _spawn()
    store = ps_server.RemoteStore([addr], retry_policy=_fast_policy())
    clean = _ef_train(store, 30, target)
    store.close(); srv.shutdown(); srv.server_close()

    # faulted run: drop_after on three of the compressed PUSH_PULLs
    reset_config()
    reset_compression_stats()
    set_config(Config(**cfgkw))
    srv, addr = _spawn()
    proxy = FaultInjectingProxy(addr, seed=0)
    # request 1 = INIT; fault requests 3, 9, 17 (all PUSH_PULLs)
    script = ["pass"] * 40
    for i in (2, 8, 16):
        script[i] = "drop_after"
    proxy.script(*script)
    counters = ResilienceCounters()
    store = ps_server.RemoteStore([proxy.addr],
                                  retry_policy=_fast_policy(),
                                  counters=counters)
    chaos = _ef_train(store, 30, target)
    assert proxy.faults_injected == 3
    assert counters.snapshot().get(cn.DEDUP, 0) >= 1
    store.close(); proxy.close(); srv.shutdown(); srv.server_close()

    assert clean.tobytes() == chaos.tobytes(), (
        f"{scheme}: retried compressed PUSH diverged from the clean run "
        f"(max |d| = {np.abs(clean - chaos).max()})")


def test_seeded_chaos_run_is_reproducible():
    """Same seeds, same fault plan -> bit-identical results across two
    whole chaos runs (the 'run-reproducible' half of the criterion, at a
    tier-1-friendly size; scripts/chaos_smoke.py does the >=25% rate)."""

    def run():
        reset_config()
        reset_compression_stats()
        set_config(Config(compression="randomk", compression_min_bytes=64,
                          compression_ratio=0.1))
        srv, addr = _spawn()
        proxy = FaultInjectingProxy(addr, seed=3)
        proxy.set_rates(drop_after=0.15, drop_before=0.1)
        store = ps_server.RemoteStore([proxy.addr],
                                      retry_policy=_fast_policy())
        out = _ef_train(store, 25, _x(256, seed=7))
        faults = proxy.faults_injected
        store.close(); proxy.close(); srv.shutdown(); srv.server_close()
        return out, faults

    out1, faults1 = run()
    out2, faults2 = run()
    assert faults1 > 0 and faults1 == faults2
    assert out1.tobytes() == out2.tobytes()
