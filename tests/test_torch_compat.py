"""PyTorch front-end tests (byteps_tpu.torch) — the reference's
``byteps.torch`` surface: push_pull(_async)(_inplace) on torch tensors,
broadcast_parameters/broadcast_optimizer_state on torch modules/optims,
and DistributedOptimizer wrapping torch.optim.

Single-process here (the process==worker mapping means size()==1, where
push_pull is the identity-average — the reference behaves the same); the
cross-process reduce path is covered by tests/test_multihost.py.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import byteps_tpu.torch as bps_t


@pytest.fixture(autouse=True)
def _init():
    bps_t.init()
    yield


def test_push_pull_identity_single_worker():
    x = torch.arange(12, dtype=torch.float32).reshape(3, 4)
    out = bps_t.push_pull(x.clone(), average=True, name="t0")
    assert isinstance(out, torch.Tensor)
    assert out.dtype == torch.float32
    torch.testing.assert_close(out, x)
    # sum mode with one worker is also identity
    out = bps_t.push_pull(x.clone(), average=False, name="t0_sum")
    torch.testing.assert_close(out, x)


def test_push_pull_async_poll_synchronize():
    x = torch.ones(8)
    h = bps_t.push_pull_async(x, name="t1")
    bps_t.synchronize(h)  # completes regardless of poll state
    h2 = bps_t.push_pull_async(x, name="t1")
    out = bps_t.synchronize(h2)
    torch.testing.assert_close(out, x)


def test_push_pull_inplace_writes_back():
    x = torch.full((4,), 3.0)
    out = bps_t.push_pull_inplace(x, average=True, name="t2")
    assert out is x
    torch.testing.assert_close(x, torch.full((4,), 3.0))


def test_fp16_compression_roundtrip():
    x = torch.randn(16)
    out = bps_t.push_pull(x.clone(), name="t3",
                          compression=bps_t.Compression.fp16)
    assert out.dtype == torch.float32
    torch.testing.assert_close(out, x, rtol=1e-3, atol=1e-3)


def test_broadcast_parameters_state_dict():
    model = torch.nn.Linear(4, 2)
    before = {k: v.clone() for k, v in model.state_dict().items()}
    bps_t.broadcast_parameters(model.state_dict(), root_rank=0)
    # single worker: broadcast is identity, tensors unchanged in place
    for k, v in model.state_dict().items():
        torch.testing.assert_close(v, before[k])


def test_broadcast_optimizer_state():
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    # materialize momentum state
    model(torch.randn(8, 4)).sum().backward()
    opt.step()
    lr_before = opt.param_groups[0]["lr"]
    bps_t.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(lr_before)
    for pstate in opt.state_dict()["state"].values():
        for v in pstate.values():
            if isinstance(v, torch.Tensor):
                assert v.dtype in (torch.float32, torch.float64)


def test_distributed_optimizer_trains():
    """The wrapped torch optimizer drives a model to fit a linear target
    (glue test: grads flow through push_pull, update applies)."""
    torch.manual_seed(0)
    model = torch.nn.Linear(4, 1, bias=False)
    w_true = torch.tensor([[1.0, -2.0, 0.5, 3.0]])
    x = torch.randn(64, 4)
    y = x @ w_true.t()

    opt = bps_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    for _ in range(200):
        opt.zero_grad()  # grads persist after step() like the reference
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
    assert float(loss.detach()) < 1e-3
    torch.testing.assert_close(model.weight.detach(), w_true,
                               rtol=0.05, atol=0.05)


def test_distributed_optimizer_backward_passes_per_step():
    """Reference contract (torch/__init__.py:140-154): hooks count
    *backward passes*; N backwards then ONE step() applies the summed
    accumulated gradient (no division — Horovod semantics)."""
    model = torch.nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.fill_(1.0)
    opt = bps_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1.0),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2,
    )
    x = torch.ones(1, 2)

    model(x).sum().backward()      # grad = [1, 1]; delay 2 -> 1, no comm
    assert opt._bps_handles[model.weight] is None
    model(x).sum().backward()      # grad accumulates to [2, 2]; enqueues
    assert opt._bps_handles[model.weight] is not None
    opt.step()                     # update with the accumulated [2, 2]
    torch.testing.assert_close(model.weight,
                               -torch.ones_like(model.weight))


def test_distributed_optimizer_excess_backward_raises():
    """A third backward before step() with backward_passes_per_step=2
    raises (reference torch/__init__.py:141-147 assertion) — deferred to
    synchronize/step: raising inside an autograd hook can terminate the
    process, so the hook records the violation instead."""
    model = torch.nn.Linear(2, 1, bias=False)
    opt = bps_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1.0),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2,
    )
    x = torch.ones(1, 2)
    model(x).sum().backward()
    model(x).sum().backward()
    model(x).sum().backward()  # one too many — recorded, not raised here
    with pytest.raises(AssertionError, match="backward_passes_per_step"):
        opt.step()


def test_distributed_optimizer_early_step_reduces_accumulated():
    """step() before the Nth backward still reduces + applies whatever has
    accumulated (reference synchronize covers missing/None handles)."""
    model = torch.nn.Linear(2, 1, bias=False)
    with torch.no_grad():
        model.weight.fill_(1.0)
    opt = bps_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=1.0),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=4,
    )
    x = torch.ones(1, 2)
    model(x).sum().backward()      # 1 of 4 passes
    opt.step()                     # applies [1, 1]
    torch.testing.assert_close(model.weight,
                               torch.zeros_like(model.weight))
    # delays re-armed: the next 4-pass cycle starts fresh
    assert all(d == 4 for d in opt._bps_delay.values())


def test_hooks_enqueue_during_backward_in_priority_order():
    """The hook protocol (reference torch/__init__.py:112-154): push_pull
    tasks enter the engine *during* loss.backward() — before step() — in
    backward order (last layer first), each carrying the reference
    priority (-declared key, so earlier-declared names drain first)."""
    from byteps_tpu.engine import dispatcher as _dispatcher

    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8, bias=False),
        torch.nn.ReLU(),
        torch.nn.Linear(8, 1, bias=False),
    )
    engine = _dispatcher.get_engine()
    seen = []
    orig = engine.push_pull_async

    def spy(stacked, name, **kw):
        seen.append(name)
        return orig(stacked, name, **kw)

    engine.push_pull_async = spy
    try:
        opt = bps_t.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        loss = model(torch.randn(4, 4)).sum()
        loss.backward()
        # comm was enqueued by the hooks, before any step()/synchronize()
        assert seen == ["Gradient.2.weight", "Gradient.0.weight"]
        assert all(h is not None for h in opt._bps_handles.values())
        opt.step()
    finally:
        engine.push_pull_async = orig
    # correctness: single worker, averaged grad == local grad -> plain SGD
    for p in model.parameters():
        assert p.grad is not None


def test_distributed_optimizer_synchronize_for_clipping():
    """Public synchronize() between backward and step() (the reference's
    gradient-clipping recipe, torch/__init__.py docstring)."""
    model = torch.nn.Linear(4, 1, bias=False)
    opt = bps_t.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters(),
    )
    (model(torch.ones(2, 4)).sum() * 100).backward()
    opt.synchronize()
    torch.nn.utils.clip_grad_norm_(model.parameters(), 1.0)
    g = model.weight.grad.clone()
    assert float(g.norm()) <= 1.0 + 1e-5
    opt.step()
