"""The bench certification line (r4 verdict #3): printed LAST, compact
enough to survive the driver's ~2000-char stdout tail, and carrying every
bar-certified row's verdict + the headline numbers."""

import json

from bench import _certification


def _rows():
    return [
        {"metric": "resnet50_fp32_b64_images_per_sec", "value": 1128.0,
         "unit": "images/sec", "vs_baseline": 1.005, "aa_spread": 0.01,
         "bar_pass": True},
        {"metric": "resnet50_bf16_b64_images_per_sec", "value": 2064.0,
         "unit": "images/sec", "vs_baseline": 1.005, "aa_spread": 0.01,
         "bar_pass": True},
        {"metric": "vgg16_fp32_b64_images_per_sec", "value": 731.0,
         "unit": "images/sec", "vs_baseline": 0.995, "aa_spread": 0.02,
         "bar_pass": True},
        {"metric": "bert_base_finetune_tokens_per_sec", "value": 198000.0,
         "unit": "tokens/sec", "vs_baseline": 1.0, "aa_spread": 0.01,
         "bar_pass": False},
        {"metric": "flash_attention_causal_T4096_tokens_per_sec",
         "value": 1.9e6, "unit": "tokens/sec", "vs_baseline": 4.05,
         "mfu": 0.212},
        {"metric": "flash_attention_causal_T4096_D128_tokens_per_sec",
         "value": 2.9e6, "unit": "tokens/sec", "vs_baseline": 3.78,
         "mfu": 0.449},
        {"metric": "lm_train_flash_T2048_tokens_per_sec", "value": 97000.0,
         "unit": "tokens/sec", "vs_baseline": 2.21},
        {"metric": "generate_decode_T256_N32_tokens_per_sec",
         "value": 10800.0, "unit": "tokens/sec", "vs_baseline": 2.73,
         "ms_per_token_decode": 0.74},
        {"metric": "generate_decode_gqa2kv_T256_tokens_per_sec",
         "value": 29000.0, "unit": "tokens/sec",
         "ms_per_token_decode": 0.27},
        {"metric": "generate_decode_B1_T256_int8_tokens_per_sec",
         "value": 4200.0, "unit": "tokens/sec", "vs_baseline": 1.2},
        {"metric": "generate_decode_int8kv_B32_T2048_tokens_per_sec",
         "value": 33600.0, "unit": "tokens/sec", "vs_baseline": 1.54},
        {"metric": "generate_decode_int8kv_mha_B8_T1024_tokens_per_sec",
         "value": 12230.0, "unit": "tokens/sec", "vs_baseline": 1.09,
         "ms_per_token_decode": 0.654},
        {"metric": "speculative_layerskip_trained_B1_T256_tokens_per_sec",
         "value": 7100.0, "unit": "tokens/sec", "vs_baseline": 1.98},
    ]


def test_certification_line():
    rows = _rows()
    cert = _certification(rows, rows[0])
    assert cert["metric"] == "certification"
    assert cert["rows"] == len(rows)
    assert cert["bar_pass_all"] is False
    assert cert["bar_fails"] == ["bert_base_finetune_tokens_per_sec"]
    assert len(cert["bars"]) == 4
    kn = cert["key_numbers"]
    assert kn["resnet50_bf16_img_s"] == 2064.0
    assert kn["flash_d128_mfu"] == 0.449
    assert kn["lm_flash_vs_naive"] == 2.21
    assert kn["decode_b8_ms_tok"] == 0.74
    assert kn["decode_gqa_ms_tok"] == 0.27
    assert kn["decode_b1_int8_vs_bf16"] == 1.2
    assert kn["int8kv_b32_vs_bf16"] == 1.54
    assert kn["int8kv_mha_ms_tok"] == 0.654
    assert kn["spec_trained_vs_plain"] == 1.98
    # must survive the driver's ~2000-char tail capture
    assert len(json.dumps(cert)) < 1900


def test_certification_all_pass_flag():
    rows = [r for r in _rows()
            if r["metric"] != "bert_base_finetune_tokens_per_sec"]
    cert = _certification(rows, rows[0])
    assert cert["bar_pass_all"] is True and cert["value"] == 1.0
