"""CI wiring for the bench_comm.py per-transport A/B (PR 7 acceptance:
same-host `unix` and/or `shm` >= 1.8x TCP-loopback wire throughput at
>= 1 MiB tensors, min-of-reps).  Runs the bench as a subprocess — the
script owns its jax platform setup — and asserts on the JSON rows it
prints (which it also append-archives into BENCH_COMM.json, the same
pattern as the serve/compress bench tests).

Marked ``slow`` so tier-1 (-m 'not slow') stays fast.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.mark.slow
def test_bench_comm_transport_ab_meets_bar():
    proc = subprocess.run(
        [sys.executable, "bench_comm.py", "--transports-only"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    by_metric = {r["metric"]: r for r in rows if "transport" in r}
    assert len(by_metric) == 6, sorted(by_metric)  # 3 transports x 2 ops

    # TCP reference rows are self-normalized
    assert by_metric["wire_transport_pull_tcp_1mb_ms"]["vs_tcp_min"] == 1.0
    # acceptance: unix AND/OR shm clears 1.8x on at least one op (shm
    # clears both on every observed run; the and/or guards this bursty
    # 2-vCPU host's throttle windows)
    fast = [by_metric[f"wire_transport_{op}_{t}_1mb_ms"]["vs_tcp_min"]
            for op in ("pull", "push_pull") for t in ("unix", "shm")]
    assert max(fast) >= 1.8, by_metric
    # and the fast path must never be a regression on the other op
    assert all(v >= 0.7 for v in fast), by_metric

    # the rows landed in the archive
    with open(os.path.join(REPO, "BENCH_COMM.json")) as f:
        archived = {r["metric"] for r in json.load(f)["rows"]}
    assert "wire_transport_pull_shm_1mb_ms" in archived


@pytest.mark.slow
def test_bench_comm_hierarchical_ab_meets_bar():
    """ISSUE 8 acceptance: with hierarchical push/pull on, mutation
    wire bytes per step drop by >= 0.9 x local_size on the emulated
    local mesh (4 workers), and the rows are archived."""
    proc = subprocess.run(
        [sys.executable, "bench_comm.py", "--hierarchical"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    row = next(r for r in rows
               if r["metric"] == "hierarchical_wire_bytes_per_step")
    assert row["byte_reduction_x"] >= 0.9 * row["local_size"], row
    # the local reduction must not make the wall clock WORSE on a
    # latency-dominated wire (it sends 1/local_size the bytes)
    assert row["speedup_min"] >= 0.9, row
    with open(os.path.join(REPO, "BENCH_COMM.json")) as f:
        archived = {r["metric"] for r in json.load(f)["rows"]}
    assert "hierarchical_wire_bytes_per_step" in archived


@pytest.mark.slow
def test_bench_comm_zero_ab_meets_bar():
    """ISSUE 20 acceptance: ZeRO-1 optimizer-state sharding at world=2
    cuts per-rank mutation wire bytes AND client optimizer-state bytes
    by >= 1.8x vs the replicated loop, with bit-equal final params,
    and the row is archived."""
    proc = subprocess.run(
        [sys.executable, "bench_comm.py", "--zero"],
        cwd=REPO, capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.startswith("{")]
    row = next(r for r in rows
               if r["metric"] == "zero_mutation_bytes_per_rank_step")
    assert row["bit_equal"] is True, row
    assert row["byte_reduction_x"] >= 0.9 * row["world"], row
    assert row["state_bytes_reduction_x"] >= 0.9 * row["world"], row
    with open(os.path.join(REPO, "BENCH_COMM.json")) as f:
        archived = {r["metric"] for r in json.load(f)["rows"]}
    assert "zero_mutation_bytes_per_rank_step" in archived
