"""KV-cache decode + generation loop (byteps_tpu/inference.py).

The reference has no inference path (it is a training-comm library); this
is the framework's own autoregressive story.  Ground truth for every test
is the model's full causal forward — decode must reproduce it exactly
(same params, fp32 logits head).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.inference import generate, make_generate_fn, sample_logits
from byteps_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    init_cache,
)


def _tiny_model(**kw):
    cfg = TransformerConfig(
        vocab_size=61, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, **kw)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    return cfg, model, tokens, variables


def test_prefill_matches_forward():
    cfg, model, tokens, variables = _tiny_model()
    full = model.apply(variables, tokens)
    caches = init_cache(cfg, tokens.shape[0], 24)
    logits, new_caches = model.apply(
        variables, tokens, caches, 0, method=Transformer.decode)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full), rtol=1e-5, atol=1e-5)
    # prompt K/V landed in slots [0, T); the tail stayed zero
    assert not np.allclose(np.asarray(new_caches[0]["k"][:, :16]), 0)
    np.testing.assert_array_equal(
        np.asarray(new_caches[0]["k"][:, 16:]), 0)


def test_incremental_decode_matches_forward():
    """Feeding tokens one at a time through the cache reproduces the full
    forward's logits at every position."""
    cfg, model, tokens, variables = _tiny_model()
    B, T = tokens.shape
    full = model.apply(variables, tokens)
    caches = init_cache(cfg, B, T)
    outs = []
    for t in range(T):
        logits, caches = model.apply(
            variables, tokens[:, t:t + 1], caches, t,
            method=Transformer.decode)
        outs.append(logits[:, 0])
    got = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(full), rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # ~16s: token-by-token reference loop (tier-1 duration budget); incremental_decode/prefill/windowed parity stay fast
def test_greedy_generate_matches_reference_loop():
    """The scan-based generate equals a naive loop that re-runs the full
    forward on the growing sequence each step."""
    cfg, model, tokens, variables = _tiny_model()
    n = 8
    out = generate(model, variables, tokens, n, temperature=0)

    seq = tokens
    want = []
    for _ in range(n):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(out["tokens"]), np.asarray(jnp.stack(want, axis=1)))


@pytest.mark.slow
def test_generate_windowed_flash_model():
    """Decode applies the config's sliding window: greedy generation from a
    windowed model matches the naive full-forward loop of the same model.
    Slow: the windowed flash variant pays its own Pallas compile; the
    fast flash coverage is test_flash_prefill_matches_dense_cache_path /
    test_flash_prefill_awkward_lengths_fall_back."""
    cfg, model, tokens, variables = _tiny_model(
        attn_impl="flash", attn_window=8)
    n = 6
    out = generate(model, variables, tokens, n, temperature=0)
    seq = tokens
    want = []
    for _ in range(n):
        logits = model.apply(variables, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(
        np.asarray(out["tokens"]), np.asarray(jnp.stack(want, axis=1)))


def test_flash_prefill_matches_dense_cache_path():
    """The static pos=0 prefill fast path (Pallas flash kernel) must agree
    with the dense cached-attention path it replaces."""
    cfg, model, tokens, variables = _tiny_model(attn_impl="flash")
    caches = init_cache(cfg, tokens.shape[0], 24)
    # flash fast path engages for literal pos=0 with tq>1
    fast, fast_caches = model.apply(
        variables, tokens, caches, 0, method=Transformer.decode)
    # traced pos forces the dense path on identical math
    dense, dense_caches = jax.jit(
        lambda v, t, c, p: model.apply(v, t, c, p,
                                       method=Transformer.decode)
    )(variables, tokens, caches, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(fast), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)
    for fc, dc in zip(fast_caches, dense_caches):
        np.testing.assert_allclose(np.asarray(fc["k"]), np.asarray(dc["k"]),
                                   rtol=1e-6, atol=1e-6)


def test_flash_prefill_awkward_lengths_fall_back():
    """Prompt lengths the Pallas block fitter can't serve (tiny, or odd
    T>1024) must route to the dense cache path, not crash (regression:
    T=4 raised ValueError from fit_block)."""
    cfg, model, _, _ = _tiny_model(attn_impl="flash")
    init_tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), init_tokens)
    for T in (4, 7):
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, T), 0, 61)
        out = generate(model, variables, prompt, 3, temperature=0)
        assert out["tokens"].shape == (2, 3)


def test_eos_freezes_row():
    cfg, model, tokens, variables = _tiny_model()
    n = 8
    out = generate(model, variables, tokens, n, temperature=0)
    # pick the token the model actually emits at step 0 for row 0 as the
    # "eos" and re-generate: row 0 must freeze to pad from step 1 on
    eos = int(out["tokens"][0, 0])
    out2 = generate(model, variables, tokens, n, temperature=0,
                    eos_id=eos, pad_id=60)
    got = np.asarray(out2["tokens"][0])
    assert got[0] == eos
    after = got[1:][got[1:] != 60]
    # every surviving non-pad token can only appear before eos was hit
    assert after.size == 0 or bool(out2["done"][0]) is True
    assert bool(out2["done"][0])
    np.testing.assert_array_equal(got[1:], 60)


def test_sampling_filters():
    rng = jax.random.PRNGKey(0)
    logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
    # top_k=1 is greedy regardless of rng
    for i in range(5):
        tok = sample_logits(logits, jax.random.fold_in(rng, i),
                            temperature=1.0, top_k=1)
        assert int(tok[0]) == 0
    # top_p=0.6 keeps {0, 1} only
    seen = set()
    for i in range(64):
        tok = sample_logits(logits, jax.random.fold_in(rng, i),
                            temperature=1.0, top_p=0.6)
        seen.add(int(tok[0]))
    assert seen <= {0, 1} and 0 in seen
    # temperature=0 is argmax
    assert int(sample_logits(logits, rng, temperature=0)[0]) == 0


def test_generate_batch_and_shapes():
    cfg, model, tokens, variables = _tiny_model()
    fn = make_generate_fn(model, 5, temperature=0.7, top_k=10)
    out = fn(variables, tokens, jax.random.PRNGKey(3))
    assert out["tokens"].shape == (2, 5)
    assert out["tokens"].dtype in (jnp.int32, jnp.int64)
    assert ((out["tokens"] >= 0) & (out["tokens"] < 61)).all()
    # two rows with different prompts should (generically) diverge
    assert not np.array_equal(np.asarray(out["tokens"][0]),
                              np.asarray(out["tokens"][1]))


def test_prefill_last_only():
    """last_only prefill returns [B, 1, vocab] matching the full variant's
    final position (the generation hot path skips the other T-1 heads)."""
    cfg, model, tokens, variables = _tiny_model()
    caches = init_cache(cfg, tokens.shape[0], 20)
    full, _ = model.apply(
        variables, tokens, caches, 0, method=Transformer.decode)
    last, _ = model.apply(
        variables, tokens, caches, 0, True, method=Transformer.decode)
    assert last.shape == (2, 1, 61)
    np.testing.assert_allclose(
        np.asarray(last[:, 0]), np.asarray(full[:, -1]),
        rtol=1e-5, atol=1e-5)


def test_cache_rejects_key_mask():
    """Padded prompts must error, not silently poison the cache."""
    cfg, model, tokens, variables = _tiny_model()
    from byteps_tpu.models.transformer import Block
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 32))
    mask = jnp.ones((2, 4), jnp.int32)
    cache = init_cache(cfg, 2, 8)[0]
    blk = Block(cfg)
    v = blk.init(jax.random.PRNGKey(1), x)
    with pytest.raises(ValueError):
        blk.apply(v, x, key_mask=mask, cache=cache, pos=0)


def test_generate_requires_rng_when_sampling():
    cfg, model, tokens, variables = _tiny_model()
    with pytest.raises(ValueError):
        generate(model, variables, tokens, 4, temperature=0.8)
    # greedy stays rng-free
    generate(model, variables, tokens, 2, temperature=0)


def test_generate_dp_sharded():
    """Distributed inference: generation with the batch sharded over an
    8-device dp mesh equals the single-device result — XLA partitions the
    whole prefill+scan program (cache included) along batch."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    cfg, model, _, _ = _tiny_model()
    prompt = jax.random.randint(jax.random.PRNGKey(4), (8, 12), 0, 61)
    variables = model.init(jax.random.PRNGKey(1), prompt)
    want = generate(model, variables, prompt, 6, temperature=0)

    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharded = jax.device_put(prompt, NamedSharding(mesh, P("dp", None)))
    repl = jax.device_put(variables, NamedSharding(mesh, P()))
    got = generate(model, repl, sharded, 6, temperature=0)
    np.testing.assert_array_equal(
        np.asarray(got["tokens"]), np.asarray(want["tokens"]))


def test_cache_len_guard():
    cfg, model, tokens, variables = _tiny_model()
    with pytest.raises(ValueError):
        init_cache(cfg, 2, cfg.max_seq_len + 1)
    noncausal = TransformerConfig(
        vocab_size=61, num_layers=1, num_heads=2, d_model=32, d_ff=64,
        max_seq_len=64, dtype=jnp.float32, causal=False)
    m2 = Transformer(noncausal)
    v2 = m2.init(jax.random.PRNGKey(0), tokens)
    c2 = init_cache(noncausal, 2, 32)
    with pytest.raises(ValueError):
        m2.apply(v2, tokens, c2, 0, method=Transformer.decode)


def test_classify_divergence_none_tie_real():
    """The divergence classifier (VERDICT r3 #8): identical decodes ->
    none; a second-best-token flip within the tie threshold -> tie; an
    injected cache-bug-style wrong token (clearly lower logit) -> real."""
    import numpy as np

    from byteps_tpu.inference import classify_divergence, generate

    cfg, model, _, variables = _tiny_model()
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                                cfg.vocab_size)
    out = generate(model, variables, prompt, 8, temperature=0)
    toks = np.asarray(out["tokens"])

    res = classify_divergence(model, variables, prompt, toks, toks)
    assert res["divergence"] == "none"

    # teacher-force to find the runner-up token at a mid position
    full = jnp.concatenate([prompt, jnp.asarray(toks)], axis=1)
    logits = np.asarray(model.apply(variables, full), np.float32)
    T = prompt.shape[1]
    d = 4
    row = logits[0, T + d - 1]
    order = np.argsort(row)[::-1]
    runner_up = int(order[1] if order[0] == toks[0, d] else order[0])
    worst = int(order[-1])

    tie_b = toks.copy()
    tie_b[0, d] = runner_up
    # generous threshold -> the runner-up flip classifies as a tie
    res = classify_divergence(model, variables, prompt, toks, tie_b,
                              tie_rtol=10.0)
    assert res["divergence"] == "tie" and res["first_div_pos"] == d

    bug_b = toks.copy()
    bug_b[0, d] = worst
    # an injected wrong token (cache-bug analog) must classify as real
    res = classify_divergence(model, variables, prompt, toks, bug_b,
                              tie_rtol=0.0, tie_atol=1e-6)
    assert res["divergence"] == "real"
    assert res["first_div_pos"] == d
    assert res["delta_logit"] > 0  # path A's token scores higher


# ---------------------------------------------------------------------------
# flat [B, S, KV*D] decode-kernel cache layout (ops/decode_attention.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv", [None, 1])
def test_flat_cache_generate_matches_grouped(kv):
    """The flat decode-kernel layout must generate the same greedy tokens
    as the grouped dense layout (CPU: kernel runs in interpret mode)."""
    cfg, model, tokens, variables = _tiny_model(num_kv_heads=kv)
    fn_g = make_generate_fn(model, 6, temperature=0,
                            cache_layout="grouped")
    fn_f = make_generate_fn(model, 6, temperature=0, cache_layout="flat")
    rng = jax.random.PRNGKey(3)
    out_g = fn_g(variables, tokens, rng)
    out_f = fn_f(variables, tokens, rng)
    np.testing.assert_array_equal(np.asarray(out_g["tokens"]),
                                  np.asarray(out_f["tokens"]))


@pytest.mark.slow  # ~11s: token-by-token stepwise reference loop (tier-1 duration budget); flat_cache_generate_matches_grouped keeps flat-layout parity fast
def test_flat_cache_stepwise_matches_forward():
    """Per-token decode against the flat cache reproduces the full causal
    forward — including the tq>1-at-pos>0 dense fallback (speculative
    verify) and awkward-length dense prefill."""
    cfg, model, tokens, variables = _tiny_model()
    B, T = tokens.shape
    full = model.apply(variables, tokens)
    caches = init_cache(cfg, B, T, layout="flat")
    assert caches[0]["k"].ndim == 3
    # prefill the first 11 tokens (awkward length -> dense prefill on
    # fresh k/v), then one-token decode steps, then a 3-token chunk at
    # pos>0 (the speculative-verify shape)
    logits, caches = model.apply(
        variables, tokens[:, :11], caches, 0, method=Transformer.decode)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full[:, :11]),
                               rtol=2e-4, atol=2e-4)
    outs = [logits]
    for t in range(11, 13):
        logits, caches = model.apply(
            variables, tokens[:, t:t + 1], caches, t,
            method=Transformer.decode)
        outs.append(logits)
    logits, caches = model.apply(
        variables, tokens[:, 13:16], caches, jnp.int32(13),
        method=Transformer.decode)
    outs.append(logits)
    got = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.skipif(jax.default_backend() != "cpu",
                    reason="asserts the CPU resolution of auto")
def test_flat_cache_auto_layout_cpu_is_grouped():
    cfg, model, tokens, variables = _tiny_model()
    caches = init_cache(cfg, 2, 24, layout="auto")
    # CPU backend: auto resolves to grouped (interpret-mode Pallas per
    # decode step would crawl); the TPU resolution is covered on-chip
    assert caches[0]["k"].ndim == 4


def test_classify_divergence_position_profile():
    """The position profile separates late near-tie churn from an early
    cliff (r4 verdict: one sentence of diagnosis next to the number)."""
    from byteps_tpu.inference import classify_divergence

    cfg, model, tokens, variables = _tiny_model()
    N = 16
    base = np.asarray(
        jax.random.randint(jax.random.PRNGKey(9), (2, N), 0, 50))
    # churn: row 0 diverges late (pos 12), row 1 later (pos 14)
    churn = base.copy()
    churn[0, 12:] = (churn[0, 12:] + 1) % 50
    churn[1, 14:] = (churn[1, 14:] + 1) % 50
    res = classify_divergence(model, variables, tokens[:, :4],
                              base, churn)
    assert res["first_div_positions"] == [12, 14]
    q = res["div_frac_by_quarter"]
    assert len(q) == 4 and q[0] == 0.0 and q[1] == 0.0 and q[3] == 0.75
    # cliff: both rows diverge from pos 1
    cliff = base.copy()
    cliff[:, 1:] = (cliff[:, 1:] + 1) % 50
    res = classify_divergence(model, variables, tokens[:, :4],
                              base, cliff)
    assert res["first_div_positions"] == [1, 1]
    assert res["div_frac_by_quarter"][0] > 0.5
    # identical rows report -1
    same = base.copy()
    same[1] = base[1]
    mix = base.copy()
    mix[0, 5:] = (mix[0, 5:] + 3) % 50
    res = classify_divergence(model, variables, tokens[:, :4], base, mix)
    assert res["first_div_positions"] == [5, -1]
