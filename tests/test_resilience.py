"""Resilience subsystem tests: retry policy, fault-injecting proxy,
version-guarded idempotence, heartbeat failure detection, degraded-mode
failover and bit-for-bit recovery.

Every network fault here is injected deterministically through
``FaultInjectingProxy`` (resilience/chaos.py) — no real network failures,
no sleeps hoping a race resolves.
"""

import struct
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config, reset_config, set_config
from byteps_tpu.engine import ps_server
from byteps_tpu.engine.ps_server import OP_PING, RemoteStore, _decode, _encode
from byteps_tpu.resilience import (DegradedModeRouter, FailureDetector,
                                   FaultInjectingProxy, ResilienceCounters,
                                   RetryPolicy, reset_counters)
from byteps_tpu.resilience import counters as cn


@pytest.fixture(autouse=True)
def _fresh_resilience_state():
    reset_config()
    reset_counters()
    yield
    reset_config()
    reset_counters()


def _spawn_shard():
    srv, thread = ps_server.serve(0, host="127.0.0.1", use_native=False,
                                  in_thread=True)
    return srv, thread, f"127.0.0.1:{srv.server_address[1]}"


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 4)
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("jitter", 0.0)
    kw.setdefault("deadline", 10.0)
    return RetryPolicy(**kw)


# ---------------------------------------------------------------- RetryPolicy


def test_retry_policy_backoff_schedule():
    p = RetryPolicy(max_attempts=4, backoff_base=0.1, backoff_mult=2.0,
                    jitter=0.0, backoff_cap=10.0, deadline=0.0)
    assert p.backoff(1) == 0.0
    assert p.backoff(2) == pytest.approx(0.1)
    assert p.backoff(3) == pytest.approx(0.2)
    assert p.backoff(4) == pytest.approx(0.4)
    # deadline 0 = unbounded; attempts still bound
    assert p.should_retry(3, p.start())
    assert not p.should_retry(4, p.start())


def test_retry_policy_jitter_bounded_and_seeded():
    import random

    p = RetryPolicy(backoff_base=1.0, backoff_mult=1.0, jitter=0.25,
                    backoff_cap=10.0)
    rng = random.Random(7)
    vals = [p.backoff(2, rng) for _ in range(50)]
    assert all(0.75 <= v <= 1.25 for v in vals)
    assert len(set(vals)) > 1  # actually randomized
    # same seed -> same schedule (determinism for chaos tests)
    rng2 = random.Random(7)
    assert vals == [p.backoff(2, rng2) for _ in range(50)]


def test_retry_policy_deadline_stops_retries():
    p = RetryPolicy(max_attempts=100, backoff_base=10.0, jitter=0.0,
                    deadline=0.5)
    # next backoff (10s) would overshoot the 0.5s deadline
    assert not p.should_retry(1, p.start())


def test_retry_policy_from_config():
    cfg = Config(retry_max_attempts=7, retry_backoff_ms=5.0,
                 retry_backoff_mult=3.0, retry_jitter=0.0,
                 retry_deadline_ms=1000.0)
    p = RetryPolicy.from_config(cfg)
    assert p.max_attempts == 7
    assert p.backoff_base == pytest.approx(0.005)
    assert p.backoff_mult == 3.0
    assert p.deadline == pytest.approx(1.0)


# ------------------------------------------------------------------- sharder


def test_sharder_remap_deterministic_next_alive():
    from byteps_tpu.common.context import ServerSharder

    assert ServerSharder.remap(1, {1}, 4) == 2
    assert ServerSharder.remap(3, {3, 0}, 4) == 1
    assert ServerSharder.remap(2, set(), 4) == 2
    with pytest.raises(RuntimeError):
        ServerSharder.remap(0, {0, 1}, 2)


def test_router_routes_around_down_shard_and_keeps_ledger():
    r = DegradedModeRouter(3, counters=ResilienceCounters())
    assert r.route(1) == 1
    assert r.mark_down(1)
    assert r.is_degraded()
    assert r.route(1) == 2
    assert r.route(0) == 0  # healthy shards unaffected
    r.note_failover("w", 1, 2)
    assert r.fallback_for("w") == 2
    assert r.failed_over_names(1) == [("w", 2)]
    assert r.mark_up(1)
    assert r.route(1) == 1
    # never excludes the last alive shard
    r2 = DegradedModeRouter(2, counters=ResilienceCounters())
    assert r2.mark_down(0)
    assert not r2.mark_down(1)
    assert r2.route(1) == 1


# ------------------------------------------------------------ chaos proxy


def test_proxy_passthrough_and_request_count():
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    try:
        store = RemoteStore([proxy.addr], retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        out = store.push_pull("w", np.ones(4, np.float32))
        np.testing.assert_allclose(out, 1.0)
        np.testing.assert_allclose(store.pull("w"), 1.0)
        assert proxy.requests_seen >= 3
        assert proxy.faults_injected == 0
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_reconnect_after_poisoned_socket_drop():
    """The seed's only recovery behavior — drop the poisoned cached
    socket so the next RPC reconnects — exercised deterministically: a
    scripted connection reset kills the cached socket mid-RPC; with
    retries disabled the op raises, and the *next* op transparently
    reconnects and succeeds."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy(max_attempts=1))
        store.init_tensor("w", np.zeros(2, np.float32))
        proxy.script("drop_before")
        with pytest.raises(OSError):
            store.pull("w")
        # poisoned socket was dropped -> this op opens a fresh connection
        np.testing.assert_allclose(store.pull("w"), 0.0)
        assert counters.get(cn.RECONNECT) >= 1
        assert counters.get(cn.GIVE_UP) == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_retry_recovers_from_transient_resets():
    """drop_before faults are retried transparently: the op succeeds and
    is applied exactly once (the request never reached the server)."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.script("drop_before", "drop_before")  # two resets, then ok
        out = store.push_pull("w", np.ones(4, np.float32))
        np.testing.assert_allclose(out, 1.0)  # applied exactly once
        # (the version-guard probe between attempts consumes one of the
        # scripted faults, so the exact retry count varies — >=1 holds)
        assert counters.get(cn.RETRY) >= 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_garbled_reply_poisons_socket_and_retries():
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.script("garble_reply")
        np.testing.assert_allclose(store.pull("w"), 0.0)
        assert counters.get(cn.RETRY) >= 1
        assert counters.get(cn.RECONNECT) >= 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_delay_fault_passes_through():
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    try:
        store = RemoteStore([proxy.addr], retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(2, np.float32))
        proxy.script(("delay", 0.2))
        t0 = time.monotonic()
        np.testing.assert_allclose(store.pull("w"), 0.0)
        assert time.monotonic() - t0 >= 0.2
        assert proxy.faults_injected == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


# ------------------------------------------------- version-guard idempotence


def test_retried_push_applied_exactly_once_under_connection_reset():
    """ISSUE acceptance: OP_PUSH whose reply is lost (applied server-side,
    connection reset before the status came back) must NOT be re-applied
    by the retry — the version guard (OP_VERSION vs the last acknowledged
    version) detects the landed mutation and suppresses the resend."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        # the ambiguous fault: push IS applied, reply discarded, reset
        proxy.script("drop_after")
        store.push_delta("w", np.ones(4, np.float32))
        np.testing.assert_allclose(store.pull("w"), 1.0)  # once, not twice
        assert counters.get(cn.DEDUP) == 1
        assert srv.store.version("w") == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_retried_push_resent_when_request_was_lost():
    """The complementary case: reset BEFORE the server saw the push — the
    version did not advance, so the retry must resend (otherwise the
    update is lost)."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.script("drop_before")
        store.push_delta("w", np.ones(4, np.float32))
        np.testing.assert_allclose(store.pull("w"), 1.0)
        assert counters.get(cn.DEDUP) == 0  # guard saw v unchanged
        assert srv.store.version("w") == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_retried_push_pull_exactly_once_with_result_recovery():
    """push_pull under drop_after: the add landed but its reply (the
    global tensor) was lost — the guard suppresses the resend and
    recovers the result with an idempotent pull."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.full(4, 10.0, np.float32))
        proxy.script("drop_after")
        out = store.push_pull("w", np.ones(4, np.float32))
        np.testing.assert_allclose(out, 11.0)  # 10 + 1, not 10 + 2
        assert counters.get(cn.DEDUP) == 1
        assert srv.store.version("w") == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_version_guard_auto_disabled_for_multi_worker(monkeypatch):
    """With DMLC_NUM_WORKER > 1 the version counter cannot attribute an
    advance to OUR lost push, so the guard auto-disables: retries fall
    back to at-least-once resend (double-apply beats a silent drop);
    BYTEPS_RETRY_VERSION_GUARD=1 forces it back on."""
    monkeypatch.setenv("DMLC_NUM_WORKER", "4")
    reset_config()
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    counters = ResilienceCounters()
    try:
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.script("drop_after")
        store.push_delta("w", np.ones(4, np.float32))
        # applied + resent = at-least-once double-apply, no dedup
        np.testing.assert_allclose(store.pull("w"), 2.0)
        assert counters.get(cn.DEDUP) == 0
        store.close()

        # explicit override re-enables exactly-once on a fresh store
        monkeypatch.setenv("BYTEPS_RETRY_VERSION_GUARD", "1")
        reset_config()
        store = RemoteStore([proxy.addr], counters=counters,
                            retry_policy=_fast_policy())
        store.init_tensor("w2", np.zeros(4, np.float32))
        proxy.script("drop_after")
        store.push_delta("w2", np.ones(4, np.float32))
        np.testing.assert_allclose(store.pull("w2"), 1.0)
        assert counters.get(cn.DEDUP) == 1
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


# ----------------------------------------------------------- failure detector


def test_failure_detector_transitions_and_callbacks():
    health = {0: True, 1: True}
    downs, ups = [], []
    det = FailureDetector(
        2, lambda s: health[s], interval=0.02, miss_threshold=2,
        on_down=downs.append, on_up=ups.append,
        counters=ResilienceCounters())
    det.start()
    try:
        time.sleep(0.1)
        assert det.is_up(0) and det.is_up(1)
        health[1] = False
        # poll the CALLBACK list, not is_up(): the state flips inside
        # the lock before the callback fires outside it
        deadline = time.monotonic() + 10.0
        while not downs and time.monotonic() < deadline:
            time.sleep(0.01)
        assert downs == [1] and ups == []
        assert not det.is_up(1)
        health[1] = True
        deadline = time.monotonic() + 10.0
        while not ups and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ups == [1]
        assert det.is_up(1)
    finally:
        det.stop()


def test_report_failure_accelerates_detection():
    det = FailureDetector(1, lambda s: True, interval=60.0,
                          miss_threshold=3, counters=ResilienceCounters())
    # never started: report_failure alone trips the threshold
    det.report_failure(0)
    det.report_failure(0)
    assert det.is_up(0)
    det.report_failure(0)
    assert not det.is_up(0)
    det.report_success(0)
    assert det.is_up(0)


def test_deadline_bounds_op_against_hung_shard():
    """BYTEPS_RETRY_DEADLINE_MS must bound the whole op even when the
    shard HANGS (accepts, never answers): each attempt's socket timeout
    is clamped to the remaining deadline, so a 30s connection timeout
    cannot stall a 1s-deadline op for minutes."""
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    try:
        store = RemoteStore([proxy.addr],
                            retry_policy=_fast_policy(max_attempts=10,
                                                      backoff_base=0.01,
                                                      deadline=1.0),
                            timeout=30.0, counters=ResilienceCounters())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.blackhole(True)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            store.pull("w")
        assert time.monotonic() - t0 < 5.0  # not 30s-per-attempt
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


def test_heartbeat_detects_blackholed_shard():
    """A hung (blackholed) shard times out pings and is declared down."""
    cfg = Config(heartbeat_timeout_ms=200.0)
    set_config(cfg)
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    try:
        store = RemoteStore([proxy.addr], retry_policy=_fast_policy(),
                            counters=ResilienceCounters())
        assert store.ping_shard(0)
        proxy.blackhole(True)
        assert not store.ping_shard(0)
        proxy.blackhole(False)
        assert store.ping_shard(0)
        store.close()
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()


# ------------------------------------------------------- failover + recovery


def _targets(dim, names):
    return {n: (np.arange(dim, dtype=np.float32) if n in ("w", "c0")
                else np.full(dim, -3.0, np.float32)) for n in names}


def _train(store, steps, lr=0.1, dim=4, names=("w", "b")):
    """Deterministic single-worker SGD-ish loop over the PS store:
    every step push_pulls a fixed-form delta per tensor.  Returns the
    final pulled values."""
    target = _targets(dim, names)
    state = {n: np.zeros(dim, np.float32) for n in names}
    for n in names:
        store.init_tensor(n, state[n])
    for _ in range(steps):
        for n in names:
            delta = lr * (target[n] - state[n])
            state[n] = store.push_pull(n, delta.astype(np.float32))
    return state


@pytest.mark.slow
def test_shard_death_failover_restart_bitwise_recovery():
    """ISSUE acceptance: kill one of two shards mid-training; training
    continues in degraded mode (keys re-homed + re-initialized from
    worker state); the shard restarts (fresh store, same port); the
    heartbeat sees it, state migrates back; final pulled parameters are
    bit-for-bit identical to the no-fault run.

    Slow-marked (PR 4 tier-1 budget): the full 30-step
    kill/degrade/restart/migrate cycle with heartbeat waits; the fast
    failover coverage stays in tier-1 via
    test_degraded_mode_routes_and_reinits_without_heartbeat,
    test_repeat_failover_overwrites_stale_fallback_copy,
    test_partition_recovery_overwrites_survivor_state and the wire
    pipeline's failover-seed fold tests."""
    dim, steps, kill_at, restart_at = 8, 30, 10, 20
    names = ("w", "b", "c0", "c1")

    target = _targets(dim, names)

    # --- reference run: two shards, no faults --------------------------
    s1, t1, a1 = _spawn_shard()
    s2, t2, a2 = _spawn_shard()
    ref_store = RemoteStore([a1, a2], retry_policy=_fast_policy())
    # sanity: the keyspace actually spans both shards (else the test
    # proves nothing about failover)
    assert {ref_store._shard_of(n) for n in names} == {0, 1}
    ref = _train(ref_store, steps, dim=dim, names=names)
    ref_store.close()
    s1.shutdown(); s1.server_close()
    s2.shutdown(); s2.server_close()

    # --- faulted run ---------------------------------------------------
    s1, t1, a1 = _spawn_shard()
    s2, t2, a2 = _spawn_shard()
    servers, addrs = [s1, s2], [a1, a2]
    counters = ResilienceCounters()
    store = RemoteStore(addrs, counters=counters,
                        retry_policy=_fast_policy(
                            max_attempts=2, backoff_base=0.01, deadline=5.0),
                        heartbeat=0.05)
    victim = store._shard_of("b")  # the shard serving "b" will die
    victim_port = int(addrs[victim].rsplit(":", 1)[1])

    state = {n: np.zeros(dim, np.float32) for n in names}
    for n in names:
        store.init_tensor(n, state[n])

    for step in range(steps):
        if step == kill_at:
            servers[victim].kill()  # crash: accept loop AND live conns die
        if step == restart_at:
            # fresh store on the SAME port (the launcher restart hook's
            # behavior): the client must re-init state on recovery
            servers[victim], _ = ps_server.serve(
                victim_port, host="127.0.0.1", use_native=False,
                in_thread=True)
            # wait for the heartbeat to notice and migrate back
            deadline = time.monotonic() + 10.0
            while store._router.is_down(victim) and \
                    time.monotonic() < deadline:
                time.sleep(0.02)
            assert not store._router.is_down(victim), \
                "heartbeat never saw the shard recover"
        for n in names:
            delta = 0.1 * (target[n] - state[n])
            state[n] = store.push_pull(n, delta.astype(np.float32))

    # degraded mode really happened and was repaired
    assert counters.get(cn.FAILOVER) >= 1
    assert counters.get(cn.REINIT) >= 1
    assert counters.get(cn.FAILBACK) >= 1

    # final pulled parameters: bit-for-bit vs the no-fault run
    for n in names:
        final = store.pull(n)
        np.testing.assert_array_equal(final, ref[n])
        assert final.tobytes() == ref[n].tobytes()

    store.close()
    for srv in servers:
        try:
            srv.shutdown(); srv.server_close()
        except Exception:
            pass


def test_degraded_mode_routes_and_reinits_without_heartbeat():
    """Failover driven purely by RPC failure (no heartbeat configured up
    front): the dead shard's key is re-homed to the surviving shard and
    re-initialized from the client's last seen global state."""
    s1, t1, a1 = _spawn_shard()
    s2, t2, a2 = _spawn_shard()
    counters = ResilienceCounters()
    store = RemoteStore([a1, a2], counters=counters,
                        retry_policy=_fast_policy(max_attempts=2,
                                                  deadline=5.0))
    try:
        names = ["w", "b", "c0", "c1"]
        for n in names:
            store.init_tensor(n, np.zeros(4, np.float32))
            store.push_pull(n, np.ones(4, np.float32))
        shards = {n: store._shard_of(n) for n in names}
        assert set(shards.values()) == {0, 1}
        victim = shards[names[0]]
        ((s1, s2)[victim]).kill()
        # ops on the dead shard's keys keep working, now on the fallback
        for n in names:
            out = store.push_pull(n, np.ones(4, np.float32))
            np.testing.assert_allclose(out, 2.0)  # state survived failover
        assert counters.get(cn.FAILOVER) >= 1
        assert counters.get(cn.REINIT) >= 1
        surviving = (s1, s2)[1 - victim]
        # the surviving server now hosts every name
        assert set(surviving.store.names()) == set(names)
        # client-side names(): down shard skipped, no duplicates
        assert sorted(store.names()) == sorted(names)
    finally:
        store.close()
        for srv in (s1, s2):
            try:
                srv.shutdown(); srv.server_close()
            except Exception:
                pass


def test_repeat_failover_overwrites_stale_fallback_copy():
    """A second failover episode must not be shadowed by the fallback's
    leftover copy from the first episode: the re-seed is a force-SET,
    not a first-push-wins INIT.  Updates made between failback and the
    second failure survive."""
    s1, t1, a1 = _spawn_shard()
    s2, t2, a2 = _spawn_shard()
    servers, addrs = [s1, s2], [a1, a2]
    store = RemoteStore(addrs, counters=ResilienceCounters(),
                        retry_policy=_fast_policy(max_attempts=2,
                                                  deadline=5.0),
                        heartbeat=0.05)
    victim = store._shard_of("b")
    victim_port = int(addrs[victim].rsplit(":", 1)[1])
    try:
        store.init_tensor("b", np.zeros(4, np.float32))

        # episode 1: kill, push +1 on the fallback (value 1 there)
        servers[victim].kill()
        np.testing.assert_allclose(
            store.push_pull("b", np.ones(4, np.float32)), 1.0)
        # restart -> failback seeds the fresh shard with 1
        servers[victim], _ = ps_server.serve(victim_port, host="127.0.0.1",
                                             use_native=False,
                                             in_thread=True)
        deadline = time.monotonic() + 10.0
        while store._router.is_down(victim) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not store._router.is_down(victim)
        # post-failback progress on the primary: 1 -> 3
        np.testing.assert_allclose(
            store.push_pull("b", np.full(4, 2.0, np.float32)), 3.0)

        # episode 2: kill again; the fallback still holds its stale 1 —
        # a first-push-wins seed would resume from 1 and lose the +2
        servers[victim].kill()
        out = store.push_pull("b", np.ones(4, np.float32))
        np.testing.assert_allclose(out, 4.0)  # 3 (re-seeded) + 1
    finally:
        store.close()
        for srv in servers:
            try:
                srv.shutdown(); srv.server_close()
            except Exception:
                pass


def test_single_shard_restart_reseeds_without_failover():
    """A 1-shard cluster (failover impossible) whose shard is restarted
    with a fresh store (launcher supervision) must keep training: the
    restarted shard's KeyError triggers a one-shot re-seed from the
    client's last-seen global state instead of killing the job."""
    srv, thread, addr = _spawn_shard()
    port = srv.server_address[1]
    counters = ResilienceCounters()
    store = RemoteStore([addr], counters=counters,
                        retry_policy=_fast_policy(max_attempts=3,
                                                  deadline=5.0))
    try:
        store.init_tensor("w", np.zeros(4, np.float32))
        np.testing.assert_allclose(
            store.push_pull("w", np.ones(4, np.float32)), 1.0)
        srv.kill()  # crash...
        srv, _ = ps_server.serve(port, host="127.0.0.1", use_native=False,
                                 in_thread=True)  # ...supervised restart
        # next op reconnects, hits the fresh store's KeyError, re-seeds
        # with the last-seen value (1.0) and applies the delta
        out = store.push_pull("w", np.full(4, 2.0, np.float32))
        np.testing.assert_allclose(out, 3.0)
        assert counters.get(cn.REINIT) >= 1
        # a genuinely never-declared name still errors loudly
        with pytest.raises(RuntimeError, match="ps_server error"):
            store.pull("never_declared")
    finally:
        store.close()
        try:
            srv.shutdown(); srv.server_close()
        except Exception:
            pass


def test_partition_recovery_overwrites_survivor_state():
    """A shard that was only unreachable (network partition — process
    alive, state intact) must not resume with its pre-partition values:
    failback force-SETs the fallback's newer state over the survivor's."""
    cfg = Config(heartbeat_timeout_ms=150.0)
    set_config(cfg)
    s1, t1, a1 = _spawn_shard()
    s2, t2, a2 = _spawn_shard()
    # front the would-be victim with a proxy so we can partition it
    # without killing it
    name = "b"
    proxies = [FaultInjectingProxy(a) for a in (a1, a2)]
    addrs = [p.addr for p in proxies]
    store = RemoteStore(addrs, counters=ResilienceCounters(),
                        retry_policy=_fast_policy(max_attempts=2,
                                                  backoff_base=0.01,
                                                  deadline=3.0),
                        timeout=0.5, heartbeat=0.05)
    victim = store._shard_of(name)
    victim_srv = (s1, s2)[victim]
    try:
        store.init_tensor(name, np.zeros(4, np.float32))
        np.testing.assert_allclose(
            store.push_pull(name, np.ones(4, np.float32)), 1.0)

        proxies[victim].blackhole(True)  # partition: alive but silent
        # degraded-mode progress on the fallback: 1 -> 4
        np.testing.assert_allclose(
            store.push_pull(name, np.full(4, 3.0, np.float32)), 4.0)
        assert store._router.is_down(victim)

        proxies[victim].blackhole(False)  # partition heals
        deadline = time.monotonic() + 10.0
        while store._router.is_down(victim) and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not store._router.is_down(victim)
        # the survivor held 1; failback must have overwritten it with 4
        np.testing.assert_allclose(victim_srv.store.pull(name), 4.0)
        np.testing.assert_allclose(store.pull(name), 4.0)
    finally:
        store.close()
        for p in proxies:
            p.close()
        for srv in (s1, s2):
            try:
                srv.shutdown(); srv.server_close()
            except Exception:
                pass


def test_cascading_failover_reseeds_on_new_fallback():
    """When the fallback shard dies too, a previously re-homed key moves
    to the NEXT alive shard and is re-seeded there (the ledger check
    compares the ledgered fallback against current routing, not just
    'already failed over')."""
    servers, addrs = [], []
    for _ in range(3):
        srv, th, a = _spawn_shard()
        servers.append(srv)
        addrs.append(a)
    store = RemoteStore(addrs, counters=ResilienceCounters(),
                        retry_policy=_fast_policy(max_attempts=2,
                                                  deadline=5.0))
    name = "t0"  # placed on shard 1 (see name_key formula)
    try:
        primary = store._shard_of(name)
        assert primary == 1
        store.init_tensor(name, np.zeros(4, np.float32))
        np.testing.assert_allclose(
            store.push_pull(name, np.ones(4, np.float32)), 1.0)

        servers[primary].kill()  # first failover -> shard 2
        np.testing.assert_allclose(
            store.push_pull(name, np.ones(4, np.float32)), 2.0)
        fb1 = store._router.fallback_for(name)
        assert fb1 is not None and fb1 != primary

        servers[fb1].kill()  # cascading: the fallback dies too
        out = store.push_pull(name, np.ones(4, np.float32))
        np.testing.assert_allclose(out, 3.0)  # re-seeded with 2 on fb2
        fb2 = store._router.fallback_for(name)
        assert fb2 not in (primary, fb1)
    finally:
        store.close()
        for srv in servers:
            try:
                srv.shutdown(); srv.server_close()
            except Exception:
                pass


# ----------------------------------------------------------- tracer surfacing


def test_resilience_counters_reach_tracer(tmp_path, monkeypatch):
    """ISSUE acceptance: with BYTEPS_TRACE_PATH set, resilience events
    (retries at minimum; failovers/heartbeat misses in the faulted
    flows) appear in the Tracer output."""
    import json

    from byteps_tpu.common import tracing

    trace = tmp_path / "trace.json"
    monkeypatch.setenv("BYTEPS_TRACE_PATH", str(trace))
    reset_config()
    tracing.reset_tracer()
    srv, thread, addr = _spawn_shard()
    proxy = FaultInjectingProxy(addr)
    try:
        store = RemoteStore([proxy.addr], retry_policy=_fast_policy())
        store.init_tensor("w", np.zeros(4, np.float32))
        proxy.script("drop_before")
        store.push_pull("w", np.ones(4, np.float32))   # retried
        proxy.script("drop_after")
        store.push_delta("w", np.ones(4, np.float32))  # deduped
        store.close()
        tracing.get_tracer().flush()
        events = json.loads(trace.read_text())["traceEvents"]
        names = {e["name"] for e in events}
        assert cn.RETRY in names
        assert cn.DEDUP in names
        assert cn.RECONNECT in names
        # both surfacing shapes: instant events + counter track
        phs = {e["ph"] for e in events if e["name"] == cn.RETRY}
        assert {"i", "C"} <= phs
    finally:
        proxy.close()
        srv.shutdown(); srv.server_close()
        tracing.reset_tracer()


def test_profiler_record_after_close_drops_loudly():
    """Satellite: ServerProfiler.record() after close() must not buffer
    events nothing will drain — it drops them (debug-logged) and leaves
    the closed JSON file untouched and valid."""
    import json

    import byteps_tpu.common.logging as bps_log
    from byteps_tpu.engine.ps_server import OP_PUSH, ServerProfiler

    path = None
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        path = f.name
    prof = ServerProfiler(path)
    prof.record(OP_PUSH, "w", "peer", 0.0, 1.0)
    prof.close()
    before = open(path).read()
    json.loads(before)  # valid strict JSON after close
    prof.record(OP_PUSH, "w", "peer", 2.0, 3.0)  # must be dropped
    assert open(path).read() == before
    assert prof._events == []  # nothing buffered forever
    prof.close()  # idempotent, no corruption
    json.loads(open(path).read())


# ---------------------------------------------------------------- satellites


def test_flash_bwd_blocks_distinguish_explicit_choice():
    """Satellite: explicit block_q/block_k — including an explicit
    1024x1024 equal to the old defaults — bind the backward kernels;
    only unset (None) picks the swept bwd defaults."""
    from byteps_tpu.ops.flash_attention import (DEFAULT_BWD_DKV_BLOCKS,
                                                DEFAULT_BWD_DQ_BLOCKS,
                                                _bwd_blocks)

    assert _bwd_blocks(None, None) == (DEFAULT_BWD_DQ_BLOCKS,
                                       DEFAULT_BWD_DKV_BLOCKS)
    assert _bwd_blocks(1024, 1024) == ((1024, 1024), (1024, 1024))
    assert _bwd_blocks(128, 256) == ((128, 256), (128, 256))
    # one side explicit: the other resolves to its fwd default
    assert _bwd_blocks(512, None) == ((512, 1024), (512, 1024))


def test_flash_attention_none_defaults_still_run():
    import jax
    import jax.numpy as jnp

    from byteps_tpu.ops.flash_attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 2, 8))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 2, 8))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 2, 8))
    out = flash_attention(q, k, v, True)
    ref = flash_attention(q, k, v, True, None, 1024, 1024)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_init_cache_flat_tp_refusal_narrowed():
    """Satellite: layout="flat" under an active tp axis that DIVIDES
    kv_heads now shards the head-major minor axis (whole KV-head
    slices); only a NON-dividing tp axis keeps the typed refusal, and
    its message names both honest ways out (grouped fallback, head
    padding) — tests/test_tp_serving.py pins the paged-pool twin."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from byteps_tpu.models.transformer import TransformerConfig, init_cache

    devs = np.array(jax.devices()[:2])
    mesh = Mesh(devs, ("tp",))
    cfg = TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                            d_model=32, d_ff=64, max_seq_len=32,
                            num_kv_heads=2, dtype=jnp.float32, mesh=mesh)
    # tp=2 divides kv_heads=2: flat works and tp-shards the minor axis
    caches = init_cache(cfg, 2, 16, layout="flat")
    assert caches[0]["k"].ndim == 3
    assert caches[0]["k"].sharding.spec[2] == "tp"
    # grouped + auto still fine under the mesh
    caches = init_cache(cfg, 2, 16, layout="grouped")
    assert caches[0]["k"].ndim == 4
    init_cache(cfg, 2, 16, layout="auto")
    # tp=2 does NOT divide kv_heads=1 (MQA): typed refusal naming the
    # grouped-layout fallback and the padding option
    cfg1 = TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                             d_model=32, d_ff=64, max_seq_len=32,
                             num_kv_heads=1, dtype=jnp.float32, mesh=mesh)
    with pytest.raises(ValueError, match="divide kv_heads") as ei:
        init_cache(cfg1, 2, 16, layout="flat")
    assert 'layout="grouped"' in str(ei.value)
    assert "pad kv_heads" in str(ei.value)
    # and flat stays available without a mesh
    cfg2 = TransformerConfig(vocab_size=32, num_layers=1, num_heads=4,
                             d_model=32, d_ff=64, max_seq_len=32,
                             num_kv_heads=2, dtype=jnp.float32)
    caches = init_cache(cfg2, 2, 16, layout="flat")
    assert caches[0]["k"].ndim == 3
