"""Tracing subsystem tests (reference docs/timeline.md behavior)."""

import json
import os

import jax.numpy as jnp
import numpy as np

import byteps_tpu as bps
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common import tracing


def test_tracer_records_spans(tmp_path):
    t = tracing.Tracer(path=str(tmp_path / "trace.json"))
    with t.span("Gradient_w", "push_pull", key=7, bytes=128):
        pass
    t.instant("start", "engine")
    path = t.flush()
    data = json.load(open(path))
    evs = data["traceEvents"]
    assert len(evs) == 2
    span = [e for e in evs if e["ph"] == "X"][0]
    assert span["name"] == "Gradient_w"
    assert span["args"]["key"] == 7
    assert span["dur"] >= 0


def test_tracer_key_filter():
    t = tracing.Tracer(path="unused.json", key_filter="Gradient")
    with t.span("Parameter_b", "push_pull"):
        pass
    with t.span("Gradient_w", "push_pull"):
        pass
    assert [e["name"] for e in t.events()] == ["Gradient_w"]


def test_disabled_tracer_is_noop():
    t = tracing.Tracer(path="")
    with t.span("x", "s"):
        pass
    assert t.events() == []
    assert t.flush() is None


def test_engine_emits_trace(tmp_path):
    trace_file = str(tmp_path / "bps_trace.json")
    cfg = Config.from_env()
    cfg.trace_path = trace_file
    set_config(cfg)
    tracing.reset_tracer()

    bps.init()
    n = bps.size()
    x = jnp.ones((n, 4), jnp.float32)
    out = bps.push_pull(x, average=False, name="traced_tensor")
    np.testing.assert_allclose(np.asarray(out), n)
    bps.shutdown()  # flushes

    data = json.load(open(trace_file))
    names = {e["name"] for e in data["traceEvents"]}
    assert any("traced_tensor" in s for s in names)
    stages = {e["tid"] for e in data["traceEvents"]}
    assert {"dispatch", "push_pull"} <= stages
