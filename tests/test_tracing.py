"""Tracing subsystem tests (reference docs/timeline.md behavior)."""

import json
import os

import jax.numpy as jnp
import numpy as np

import byteps_tpu as bps
from byteps_tpu.common.config import Config, set_config
from byteps_tpu.common import tracing


def test_tracer_records_spans(tmp_path):
    t = tracing.Tracer(path=str(tmp_path / "trace.json"))
    with t.span("Gradient_w", "push_pull", key=7, bytes=128):
        pass
    t.instant("start", "engine")
    path = t.flush()
    data = json.load(open(path))
    evs = data["traceEvents"]
    assert len(evs) == 2
    span = [e for e in evs if e["ph"] == "X"][0]
    assert span["name"] == "Gradient_w"
    assert span["args"]["key"] == 7
    assert span["dur"] >= 0


def test_tracer_key_filter():
    t = tracing.Tracer(path="unused.json", key_filter="Gradient")
    with t.span("Parameter_b", "push_pull"):
        pass
    with t.span("Gradient_w", "push_pull"):
        pass
    assert [e["name"] for e in t.events()] == ["Gradient_w"]


def test_disabled_tracer_is_noop():
    t = tracing.Tracer(path="")
    with t.span("x", "s"):
        pass
    assert t.events() == []
    assert t.flush() is None


def test_engine_emits_trace(tmp_path):
    trace_file = str(tmp_path / "bps_trace.json")
    cfg = Config.from_env()
    cfg.trace_path = trace_file
    set_config(cfg)
    tracing.reset_tracer()

    bps.init()
    n = bps.size()
    x = jnp.ones((n, 4), jnp.float32)
    out = bps.push_pull(x, average=False, name="traced_tensor")
    np.testing.assert_allclose(np.asarray(out), n)
    bps.shutdown()  # flushes

    data = json.load(open(trace_file))
    names = {e["name"] for e in data["traceEvents"]}
    assert any("traced_tensor" in s for s in names)
    stages = {e["tid"] for e in data["traceEvents"]}
    assert {"dispatch", "push_pull"} <= stages


def test_debug_sample_tensor_logs(monkeypatch):
    """BYTEPS_DEBUG_SAMPLE_TENSOR=<name> prints the tensor's first/last
    values after the stage completes (reference core_loops.cc:33-63)."""
    import logging

    import numpy as np

    import byteps_tpu as bps

    bps.shutdown()  # drop engine + config so the env var is re-read
    monkeypatch.setenv("BYTEPS_DEBUG_SAMPLE_TENSOR", "dbg_probe")
    bps.init()
    # the byteps_tpu logger doesn't propagate and caches its level from
    # the first init; attach a handler + raise the level directly
    logger = logging.getLogger("byteps_tpu")
    messages = []

    class _Capture(logging.Handler):
        def emit(self, record):
            messages.append(record.getMessage())

    handler = _Capture(level=logging.INFO)
    old_level = logger.level
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        n = bps.size()
        x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        out = bps.push_pull(x, average=False, name="dbg_probe_w")
        np.asarray(out)
        assert any("sample dbg_probe_w" in m for m in messages), messages
        # non-matching names stay silent
        messages.clear()
        bps.push_pull(x, average=False, name="other_tensor")
        assert not any("sample other" in m for m in messages)
    finally:
        logger.removeHandler(handler)
        logger.setLevel(old_level)
        # undo the env BEFORE re-init, or the sampling config leaks into
        # the restored engine for the rest of the session
        monkeypatch.delenv("BYTEPS_DEBUG_SAMPLE_TENSOR", raising=False)
        bps.shutdown()
        bps.init()  # restore a clean engine for subsequent tests
