"""Row-sparse push_pull tests — the capability the reference reserves as
``kRowSparsePushPull`` (common.h:212-216) and never implements.

Contracts: dense-equivalence (sparse result == dense scatter + psum),
duplicate-index accumulation, average mode, out-of-range row dropping,
wire-dtype casting, and the embedding-gradient training use case.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import byteps_tpu as bps
from byteps_tpu.parallel.collectives import shard_map, sparse_push_pull

N_ROWS, DIM = 16, 8


@pytest.fixture
def mesh():
    return Mesh(np.array(jax.devices()), ("dp",))


def _random_contribs(n_workers, k, seed=0, n_rows=N_ROWS):
    rng = np.random.RandomState(seed)
    idx = rng.randint(0, n_rows, size=(n_workers, k)).astype(np.int32)
    val = rng.randn(n_workers, k, DIM).astype(np.float32)
    return idx, val


def _dense_reference(idx, val, average=False, n_rows=N_ROWS):
    dense = np.zeros((n_rows, DIM), np.float32)
    for w in range(idx.shape[0]):
        for j in range(idx.shape[1]):
            if 0 <= idx[w, j] < n_rows:
                dense[idx[w, j]] += val[w, j]
    return dense / idx.shape[0] if average else dense


def test_sparse_matches_dense_reference(mesh):
    n = len(jax.devices())
    idx, val = _random_contribs(n, k=5)

    fn = jax.jit(shard_map(
        lambda i, v: sparse_push_pull(i[0], v[0], N_ROWS, axes=("dp",)),
        mesh, in_specs=(P("dp"), P("dp")), out_specs=P(),
    ))
    out = fn(jnp.asarray(idx), jnp.asarray(val))
    np.testing.assert_allclose(np.asarray(out), _dense_reference(idx, val),
                               rtol=1e-6, atol=1e-6)


def test_sparse_average_and_duplicates(mesh):
    n = len(jax.devices())
    # every worker hits row 3 twice: duplicates must accumulate
    idx = np.full((n, 2), 3, np.int32)
    val = np.ones((n, 2, DIM), np.float32)

    fn = jax.jit(shard_map(
        lambda i, v: sparse_push_pull(i[0], v[0], N_ROWS, axes=("dp",),
                                      average=True),
        mesh, in_specs=(P("dp"), P("dp")), out_specs=P(),
    ))
    out = np.asarray(fn(jnp.asarray(idx), jnp.asarray(val)))
    np.testing.assert_allclose(out[3], 2.0)  # 2 dups * n workers / n
    assert np.all(out[:3] == 0) and np.all(out[4:] == 0)


def test_sparse_wire_dtype_bf16(mesh):
    n = len(jax.devices())
    idx, val = _random_contribs(n, k=4, seed=1)
    fn = jax.jit(shard_map(
        lambda i, v: sparse_push_pull(i[0], v[0], N_ROWS, axes=("dp",),
                                      wire_dtype=jnp.bfloat16),
        mesh, in_specs=(P("dp"), P("dp")), out_specs=P(),
    ))
    out = np.asarray(fn(jnp.asarray(idx), jnp.asarray(val)))
    assert out.dtype == np.float32  # restored after the wire
    np.testing.assert_allclose(out, _dense_reference(idx, val),
                               rtol=0.05, atol=0.05)


def test_eager_api_stacked_and_single(mesh):
    bps.init()
    n = bps.size()
    idx, val = _random_contribs(n, k=3, seed=2)
    out = bps.push_pull_sparse(idx, val, N_ROWS)
    np.testing.assert_allclose(np.asarray(out), _dense_reference(idx, val),
                               rtol=1e-6, atol=1e-6)
    # average
    out = bps.push_pull_sparse(idx, val, N_ROWS, average=True)
    np.testing.assert_allclose(
        np.asarray(out), _dense_reference(idx, val, average=True),
        rtol=1e-6, atol=1e-6)
    # shape validation
    with pytest.raises(ValueError):
        bps.push_pull_sparse(idx[0], val, N_ROWS)


def test_embedding_gradient_training(mesh):
    """The use case: data-parallel embedding training where each worker
    touches few rows.  Sparse allreduce of the embedding grads must give
    the same trajectory as dense."""
    n = len(jax.devices())
    table = jnp.asarray(np.random.RandomState(3).randn(N_ROWS, DIM)
                        .astype(np.float32))
    tokens = np.random.RandomState(4).randint(
        0, N_ROWS, size=(n, 4)).astype(np.int32)
    targets = np.random.RandomState(5).randn(n, 4, DIM).astype(np.float32)

    def local_grad(table, tok, tgt):
        def loss(tb):
            return jnp.mean((tb[tok] - tgt) ** 2)

        return jax.grad(loss)(table)

    def sparse_step(table, tok, tgt):
        tok, tgt = tok[0], tgt[0]
        # local grads only touch `tok` rows; ship just those
        g_rows = jax.grad(
            lambda rows: jnp.mean((rows - tgt) ** 2))(table[tok])
        g = sparse_push_pull(tok, g_rows, N_ROWS, axes=("dp",),
                             average=True)
        return table - 0.1 * g

    def dense_step(table, tok, tgt):
        g = local_grad(table, tok[0], tgt[0])
        return table - 0.1 * jax.lax.pmean(g, "dp")

    sp = jax.jit(shard_map(sparse_step, mesh,
                           in_specs=(P(), P("dp"), P("dp")), out_specs=P()))
    de = jax.jit(shard_map(dense_step, mesh,
                           in_specs=(P(), P("dp"), P("dp")), out_specs=P()))
    t_sparse = sp(table, jnp.asarray(tokens), jnp.asarray(targets))
    t_dense = de(table, jnp.asarray(tokens), jnp.asarray(targets))
    np.testing.assert_allclose(np.asarray(t_sparse), np.asarray(t_dense),
                               rtol=1e-5, atol=1e-6)
