"""Top-k error-feedback sparsified push_pull tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.ops.sparsification import (
    topk_ef_push_pull_gradients,
    topk_select,
)
from byteps_tpu.parallel.collectives import shard_map


def test_topk_select_basic():
    x = jnp.array([0.1, -5.0, 0.2, 3.0, -0.05])
    idx, vals, residual = topk_select(x, 2)
    assert set(np.asarray(idx).tolist()) == {1, 3}
    got = {int(i): float(v) for i, v in zip(np.asarray(idx), np.asarray(vals))}
    assert got[1] == pytest.approx(-5.0)
    assert got[3] == pytest.approx(3.0)
    # residual keeps exactly the unsent mass
    np.testing.assert_allclose(
        np.asarray(residual), [0.1, 0.0, 0.2, 0.0, -0.05], atol=1e-7)


def _run_tx_on_mesh(tx, grads_per_worker, n_workers=4):
    """Run one tx.update inside shard_map with per-worker gradients."""
    mesh = Mesh(np.array(jax.devices()[:n_workers]), ("dp",))
    stacked = jnp.stack(grads_per_worker)

    def local(g_stack):
        g = g_stack[0]
        state = tx.init(g)
        upd, _ = tx.update(g, state)
        return upd[None]

    fn = jax.jit(shard_map(
        local, mesh, in_specs=(P("dp"),), out_specs=P("dp")))
    return np.asarray(fn(stacked))


def test_topk_cross_worker_union_sum():
    """Workers with disjoint top-k coordinates: every worker receives the
    dense mean over the union."""
    n = 16
    g0 = np.zeros(n, np.float32)
    g1 = np.zeros(n, np.float32)
    g0[2], g0[7] = 4.0, -8.0
    g1[11], g1[13] = 2.0, 6.0
    tx = topk_ef_push_pull_gradients(ratio=2 / n, axis_name="dp",
                                     average=True)
    out = _run_tx_on_mesh(tx, [jnp.array(g0), jnp.array(g1)], n_workers=2)
    expected = (g0 + g1) / 2.0
    np.testing.assert_allclose(out[0], expected, atol=1e-6)
    np.testing.assert_allclose(out[1], expected, atol=1e-6)


def test_topk_ratio_one_matches_dense_allreduce():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    g0 = jax.random.normal(k1, (32,))
    g1 = jax.random.normal(k2, (32,))
    tx = topk_ef_push_pull_gradients(ratio=1.0, axis_name="dp", average=True)
    out = _run_tx_on_mesh(tx, [g0, g1], n_workers=2)
    expected = np.asarray((g0 + g1) / 2.0)
    np.testing.assert_allclose(out[0], expected, rtol=1e-6)


def test_topk_single_worker_sparsifies_without_comm():
    g = jnp.array([1.0, -3.0, 0.5, 2.5])
    tx = topk_ef_push_pull_gradients(ratio=0.5, axis_name=None)
    state = tx.init(g)
    upd, state = tx.update(g, state)
    np.testing.assert_allclose(
        np.asarray(upd), [0.0, -3.0, 0.0, 2.5], atol=1e-7)
    # error carries the unsent coordinates
    np.testing.assert_allclose(
        np.asarray(state.error), [1.0, 0.0, 0.5, 0.0], atol=1e-7)
    # residual accumulates until a previously-unsent coordinate outgrows
    # a sent one and finally ships (EF catch-up): corrected[0] grows by
    # 1.0/step, passing |corrected[3]|=2.5 on step 3
    upd2, state = tx.update(g, state)
    assert float(upd2[0]) == 0.0
    upd3, state = tx.update(g, state)
    np.testing.assert_allclose(
        np.asarray(upd3), [3.0, -3.0, 0.0, 0.0], atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(state.error), [0.0, 0.0, 1.5, 2.5], atol=1e-6)


def test_topk_error_feedback_total_mass_conserved():
    """Over many steps the sum of applied updates approaches the sum of
    true gradients (EF conservation) even at high sparsity."""
    n = 64
    g = jax.random.normal(jax.random.PRNGKey(1), (n,)) * 0.1
    tx = topk_ef_push_pull_gradients(ratio=4 / n, axis_name=None)
    state = tx.init(g)
    applied = jnp.zeros_like(g)
    steps = 60
    for _ in range(steps):
        upd, state = tx.update(g, state)
        applied = applied + upd
    # applied == steps*g - residual; residual is bounded, so the relative
    # gap shrinks with steps
    gap = np.abs(np.asarray(applied - steps * g))
    assert gap.max() <= float(np.abs(np.asarray(g)).max()) * 12


def test_topk_training_converges():
    """Linear regression under 12.5%-sparse top-k EF still converges, on a
    2-worker mesh with different data shards."""
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    dim = 16
    key = jax.random.PRNGKey(2)
    xk, yk = jax.random.split(key)
    X = jax.random.normal(xk, (32, dim))
    w_true = jax.random.normal(yk, (dim,))
    Y = X @ w_true

    tx = optax.chain(
        topk_ef_push_pull_gradients(ratio=2 / dim, axis_name="dp"),
        optax.sgd(0.05),
    )

    def local_step(w, opt_state, xb, yb):
        def loss_of(w):
            return jnp.mean((xb[0] @ w - yb[0]) ** 2)

        loss, grads = jax.value_and_grad(loss_of)(w)
        updates, opt_state = tx.update(grads, opt_state, w)
        return optax.apply_updates(w, updates), opt_state, \
            jax.lax.pmean(loss, "dp")

    fn = jax.jit(shard_map(
        local_step, mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P()),
    ))
    w = jnp.zeros((dim,))
    opt_state = tx.init(w)
    Xs = X.reshape(2, 1, 16, dim)
    Ys = Y.reshape(2, 1, 16)
    first = None
    for i in range(300):
        w, opt_state, loss = fn(w, opt_state, Xs[:, 0], Ys[:, 0])
        if first is None:
            first = float(loss)
    assert float(loss) < first * 1e-2, (first, float(loss))


def test_topk_tuple_structured_pytree():
    """Gradient pytrees that ARE tuples (or contain them) must round-trip
    intact — regression for the is_leaf=tuple pair-splitting bug."""
    g = (jnp.array([1.0, -3.0]), {"w": jnp.array([0.5, 2.5, -4.0])})
    tx = topk_ef_push_pull_gradients(ratio=0.5, axis_name=None)
    state = tx.init(g)
    upd, state = tx.update(g, state)
    assert isinstance(upd, tuple) and len(upd) == 2
    assert upd[0].shape == (2,) and upd[1]["w"].shape == (3,)
    np.testing.assert_allclose(np.asarray(upd[0]), [0.0, -3.0], atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(upd[1]["w"]), [0.0, 0.0, -4.0], atol=1e-7)


def test_int8_ef_tuple_structured_pytree():
    """Same regression for the int8-EF transformation."""
    from byteps_tpu.ops.quantization import error_feedback_quantize_gradients

    g = (jnp.array([1.0, -3.0]), jnp.array([[0.5, 2.5]]))
    tx = error_feedback_quantize_gradients()
    state = tx.init(g)
    upd, state = tx.update(g, state)
    assert isinstance(upd, tuple) and len(upd) == 2
    assert upd[0].shape == (2,) and upd[1].shape == (1, 2)
    np.testing.assert_allclose(np.asarray(upd[0]), [1.0, -3.0], atol=0.05)
