"""LLaMA architecture compatibility (integrations/llama.py).

Ground truth is HF's torch ``LlamaForCausalLM`` itself, randomly
initialized (no network access needed): converted weights must reproduce
its logits, and the whole inference stack — RoPE cached decode, GQA
grouping, beam, speculative, int8 — must run on the converted model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from byteps_tpu.inference import (  # noqa: E402
    beam_search,
    generate,
    quantize_params,
)
from byteps_tpu.integrations.llama import (  # noqa: E402
    llama_config,
    load_llama,
)

VOCAB = 97


def _hf_model(layers=2, heads=4, kv_heads=2, d=64, d_ff=128, seed=0,
              **kw):
    torch.manual_seed(seed)
    cfg = transformers.LlamaConfig(
        hidden_size=d, intermediate_size=d_ff, num_hidden_layers=layers,
        num_attention_heads=heads, num_key_value_heads=kv_heads,
        vocab_size=VOCAB, max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, tie_word_embeddings=False,
        attention_dropout=0.0, **kw)
    return transformers.LlamaForCausalLM(cfg).eval()


def test_logits_match_torch():
    hf = _hf_model()
    model, variables = load_llama(hf)
    assert model.cfg.pos_emb == "rope"
    assert model.cfg.mlp == "swiglu"
    assert model.cfg.kv_heads == 2
    tokens = np.random.RandomState(0).randint(0, VOCAB, size=(2, 12))
    with torch.no_grad():
        want = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_mha_llama_matches_torch():
    """num_key_value_heads == num_attention_heads (original LLaMA-1/2-7B
    layout) converts and matches too."""
    hf = _hf_model(kv_heads=4, seed=3)
    model, variables = load_llama(hf)
    tokens = np.random.RandomState(1).randint(0, VOCAB, size=(1, 9))
    with torch.no_grad():
        want = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_cached_decode_matches_hf_greedy():
    """Greedy generation through the RoPE/GQA KV-cache decode equals
    HF's own greedy continuation."""
    hf = _hf_model(seed=1)
    model, variables = load_llama(hf)
    prompt = np.random.RandomState(2).randint(0, VOCAB, size=(2, 8))
    N = 8
    with torch.no_grad():
        hf_out = hf.generate(
            torch.tensor(prompt), max_new_tokens=N, do_sample=False,
            num_beams=1, pad_token_id=0)
    want = hf_out.numpy()[:, 8:]
    out = generate(model, variables, jnp.asarray(prompt), N,
                   temperature=0)
    np.testing.assert_array_equal(np.asarray(out["tokens"]), want)


@pytest.mark.slow  # ~11s (tier-1 duration budget); logits_match_torch + cached_decode_matches_hf_greedy keep fast llama parity
def test_inference_stack_runs_on_llama():
    """Beam search, speculative (truncated self-draft), and int8
    weight-only quantization all run on converted LLaMA weights."""
    from byteps_tpu.inference import speculative_generate, truncated_draft

    hf = _hf_model(seed=2)
    model, variables = load_llama(hf)
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, VOCAB, size=(2, 6)))
    want = generate(model, variables, prompt, 6, temperature=0)["tokens"]

    bm = beam_search(model, variables, prompt, 6, num_beams=3)
    assert bm["tokens"].shape == (2, 6)

    dmodel, dvars = truncated_draft(model.cfg, variables, 1)
    sp = speculative_generate(model, variables, dmodel, dvars, prompt, 6,
                              gamma=3)
    np.testing.assert_array_equal(np.asarray(sp["tokens"]),
                                  np.asarray(want))

    qvars = {"params": quantize_params(variables["params"])}
    qout = generate(model, qvars, prompt, 6, temperature=0)
    assert qout["tokens"].shape == (2, 6)


def test_unsupported_axes_raise():
    hf = _hf_model()
    with pytest.raises(ValueError, match="hidden_act"):
        llama_config(type("C", (), dict(
            vars(hf.config), hidden_act="gelu"))())
    bad = _hf_model()
    bad.config.rope_scaling = {"rope_type": "yarn", "factor": 2.0}
    with pytest.raises(ValueError, match="rope_scaling"):
        llama_config(bad.config)


# ---------------------------------------------------------------------------
# Llama-3.x axes: rope_scaling (llama3 / linear) + explicit head_dim
# ---------------------------------------------------------------------------


def test_llama3_rope_scaling_and_head_dim_match_torch():
    """The Llama-3 frequency-rescale schedule and an explicit
    head_dim != hidden_size/num_heads must reproduce HF logits — these
    are the axes every 2024+ LLaMA checkpoint sets (r4 verdict #4)."""
    hf = _hf_model(
        seed=7, head_dim=24,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16})
    model, variables = load_llama(hf)
    assert model.cfg.head_dim == 24
    assert dict(model.cfg.rope_scaling)["rope_type"] == "llama3"
    tokens = np.random.RandomState(2).randint(0, VOCAB, size=(2, 20))
    with torch.no_grad():
        want = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=3e-5)


@pytest.mark.slow  # ~18s: stepwise HF forward per token (tier-1 duration budget); cached_decode_matches_hf_greedy keeps fast parity
def test_llama3_cached_decode_matches_hf_forward_stepwise():
    """Cached decode under llama3 scaling + explicit head_dim must
    reproduce HF's forward logits at every step (teacher-forced).  NOT
    compared against ``hf.generate`` token chains: HF's own cached
    generate flips near-tie argmaxes vs its forward (measured: a 0.04
    logit gap flipped at step 1 on this random model), and chain
    equality amplifies one flip into total divergence."""
    hf = _hf_model(
        seed=11, head_dim=24,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16})
    model, variables = load_llama(hf)
    from byteps_tpu.models.transformer import (
        Transformer as _T,
        init_cache,
    )

    rs = np.random.RandomState(3)
    prompt = rs.randint(0, VOCAB, size=(2, 8))
    cont = rs.randint(0, VOCAB, size=(2, 6))
    full = np.concatenate([prompt, cont], axis=1)
    with torch.no_grad():
        want = hf(torch.tensor(full)).logits.numpy()
    caches = init_cache(model.cfg, 2, 16)
    lg, caches = model.apply(variables, jnp.asarray(prompt), caches, 0,
                             method=_T.decode)
    got = [np.asarray(lg)]
    for t in range(cont.shape[1]):
        lg, caches = model.apply(
            variables, jnp.asarray(full[:, 8 + t:9 + t]), caches, 8 + t,
            method=_T.decode)
        got.append(np.asarray(lg))
    got = np.concatenate(got, axis=1)
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=3e-5)

    # self-consistency: our generate is exactly our forward's argmax
    # chain (greedy), llama3 scaling active in both paths
    N = 6
    toks = np.asarray(generate(model, variables, jnp.asarray(prompt), N,
                               temperature=0)["tokens"])
    seq = prompt.copy()
    for i in range(N):
        nxt = np.asarray(
            model.apply(variables, jnp.asarray(seq)))[:, -1].argmax(-1)
        np.testing.assert_array_equal(toks[:, i], nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)


def test_linear_rope_scaling_matches_torch():
    hf = _hf_model(
        seed=13,
        rope_scaling={"rope_type": "linear", "factor": 4.0})
    model, variables = load_llama(hf)
    tokens = np.random.RandomState(4).randint(0, VOCAB, size=(1, 16))
    with torch.no_grad():
        want = hf(torch.tensor(tokens)).logits.numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(tokens)))
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=3e-5)


def test_redundant_head_dim_is_derived():
    hf = _hf_model(seed=17, head_dim=16)  # == hidden/heads: redundant
    model, variables = load_llama(hf)
    assert model.cfg.head_dim is None
