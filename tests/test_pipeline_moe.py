"""Pipeline (pp) and expert (ep) parallelism tests on the CPU mesh.

Contracts: a 4-stage GPipe pipeline must equal sequential application of
the 4 stages (forward AND gradients); expert-parallel MoE over 4 ranks must
equal the single-rank routed MoE on the same tokens/experts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.parallel.collectives import shard_map
from byteps_tpu.parallel.moe import load_balancing_loss, moe_ffn, top1_routing
from byteps_tpu.parallel.pipeline import pipeline_apply, pipeline_loss


# ---------------------------------------------------------------- pipeline

N_STAGES, N_MICRO, MB, D = 4, 8, 2, 16


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(key):
    ks = jax.random.split(key, N_STAGES)
    return {
        "w": jnp.stack(
            [jax.random.normal(k, (D, D)) * 0.5 for k in ks]
        ),
        "b": jnp.stack([jnp.full((D,), 0.01 * i) for i in range(N_STAGES)]),
    }


def _sequential(params, micro):
    x = micro
    for s in range(N_STAGES):
        x = stage_fn({"w": params["w"][s], "b": params["b"][s]}, x)
    return x


def _pp_mesh():
    return Mesh(np.array(jax.devices()[:N_STAGES]), ("pp",))


def test_pipeline_forward_matches_sequential():
    params = _stacked_params(jax.random.PRNGKey(0))
    micros = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, D))
    expected = jax.vmap(lambda m: _sequential(params, m))(micros)

    mesh = _pp_mesh()

    def run(p, m):
        local = jax.tree_util.tree_map(lambda a: a[0], p)  # my stage
        return pipeline_apply(stage_fn, local, m, axis_name="pp")

    fn = jax.jit(shard_map(
        run, mesh, in_specs=(P("pp"), P()), out_specs=P("pp"),
    ))
    # out_specs P("pp") concatenates per-stage outputs along axis 0
    out = fn(params, micros).reshape(N_STAGES, N_MICRO, MB, D)
    np.testing.assert_allclose(
        np.asarray(out[-1]), np.asarray(expected), atol=1e-5
    )


@pytest.mark.slow  # ~19s: pipeline bwd compile (tier-1 duration budget); forward/remat/ep parity stay fast
def test_pipeline_grads_match_sequential():
    params = _stacked_params(jax.random.PRNGKey(2))
    micros = jax.random.normal(jax.random.PRNGKey(3), (N_MICRO, MB, D))
    targets = jax.random.normal(jax.random.PRNGKey(4), (N_MICRO, MB, D))

    def seq_loss(p):
        outs = jax.vmap(lambda m: _sequential(p, m))(micros)
        return jnp.mean(jax.vmap(
            lambda o, t: jnp.mean((o - t) ** 2))(outs, targets))

    g_seq = jax.grad(seq_loss)(params)

    mesh = _pp_mesh()

    def pp_loss(p, m, t):
        local = jax.tree_util.tree_map(lambda a: a[0], p)
        loss = pipeline_loss(
            stage_fn,
            lambda o, tt: jnp.mean((o - tt) ** 2),
            local, m, t, axis_name="pp",
        )
        return loss

    def outer(p):
        fn = shard_map(
            pp_loss, mesh, in_specs=(P("pp"), P(), P()), out_specs=P(),
        )
        return fn(p, micros, targets)

    loss_pp = jax.jit(outer)(params)
    np.testing.assert_allclose(float(loss_pp), float(seq_loss(params)),
                               atol=1e-5)
    g_pp = jax.grad(outer)(params)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(g_pp[k]), np.asarray(g_seq[k]), atol=1e-4, rtol=1e-4
        )


def test_pipeline_remat_matches():
    params = _stacked_params(jax.random.PRNGKey(5))
    micros = jax.random.normal(jax.random.PRNGKey(6), (N_MICRO, MB, D))
    mesh = _pp_mesh()

    def run(p, m, remat):
        local = jax.tree_util.tree_map(lambda a: a[0], p)
        return pipeline_apply(stage_fn, local, m, axis_name="pp", remat=remat)

    f1 = jax.jit(shard_map(lambda p, m: run(p, m, False), mesh,
                           in_specs=(P("pp"), P()), out_specs=P("pp")))
    f2 = jax.jit(shard_map(lambda p, m: run(p, m, True), mesh,
                           in_specs=(P("pp"), P()), out_specs=P("pp")))
    np.testing.assert_allclose(np.asarray(f1(params, micros)),
                               np.asarray(f2(params, micros)), atol=1e-5)


# --------------------------------------------------------------------- moe

T, DM, F, E = 32, 8, 16, 8  # tokens, d_model, d_ff, experts
N_RANKS = 4


def _moe_weights(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (DM, E)) * 0.5,          # gate
        jax.random.normal(k2, (E, DM, F)) * 0.2,       # up
        jax.random.normal(k3, (E, F, DM)) * 0.2,       # down
    )


def test_top1_routing_capacity():
    logits = jnp.array([[10.0, 0.0]] * 5)  # all 5 tokens -> expert 0
    dispatch, combine = top1_routing(logits, capacity=3)
    # only 3 fit
    assert float(dispatch[:, 0].sum()) == 3.0
    assert float(dispatch[3:, 0].sum()) == 0.0  # overflow dropped in order
    # combine weighted by gate prob
    assert np.all(np.asarray(combine) <= np.asarray(dispatch))


def test_moe_ep_matches_single_rank():
    """4-way expert-parallel == all-experts-local, same capacity."""
    gate, up, down = _moe_weights(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (T, DM))

    # single-rank reference: capacity must match the ep run, where each
    # rank routes T tokens into E experts with factor cf
    cf = 2.0
    ref = moe_ffn(x, gate, up, down, axis_name=None, capacity_factor=cf)

    mesh = Mesh(np.array(jax.devices()[:N_RANKS]), ("ep",))
    E_local = E // N_RANKS

    def run(x_all, gate, up, down):
        # every rank gets the SAME tokens (replicated) and its expert slice
        return moe_ffn(x_all, gate, up[0], down[0],
                       axis_name="ep", capacity_factor=cf)

    fn = jax.jit(shard_map(
        run, mesh,
        in_specs=(P(), P(), P("ep"), P("ep")),
        out_specs=P(),  # identical tokens => identical outputs
    ))
    out = fn(x, gate, up.reshape(N_RANKS, E_local, DM, F),
             down.reshape(N_RANKS, E_local, F, DM))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_moe_overflow_tokens_get_zero():
    gate, up, down = _moe_weights(jax.random.PRNGKey(2))
    # tiny capacity: force drops
    x = jax.random.normal(jax.random.PRNGKey(3), (T, DM))
    out = moe_ffn(x, gate, up, down, axis_name=None, capacity_factor=0.1)
    # some rows must be exactly zero (dropped), others not
    norms = np.linalg.norm(np.asarray(out), axis=-1)
    assert (norms == 0).any() and (norms > 0).any()


def test_load_balancing_loss_uniform_is_one():
    # perfectly uniform router -> loss == 1.0 (E * E * (1/E) * (1/E))
    logits = jnp.zeros((64, E))
    lb = load_balancing_loss(logits)
    # argmax breaks ties to expert 0, so frac is degenerate; use random
    logits = jax.random.normal(jax.random.PRNGKey(0), (4096, E)) * 0.01
    lb = load_balancing_loss(logits)
    assert 0.9 < float(lb) < 1.3
