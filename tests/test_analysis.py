"""Concurrency-analysis subsystem (byteps_tpu/analysis/ — docs/analysis.md).

Three layers of coverage:

1. **Synthetic fixtures per static rule** — a deliberate violation the
   rule must catch, and a clean twin it must not flag (the lints guard
   the tree, these guard the lints).
2. **Runtime lock-order detector** — a deliberate 2-thread A->B / B->A
   schedule the detector must report as a typed
   ``LockOrderViolation`` carrying both acquisition stacks, plus
   clean/reentrant/condition legs that must stay silent.
3. **The tree itself** — ``scripts/lint.py`` must exit 0 (no
   unbaselined violations, every baseline entry reviewed), and the
   violations fixed in this PR must stay fixed (regression pins on
   ``serving/router.py`` and the env-knob reads).

The env-knob docs check here supersedes the PR 6 one-off
``test_every_config_knob_is_documented_in_env_md`` that lived in
tests/test_observability.py.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from byteps_tpu.analysis import (envknobs, locks, metricnames,
                                 partitionspecs, protocols)
from byteps_tpu.analysis import runtime as lockrt
from byteps_tpu.analysis.runner import BASELINE_FILE, repo_root, run_all
from byteps_tpu.analysis.violations import (Baseline, Violation,
                                            apply_baseline)

REPO = repo_root()


def _rules(violations):
    return sorted(v.rule for v in violations)


def _details(violations, rule):
    return sorted(v.detail for v in violations if v.rule == rule)


# ======================================================================
# 1. static rule fixtures
# ======================================================================


class TestLockRules:
    def test_unguarded_field_read_and_write_flagged(self):
        src = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        with self._lock:
            return self._n

    def c(self):
        with self._lock:
            self._n = 0

    def racy_read(self):
        return self._n

    def racy_write(self):
        self._n = 7
'''
        vs = locks.analyze_locks_source(src, "x.py")
        assert _rules(vs) == ["lock-unguarded-field"] * 2
        assert _details(vs, "lock-unguarded-field") == \
            ["_n:read", "_n:write"]

    def test_clean_class_not_flagged(self):
        src = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self.limit = 5  # immutable config: reads anywhere are fine

    def a(self):
        with self._lock:
            self._n += 1

    def b(self):
        with self._lock:
            return self._n + self.limit

    def c(self):
        return self.limit
'''
        assert locks.analyze_locks_source(src, "x.py") == []

    def test_never_written_fields_exempt(self):
        # read mostly under the lock but never mutated post-init:
        # immutable state needs no guard
        src = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cfg = {}

    def a(self):
        with self._lock:
            return self._cfg

    def b(self):
        with self._lock:
            return self._cfg

    def c(self):
        return self._cfg
'''
        assert locks.analyze_locks_source(src, "x.py") == []

    @pytest.mark.parametrize("call,detail", [
        ("time.sleep(0.1)", "time.sleep"),
        ("fut.result()", ".result"),
        ("t.join()", ".join"),
        ("t.join(2.0)", ".join"),
        ("sock.sendall(b'x')", ".sendall"),
        ("sock.send(b'x')", ".send"),
        ("sock.recv(1)", ".recv"),
        ("self._event.wait(1.0)", ".wait"),
        ("subprocess.run(['ls'])", "subprocess.run"),
    ])
    def test_blocking_call_under_lock_flagged(self, call, detail):
        src = f'''
import threading, time, subprocess

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()

    def work(self, fut, t, sock):
        with self._lock:
            {call}
'''
        vs = locks.analyze_locks_source(src, "x.py")
        assert _rules(vs) == ["lock-blocking-call"]
        assert vs[0].detail == detail
        assert vs[0].symbol == "Box.work"

    def test_blocking_outside_lock_not_flagged(self):
        src = '''
import threading, time

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def work(self, fut):
        with self._lock:
            n = 1
        time.sleep(0.1)
        fut.result()
'''
        assert locks.analyze_locks_source(src, "x.py") == []

    def test_own_condition_wait_ok_foreign_lock_held_flagged(self):
        # with cv: cv.wait()  -> releases the only held lock: fine.
        # with other: with cv: cv.wait() -> blocks with `other` pinned
        # (the PR 14 journal-snapshot shape): flagged.
        src = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._other = threading.Lock()

    def good(self):
        with self._cv:
            self._cv.wait(0.1)

    def good_via_lock(self):
        with self._lock:
            self._cv.wait(0.1)

    def bad(self):
        with self._other:
            with self._cv:
                self._cv.wait(0.1)
'''
        vs = locks.analyze_locks_source(src, "x.py")
        assert _rules(vs) == ["lock-blocking-call"]
        assert vs[0].symbol == "Box.bad"
        assert vs[0].detail == ".wait-holding-other-lock"

    def test_str_join_not_flagged(self):
        # literal-string receivers prove str.join even with Call/BinOp
        # args (the ", ".join(map(...)) false positive); t.join() still
        # flags (covered by the parametrized blocking cases above)
        src = '''
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()

    def work(self, parts):
        with self._lock:
            a = ", ".join(parts)
            b = "".join(map(str, parts))
            c = " ".join(sorted(parts) + ["x"])
            return a + b + c
'''
        assert locks.analyze_locks_source(src, "x.py") == []

    def test_locked_suffix_convention(self):
        # a *_locked helper's accesses count as under-lock (no
        # unguarded noise)...
        src = '''
import threading, time

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def a(self):
        with self._lock:
            self._bump_locked()

    def b(self):
        with self._lock:
            self._n += 1

    def c(self):
        with self._lock:
            self._n += 1

    def _bump_locked(self):
        self._n += 1

    def _sleepy_locked(self):
        time.sleep(0.1)
'''
        vs = locks.analyze_locks_source(src, "x.py")
        # ...but a blocking call inside one still flags
        assert _rules(vs) == ["lock-blocking-call"]
        assert vs[0].symbol == "Box._sleepy_locked"


class TestEnvRules:
    def test_raw_reads_flagged(self):
        src = '''
import os
a = os.environ.get("BYTEPS_FOO", "")
b = os.getenv("BYTEPS_BAR")
c = os.environ["BYTEPS_BAZ"]
'''
        vs = envknobs.analyze_env_source(src, "byteps_tpu/x.py")
        assert _rules(vs) == ["env-raw-read"] * 3
        assert _details(vs, "env-raw-read") == \
            ["BYTEPS_BAR", "BYTEPS_BAZ", "BYTEPS_FOO"]

    def test_writes_and_non_byteps_and_config_not_flagged(self):
        src = '''
import os
os.environ["BYTEPS_FOO"] = "1"       # write: launcher territory
d = os.environ.get("DMLC_ROLE", "")  # cluster contract, not BYTEPS_*
e = os.environ.get(name)             # dynamic key
'''
        assert envknobs.analyze_env_source(src, "byteps_tpu/x.py") == []
        raw = 'v = os.environ.get("BYTEPS_FOO")'
        assert envknobs.analyze_env_source(
            raw, "byteps_tpu/common/config.py") == []

    def test_undocumented_knob_flagged(self):
        cfg = 'x = _env_int("BYTEPS_NEW_KNOB", 1)\n' \
              'y = _env_str("BYTEPS_OLD_KNOB", "")\n'
        docs = "| `BYTEPS_OLD_KNOB` | ... |\n"
        vs = envknobs.check_env_docs(cfg, docs)
        assert _rules(vs) == ["env-undocumented-knob"]
        assert vs[0].detail == "BYTEPS_NEW_KNOB"
        assert envknobs.check_env_docs(
            cfg, docs + "| `BYTEPS_NEW_KNOB` | ... |\n") == []


class TestPartitionSpecRules:
    ROSTER = {"dp", "tp", "sp"}

    def test_unknown_literal_axis_flagged_under_alias(self):
        src = '''
from jax.sharding import PartitionSpec as P

def make(mesh):
    good = P("dp", None, "tp")
    bad = P("model", None)
    nested = P(("dp", "tpp"))
    kw = P(axis="data")
'''
        vs = partitionspecs.analyze_pspec_source(
            src, "byteps_tpu/x.py", self.ROSTER)
        assert _rules(vs) == ["pspec-unknown-axis"] * 3
        assert sorted(v.detail for v in vs) == ["data", "model", "tpp"]
        assert all(v.symbol == "make" for v in vs)

    def test_clean_and_unaliased_modules_pass(self):
        src = '''
from jax.sharding import PartitionSpec

spec = PartitionSpec("dp", ("tp", "sp"), None)
'''
        assert partitionspecs.analyze_pspec_source(
            src, "byteps_tpu/x.py", self.ROSTER) == []
        # P that is NOT the PartitionSpec import must not be touched
        other = '''
def P(*a):
    return a

x = P("model")
'''
        assert partitionspecs.analyze_pspec_source(
            other, "byteps_tpu/x.py", self.ROSTER) == []

    def test_roster_extraction_from_real_mesh_module(self):
        with open(os.path.join(REPO, "byteps_tpu/parallel/mesh.py")) as f:
            roster = partitionspecs.mesh_axis_roster(f.read())
        assert {"dp", "tp", "dcn"} <= roster
        with pytest.raises(ValueError, match="AXIS_ORDER"):
            partitionspecs.mesh_axis_roster("x = 1\n")


class TestMetricRules:
    def test_type_conflict_across_modules(self):
        sources = [
            ("byteps_tpu/a.py",
             'NAME = "sub.thing"\n'
             'def f(reg):\n'
             '    reg.counter(NAME).inc()\n'),
            ("byteps_tpu/b.py",
             'from .a import NAME\n'
             'def g(reg):\n'
             '    reg.gauge(NAME).set(1)\n'),
        ]
        vs = metricnames.check_metric_names(sources, "`sub.thing`")
        assert _rules(vs) == ["metric-type-conflict"]
        assert vs[0].detail == "sub.thing"

    def test_undocumented_and_documented(self):
        sources = [("byteps_tpu/a.py",
                    'def f(reg):\n'
                    '    reg.counter("sub.known").inc()\n'
                    '    reg.counter("sub.mystery").inc()\n')]
        vs = metricnames.check_metric_names(sources, "has `sub.known`")
        assert _rules(vs) == ["metric-undocumented"]
        assert vs[0].detail == "sub.mystery"

    def test_filename_constants_not_metrics(self):
        # "trace.json" matches the dotted-lowercase shape but is a
        # filename — the declared-constant harvest must skip it
        sources = [("byteps_tpu/a.py",
                    'TRACE_SUFFIX = "trace.json"\n'
                    'SOCK = "ps-main.sock"\n')]
        assert metricnames.check_metric_names(sources, "") == []

    def test_declared_only_finding_names_declaration_site(self):
        # an undocumented declared-but-unused name must point at the
        # file:line that declared it, not a synthetic placeholder
        sources = [("byteps_tpu/pkg/metrics.py",
                    '"""docstring"""\n'
                    'ORPHAN = "sub.orphan"\n')]
        vs = metricnames.check_metric_names(sources, "")
        assert _rules(vs) == ["metric-undocumented"]
        assert vs[0].path == "byteps_tpu/pkg/metrics.py"
        assert vs[0].line == 2

    def test_bump_counts_as_counter_and_module_alias_resolves(self):
        sources = [
            ("byteps_tpu/pkg/metrics.py", 'TOK = "serve2.tokens"\n'),
            ("byteps_tpu/pkg/engine.py",
             'from . import metrics as sm\n'
             'def f(m):\n'
             '    m.bump(sm.TOK)\n'),
            ("byteps_tpu/pkg/other.py",
             'from .metrics import TOK\n'
             'def g(reg):\n'
             '    reg.histogram(TOK)\n'),
        ]
        vs = metricnames.check_metric_names(sources, "`serve2.tokens`")
        assert _rules(vs) == ["metric-type-conflict"]


class TestProtocolRules:
    SPEC = (protocols.ProtocolSpec(
        name="toy",
        const_modules=("proto.py",),
        server_modules=("server.py",),
        client_modules=("client.py",),
        docs=("doc.md",)),)

    def _check(self, files):
        return protocols.check_protocols(
            lambda p: files[p], specs=self.SPEC)

    def test_clean_protocol(self):
        files = {
            "proto.py": "OP_A, OP_B = range(2)\n",
            "server.py": ("from proto import OP_A, OP_B\n"
                          "def handle(op):\n"
                          "    if op == OP_A: pass\n"
                          "    elif op in (OP_B,): pass\n"),
            "client.py": ("from proto import OP_A, OP_B\n"
                          "def go(s):\n"
                          "    s.send(OP_A)\n"
                          "    s.send(OP_B)\n"),
            "doc.md": "ops: OP_A and OP_B\n",
        }
        assert self._check(files) == []

    def test_missing_dispatch_producer_docs(self):
        files = {
            "proto.py": "OP_A, OP_B = range(2)\n",
            "server.py": "def handle(op):\n    if op == OP_A: pass\n",
            "client.py": "def go(s):\n    s.send(OP_A)\n",
            "doc.md": "only OP_A here\n",
        }
        vs = self._check(files)
        assert _rules(vs) == ["proto-missing-dispatch",
                              "proto-missing-producer",
                              "proto-undocumented-op"]
        assert all(v.detail == "OP_B" for v in vs)

    def test_collision_in_framing_group(self):
        files = {
            "proto.py": "OP_A, OP_B = range(2)\nOP_C = 1\n",
            "server.py": ("def handle(op):\n"
                          "    if op in (OP_A, OP_B, OP_C): pass\n"),
            "client.py": "def go(s):\n    s.send(OP_A, OP_B, OP_C)\n",
            "doc.md": "OP_A OP_B OP_C\n",
        }
        vs = self._check(files)
        assert _rules(vs) == ["proto-op-collision"]
        assert vs[0].detail == "OP_C"

    def test_real_ps_op_values(self):
        # the checker must parse the REAL roster correctly (range
        # unpacking), not just synthetic fixtures
        src = open(os.path.join(
            REPO, "byteps_tpu/engine/ps_server.py")).read()
        ops = protocols.collect_ops(src)
        assert ops["OP_INIT"] == 0 and ops["OP_STATS"] == 8
        assert len(ops) == 9


# ======================================================================
# 2. runtime lock-order detector
# ======================================================================


@pytest.fixture
def lockcheck():
    lockrt.install()
    lockrt.reset()
    yield lockrt
    lockrt.uninstall()
    lockrt.reset()


class TestRuntimeDetector:
    def test_deliberate_ab_ba_cycle_caught(self, lockcheck):
        A = threading.Lock()
        B = threading.Lock()
        got_a = threading.Event()
        got_b = threading.Event()

        def t1():
            with A:
                got_a.set()
                got_b.wait(2.0)
                if B.acquire(timeout=0.5):  # A -> B
                    B.release()

        def t2():
            got_a.wait(2.0)
            with B:
                got_b.set()
                if A.acquire(timeout=0.5):  # B -> A: closes the cycle
                    A.release()

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start(); th2.start()
        th1.join(5.0); th2.join(5.0)
        assert not th1.is_alive() and not th2.is_alive()

        vs = lockcheck.violations()
        assert len(vs) == 1
        v = vs[0]
        assert isinstance(v, lockrt.LockOrderViolation)
        # the cycle names both allocation sites, in this test file
        assert len(v.cycle) == 3 and v.cycle[0] == v.cycle[-1]
        assert all("test_analysis.py" in site for site in v.cycle)
        # both acquisition stacks ride the violation
        assert v.this_stack and v.other_stack
        assert v.this_stack != v.other_stack
        assert "lock-order cycle" in str(v)

    def test_consistent_order_clean(self, lockcheck):
        A = threading.Lock()
        B = threading.Lock()

        def worker():
            for _ in range(50):
                with A:
                    with B:
                        pass

        ts = [threading.Thread(target=worker) for _ in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(5.0)
        assert lockcheck.violations() == []
        rep = lockcheck.report()
        assert rep["edges"] >= 1 and rep["cycles"] == 0

    def test_rlock_reentrancy_no_self_edge(self, lockcheck):
        R = threading.RLock()
        with R:
            with R:  # reentrant: must not record an edge or violation
                pass
        assert lockcheck.violations() == []
        assert lockcheck.report()["edges"] == 0

    def test_condition_wait_releases_held_entry(self, lockcheck):
        cv = threading.Condition()
        done = threading.Event()

        def consumer():
            with cv:
                cv.wait(timeout=2.0)
            done.set()

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.1)
        with cv:
            cv.notify_all()
        t.join(5.0)
        assert done.is_set()
        assert lockcheck.violations() == []

    def test_hold_time_histograms_exported(self, lockcheck):
        from byteps_tpu.observability.metrics import MetricsRegistry

        L = threading.Lock()
        with L:
            time.sleep(0.01)
        reg = MetricsRegistry()
        lockcheck.export_metrics(reg)
        hists = reg.snapshot()["histograms"]
        mine = [k for k in hists if k.startswith("lockcheck.hold_s")]
        assert mine, hists.keys()
        assert any(hists[k]["count"] >= 1 for k in mine)

    def test_export_metrics_incremental_no_double_count(self, lockcheck):
        """Regression: export_metrics replayed the FULL sample list on
        every call, so back-to-back chaos legs in one process
        (serve_smoke runs two temperatures, each ending in
        chaos_verdict -> export_metrics) double-counted every earlier
        hold into the process-global registry."""
        from byteps_tpu.observability.metrics import MetricsRegistry

        L = threading.Lock()
        with L:
            pass
        reg = MetricsRegistry()
        lockcheck.export_metrics(reg)
        lockcheck.export_metrics(reg)  # second leg: nothing new

        def total(r):
            # only THIS test's lock site: the instrumented registry's
            # own internal locks record holds too while installed
            hists = r.snapshot()["histograms"]
            return sum(hists[k]["count"] for k in hists
                       if k.startswith("lockcheck.hold_s")
                       and "test_analysis.py" in k)

        assert total(reg) == 1
        with L:
            pass
        lockcheck.export_metrics(reg)
        assert total(reg) == 2

    def test_uninstall_restores_primitives(self):
        orig = threading.Lock
        lockrt.install()
        try:
            assert threading.Lock is not orig
        finally:
            lockrt.uninstall()
        assert threading.Lock is orig
        lockrt.reset()

    def test_install_from_config_honors_knob(self):
        import dataclasses

        from byteps_tpu.common.config import (get_config, set_config)

        saved = get_config()
        try:
            set_config(dataclasses.replace(saved, lockcheck=False))
            assert lockrt.install_from_config() is False
            set_config(dataclasses.replace(saved, lockcheck=True))
            assert lockrt.install_from_config() is True
        finally:
            lockrt.uninstall()
            lockrt.reset()
            set_config(saved)


# ======================================================================
# 3. the tree itself
# ======================================================================


def test_lint_tree_clean():
    """THE gate: zero unbaselined violations, every suppression
    reviewed.  A failure here names the new violation — fix it or
    baseline it with a reason (docs/analysis.md, docs/faq.md)."""
    res = run_all(root=REPO)
    msgs = [v.render() for v in res.new]
    assert res.ok, (
        "new analysis violations (fix, or baseline with a reason in "
        f"{BASELINE_FILE}):\n" + "\n".join(msgs)
        + ("\nreasonless baseline entries: "
           f"{res.reasonless}" if res.reasonless else ""))


def test_lint_cli_exit_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/lint.py")],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "lint OK" in proc.stdout


def test_baseline_entries_carry_reasons():
    from byteps_tpu.analysis.violations import load_baseline

    bl = load_baseline(os.path.join(REPO, BASELINE_FILE))
    assert bl.entries, "baseline missing or empty"
    assert bl.reasonless() == []


def test_update_baseline_rule_filter_preserves_other_rules(tmp_path):
    """Regression: ``--update-baseline --rule X`` rewrote the baseline
    from the rule-filtered finding list, destroying every OTHER rule's
    reviewed suppressions (and their human-written reasons).  A
    partial update must preserve them verbatim."""
    import json

    root = tmp_path
    (root / "byteps_tpu" / "common").mkdir(parents=True)
    (root / "byteps_tpu" / "engine").mkdir()
    (root / "byteps_tpu" / "serving" / "disagg").mkdir(parents=True)
    (root / "docs").mkdir()
    for rel in ("byteps_tpu/common/config.py",
                "byteps_tpu/engine/ps_server.py",
                "byteps_tpu/serving/frontend.py",
                "byteps_tpu/serving/router.py",
                "byteps_tpu/serving/journal.py",
                "byteps_tpu/serving/disagg/ship.py",
                "docs/env.md", "docs/observability.md",
                "docs/wire.md", "docs/serving.md"):
        (root / rel).write_text("")
    (root / "byteps_tpu" / "parallel").mkdir()
    (root / "byteps_tpu" / "parallel" / "mesh.py").write_text(
        'AXIS_ORDER = ("dp", "tp")\n')
    (root / "byteps_tpu" / "bad.py").write_text(
        'import os, threading, time\n'
        'F = os.environ.get("BYTEPS_FAKE", "")\n'
        'class C:\n'
        '    def __init__(self):\n'
        '        self._lock = threading.Lock()\n'
        '    def a(self):\n'
        '        with self._lock:\n'
        '            time.sleep(0.1)\n')

    lint = os.path.join(REPO, "scripts/lint.py")

    def run_cli(*extra):
        return subprocess.run(
            [sys.executable, lint, "--root", str(root), *extra],
            capture_output=True, text=True, timeout=60, cwd=REPO)

    assert run_cli("--update-baseline").returncode == 0
    bl_path = root / ".analysis-baseline.json"
    data = json.load(open(bl_path))
    keys = {e["key"] for e in data["suppressions"]}
    assert any(k.startswith("env-raw-read:") for k in keys)
    assert any(k.startswith("lock-blocking-call:") for k in keys)
    # a human reviews the lock entry
    for e in data["suppressions"]:
        if e["key"].startswith("lock-blocking-call:"):
            e["reason"] = "reviewed: fixture"
    json.dump(data, open(bl_path, "w"))

    assert run_cli("--rule", "env-raw-read",
                   "--update-baseline").returncode == 0
    data2 = json.load(open(bl_path))
    by_key = {e["key"]: e["reason"] for e in data2["suppressions"]}
    assert any(k.startswith("env-raw-read:") for k in by_key)
    lock_entries = {k: r for k, r in by_key.items()
                    if k.startswith("lock-blocking-call:")}
    assert lock_entries, "rule-filtered update destroyed other rules"
    assert list(lock_entries.values()) == ["reviewed: fixture"]


def test_lint_cli_does_not_import_jax():
    """The lint CLI loads the analysis package standalone — a bare
    parent stub, never ``byteps_tpu/__init__`` — so it stays
    jax-free and at ~1 s of pure AST work (the docstring contract
    ``scripts/lint.py`` and the verify recipe both make)."""
    proc2 = subprocess.run(
        [sys.executable, "-c",
         "import sys\n"
         f"sys.path.insert(0, {os.path.join(REPO, 'scripts')!r})\n"
         "import lint\n"
         "rc = lint.main([])\n"
         "assert rc == 0, rc\n"
         "assert 'jax' not in sys.modules, 'lint pulled jax'\n"],
        capture_output=True, text=True, timeout=60, cwd=REPO)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


def test_baseline_mechanics():
    v1 = Violation("r", "p.py", "C.m", "x", "msg")
    v2 = Violation("r", "p.py", "C.n", "y", "msg")
    bl = Baseline({v1.key: "reviewed", "r:gone.py:C.o:z": "stale one"})
    new, supp, stale = apply_baseline([v1, v2], bl)
    assert new == [v2] and supp == [v1]
    assert stale == ["r:gone.py:C.o:z"]
    assert Baseline({"k": ""}).reasonless() == ["k"]


def test_every_config_knob_documented():
    """Supersedes test_observability's regex one-off: AST-accurate and
    part of the full lint."""
    cfg = open(os.path.join(
        REPO, "byteps_tpu/common/config.py")).read()
    knobs = envknobs.config_knobs(cfg)
    assert len(knobs) > 30, "config parse failed?"
    assert "BYTEPS_LOCKCHECK" in knobs  # this PR's knob, lint-green
    env_md = open(os.path.join(REPO, "docs/env.md")).read()
    assert envknobs.check_env_docs(cfg, env_md) == []


# ------------------------------------------------- PR-fix regressions


def test_router_journal_state_reads_stay_locked():
    """Regression for the sweep's serving/router.py hits: stats() and
    apply_journal() read _journal_epoch / the in-flight maps OUTSIDE
    _lock (torn role/epoch pairs, stale acks).  Fixed by widening the
    lock holds; the rule must stay silent on both symbols."""
    src = open(os.path.join(
        REPO, "byteps_tpu/serving/router.py")).read()
    vs = [v for v in locks.analyze_locks_source(
        src, "byteps_tpu/serving/router.py")
        if v.symbol in ("ServeRouter.stats", "ServeRouter.apply_journal")]
    assert vs == [], [v.render() for v in vs]


def test_router_journal_ack_consistent_under_stats_load():
    """Functional side of the same fix: epoch acks must reflect the
    batch just folded even while stats() hammers the same state from
    other threads."""
    from byteps_tpu.observability.metrics import MetricsRegistry
    from byteps_tpu.serving import ServeRouter

    r = ServeRouter(["127.0.0.1:1"], registry=MetricsRegistry(),
                    heartbeat_interval=0.0)
    stop = threading.Event()
    seen = []

    def reader():
        while not stop.is_set():
            st = r.stats()
            seen.append((st["role"], st["journal_epoch"]))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for e in range(2, 40):
            ack = r.apply_journal([{"e": e, "src": 1, "k": "hello"}])
            assert ack["epoch"] >= e  # folded batch visible in the ack
    finally:
        stop.set()
        t.join(5.0)
    assert r.stats()["journal_epoch"] == 39
    # epoch observed by readers never decreases (no torn snapshots)
    epochs = [e for _, e in seen]
    assert epochs == sorted(epochs)


def test_async_ps_and_logging_env_reads_routed():
    """Regression for the env-raw-read fixes: async server discovery
    and the log formatter read BYTEPS_* through the typed config now
    (a set_config() override steers them), not the raw environ."""
    for rel in ("byteps_tpu/engine/async_ps.py",
                "byteps_tpu/common/logging.py"):
        src = open(os.path.join(REPO, rel)).read()
        assert envknobs.analyze_env_source(src, rel) == [], rel

    import dataclasses

    from byteps_tpu.common.config import get_config, set_config
    from byteps_tpu.engine.async_ps import _server_addrs_from_env

    saved = get_config()
    try:
        set_config(dataclasses.replace(
            saved, server_addrs="10.0.0.1:9,10.0.0.2:9"))
        assert _server_addrs_from_env() == ["10.0.0.1:9", "10.0.0.2:9"]
    finally:
        set_config(saved)


def test_profiler_close_flag_atomic_with_straggler_drain():
    """Regression for the sweep's engine/ps_server.py hit: close() now
    flips ``_closed`` under BOTH locks, atomically with the straggler
    swap.  Before the fix it was set under ``_io_lock`` alone, so a
    record() passing its ``_closed`` check (under ``_lock``) could
    buffer events AFTER close()'s final drain — buried forever, no
    drop log.  Pinned functionally (a record() hammer racing close()
    must leave nothing buffered and the file valid strict JSON) and
    statically (the rule stays silent on record/close; only the
    reviewed dual-lock ``_write`` read stays baselined)."""
    import json
    import tempfile

    from byteps_tpu.engine.ps_server import OP_PUSH, ServerProfiler

    src = open(os.path.join(
        REPO, "byteps_tpu/engine/ps_server.py")).read()
    hits = [v for v in locks.analyze_locks_source(
        src, "byteps_tpu/engine/ps_server.py")
        if v.detail.startswith("_closed")
        and v.symbol in ("ServerProfiler.record", "ServerProfiler.close")]
    assert hits == [], [v.render() for v in hits]

    for _ in range(5):  # the race window is narrow: hammer it
        with tempfile.NamedTemporaryFile(suffix=".json",
                                         delete=False) as f:
            path = f.name
        prof = ServerProfiler(path)
        stop = threading.Event()

        def recorder():
            t = 0.0
            while not stop.is_set():
                prof.record(OP_PUSH, "w", "peer", t, t + 1.0)
                t += 2.0

        ths = [threading.Thread(target=recorder) for _ in range(4)]
        for t in ths:
            t.start()
        time.sleep(0.01)
        prof.close()
        stop.set()
        for t in ths:
            t.join(5.0)
        assert prof._events == []  # nothing silently buried
        json.loads(open(path).read())  # file stayed valid strict JSON
        os.unlink(path)


# ---------------------------------------- chaos smoke under lockcheck


def test_chaos_smoke_clean_under_lockcheck():
    """Acceptance: a chaos-smoke leg (pipelined window, partitioned
    tensors, compression + EF, 30% injected faults) passes bit-for-bit
    with the runtime lock-order detector installed AND reports zero
    cycles — a chaos run under ``BYTEPS_LOCKCHECK=1`` doubles as a
    deadlock-freedom proof of the schedule it drove
    (``chaos_verdict`` raises with both acquisition stacks
    otherwise)."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import chaos_smoke

    try:
        stats = chaos_smoke.run(steps=8, seed=5, rate=0.3, dim=32,
                                verbose=False, compression="randomk",
                                window=4, partition_bytes=32,
                                lockcheck=True)
    finally:
        lockrt.uninstall()
        lockrt.reset()
    assert stats["faults"] > 0  # bit-for-bit held under real churn
    assert stats["lockcheck.cycles"] == 0
    # the instrumentation actually saw the engine's locks and recorded
    # real nesting (client window + server handler paths)
    assert stats["lockcheck.locks"] > 0
    assert stats["lockcheck.edges"] >= 1
